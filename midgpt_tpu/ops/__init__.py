from midgpt_tpu.ops.norms import rms_norm, head_layer_norm
from midgpt_tpu.ops.rope import rope_table, apply_rope, rotate_interleaved
from midgpt_tpu.ops.dropout import dropout
from midgpt_tpu.ops.loss import cross_entropy_loss
from midgpt_tpu.ops.attention import multihead_attention
from midgpt_tpu.ops.online_softmax import (
    MASK,
    M_INIT,
    finalize,
    merge_normalized,
    merge_partials,
    online_block,
)

__all__ = [
    "rms_norm",
    "head_layer_norm",
    "rope_table",
    "apply_rope",
    "rotate_interleaved",
    "dropout",
    "cross_entropy_loss",
    "multihead_attention",
    "MASK",
    "M_INIT",
    "finalize",
    "merge_normalized",
    "merge_partials",
    "online_block",
]
