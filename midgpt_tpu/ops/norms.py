"""Normalization ops.

Semantics match the reference for val-loss parity:
  * `rms_norm` — weightless by default (reference layers.py:60-75 with
    use_weight=False everywhere it is instantiated: block norms and final
    norm, reference model.py:94-95,133). Reduction in the input dtype, like
    the reference.
  * `head_layer_norm` — QK-LayerNorm over the head dim: true LayerNorm (mean
    subtraction) with a learned scale, no bias, eps 1e-6 (reference
    model.py:52-53).

Both are elementwise+reduction ops XLA fuses into the surrounding matmuls, so
there is no dedicated Pallas kernel for them.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def rms_norm(x: Array, weight: tp.Optional[Array] = None, eps: float = 1e-6) -> Array:
    """RMS-normalize over the trailing axis. Weightless unless `weight` given."""
    out = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    if weight is not None:
        out = out * weight
    return out


def head_layer_norm(x: Array, weight: Array, eps: float = 1e-6) -> Array:
    """LayerNorm over the trailing (head) axis with scale, no bias."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(jnp.square(centered), axis=-1, keepdims=True)
    return centered * jax.lax.rsqrt(var + eps) * weight
