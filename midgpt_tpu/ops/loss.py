"""Softmax cross-entropy with integer labels, computed in float32.

Matches reference train.py:72-77: logits are cast to float32 before the loss
(bf16 logits would lose too much precision in the logsumexp), and the result
is the mean over all positions. Implemented directly (no optax dependency in
the ops layer) with the standard stable logsumexp formulation — XLA fuses this
with the lm_head matmul's epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def cross_entropy_loss(logits: Array, labels: Array) -> Array:
    """Mean CE over all positions. logits (..., V) any float dtype, labels (...) ints."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - label_logits)


def fused_linear_cross_entropy(
    hidden: Array, lm_head: Array, labels: Array, chunk_tokens: int = 8192
) -> Array:
    """Mean CE of `hidden @ lm_head.T` against integer labels WITHOUT ever
    materializing the full (B*T, V) float32 logits.

    At GPT-2 vocab (50304 padded) the full-batch f32 logits are the single
    biggest training buffer (B=32, T=1024 → 6.6 GB on one chip — more than
    all layer activations combined). Token-chunked `lax.scan` with a
    per-chunk `jax.checkpoint` bounds that to chunk_tokens×V and recomputes
    each chunk's logits in the backward pass (the lm_head matmul is ~8% of
    total step FLOPs at 124M, so the recompute is cheap for a ~6 GB saving).

    Numerics match `cross_entropy_loss(GPT.apply(...))` exactly: the matmul
    runs in the compute dtype (same einsum as the unfused lm_head), is cast
    to f32, and per-token losses are summed in f32 then averaged.
    """
    B, T, D = hidden.shape
    N = B * T
    h = hidden.reshape(N, D)
    l = labels.reshape(N)
    chunk = min(chunk_tokens, N)
    n_chunks, rem = divmod(N, chunk)

    def chunk_fn(hl):
        hc, lc = hl
        logits = jnp.einsum("nd,vd->nv", hc, lm_head).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        label_logits = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - label_logits)

    # lax.map (not a carried scan): carry-free stays valid under shard_map's
    # varying-axes tracking, and the per-chunk jax.checkpoint still recomputes
    # chunk logits in the backward pass.
    bulk = n_chunks * chunk
    per_chunk = jax.lax.map(
        jax.checkpoint(chunk_fn),
        (h[:bulk].reshape(n_chunks, chunk, D), l[:bulk].reshape(n_chunks, chunk)),
    )
    total = jnp.sum(per_chunk)
    if rem:  # non-divisible tail goes through the same (f32) math
        total = total + jax.checkpoint(chunk_fn)((h[bulk:], l[bulk:]))
    return total / N
