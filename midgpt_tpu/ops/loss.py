"""Softmax cross-entropy with integer labels, computed in float32.

Matches reference train.py:72-77: logits are cast to float32 before the loss
(bf16 logits would lose too much precision in the logsumexp), and the result
is the mean over all positions. Implemented directly (no optax dependency in
the ops layer) with the standard stable logsumexp formulation — XLA fuses this
with the lm_head matmul's epilogue.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def cross_entropy_loss(logits: Array, labels: Array) -> Array:
    """Mean CE over all positions. logits (..., V) any float dtype, labels (...) ints."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - label_logits)


def fused_linear_cross_entropy(
    hidden: Array,
    lm_head: Array,
    labels: Array,
    chunk_tokens: int = 8192,
    remat_chunks: tp.Optional[bool] = None,
) -> Array:
    """Mean CE of `hidden @ lm_head.T` against integer labels WITHOUT ever
    materializing the full (B*T, V) float32 logits.

    At GPT-2 vocab (50304 padded) the full-batch f32 logits are the single
    biggest training buffer (B=32, T=1024 → 6.6 GB on one chip — more than
    all layer activations combined). Token-chunked `lax.scan` with a
    per-chunk `jax.checkpoint` bounds that to chunk_tokens×V and recomputes
    each chunk's logits in the backward pass (the lm_head matmul is ~8% of
    total step FLOPs at 124M, so the recompute is cheap for a ~6 GB saving).

    Numerics match `cross_entropy_loss(GPT.apply(...))` exactly: the matmul
    runs in the compute dtype (same einsum as the unfused lm_head), is cast
    to f32, and per-token losses are summed in f32 then averaged.
    """
    B, T, D = hidden.shape
    N = B * T
    h = hidden.reshape(N, D)
    l = labels.reshape(N)
    chunk = min(chunk_tokens, N)
    n_chunks, rem = divmod(N, chunk)

    def chunk_fn(hc, lc):
        logits = jnp.einsum("nd,vd->nv", hc, lm_head)  # compute dtype
        # Hand-rolled streaming logsumexp: the bf16 logits stay the only
        # materialized (chunk, V) buffer. jax.nn.logsumexp would cast the
        # whole array to f32 first — and because that f32 copy then has two
        # consumers (the reduce and the label gather), XLA materializes it:
        # a 1.6 GB write+read per 8192-token chunk at GPT-2 vocab. Keeping
        # the cast inside the reduction's element function fuses it away.
        m = jnp.max(logits, axis=-1)  # (chunk,) — max is a selection: exact
        # elementwise f32 cast + subtract fused into the exp-sum reduction
        # (single consumer), numerically identical to casting logits first
        shifted = logits.astype(jnp.float32) - m.astype(jnp.float32)[:, None]
        sumexp = jnp.sum(jnp.exp(shifted), axis=-1)  # f32 accumulator
        lse = m.astype(jnp.float32) + jnp.log(sumexp)
        label_logits = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - label_logits.astype(jnp.float32))

    # With remat_chunks the logits are recomputed in the backward pass
    # (bounds live memory to one chunk×V buffer — for memory-tight shapes);
    # without it the bf16 chunk logits are stored, which at 124M/B<=32 is
    # cheaper than re-running the lm_head matmul + reductions (~2 HBM passes
    # vs ~1.7 TFLOP per chunk). Default (None) is auto: past the same
    # 8-chunk threshold that flips the python loop to lax.map, remat turns
    # on — at-scale microbatches (llama7b_32k, openwebtext_xl: ~128 chunks)
    # would otherwise keep every chunk's bf16 logits live, the full
    # (B*T, V) buffer the fused loss exists to avoid. An explicit
    # True/False always wins (the A/B knob stays honest).
    if remat_chunks is None:
        remat_chunks = n_chunks > 8
    chunked = jax.checkpoint(chunk_fn) if remat_chunks else chunk_fn
    total = jnp.zeros((), jnp.float32)
    if n_chunks <= 8:
        # Static python loop: no stacked (n_chunks, chunk, D) input copy.
        for i in range(n_chunks):
            total = total + chunked(
                h[i * chunk : (i + 1) * chunk], l[i * chunk : (i + 1) * chunk]
            )
    else:
        # Pod-scale batches (openwebtext_xl microsteps hit 128 chunks): one
        # rolled lax.map body keeps HLO size and compile time bounded; the
        # stacking copy amortizes at that scale.
        bulk = n_chunks * chunk
        per_chunk = jax.lax.map(
            lambda hl: chunked(*hl),
            (h[:bulk].reshape(n_chunks, chunk, D), l[:bulk].reshape(n_chunks, chunk)),
        )
        total = total + jnp.sum(per_chunk)
    if rem:  # non-divisible tail goes through the same math
        total = total + chunked(h[n_chunks * chunk :], l[n_chunks * chunk :])
    return total / N
