"""Softmax cross-entropy with integer labels, computed in float32.

Matches reference train.py:72-77: logits are cast to float32 before the loss
(bf16 logits would lose too much precision in the logsumexp), and the result
is the mean over all positions. Implemented directly (no optax dependency in
the ops layer) with the standard stable logsumexp formulation — XLA fuses this
with the lm_head matmul's epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def cross_entropy_loss(logits: Array, labels: Array) -> Array:
    """Mean CE over all positions. logits (..., V) any float dtype, labels (...) ints."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - label_logits)
