"""Keyed dropout as a pure function (reference uses eqx.nn.Dropout)."""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def dropout(x: Array, rate: float, key: tp.Optional[Array], inference: bool = False) -> Array:
    if inference or rate == 0.0:
        return x
    if key is None:
        raise ValueError("dropout(rate>0, inference=False) requires a PRNG key")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
