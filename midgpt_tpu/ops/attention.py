"""Causal multi-head attention: reference-exact naive path + O(T) blockwise path.

Numerics of the naive path match reference model.py:71-77 exactly: scores are
computed in the compute dtype (bf16 on TPU — this matmul is the MXU hot op),
cast to float32, scaled by 1/sqrt(head_dim), masked with -inf below the
diagonal, softmaxed in float32, then cast back for the PV matmul.

The blockwise path (`impl='blockwise'`) is a pure-jnp online-softmax
(flash-style) formulation with O(T) memory — the long-context fallback for
platforms where the Pallas kernel (midgpt_tpu.kernels.flash_attention,
`impl='flash'`) is unavailable, and the parity oracle for testing it.

All impls take q, k, v of shape (B, H, T, C) and return (B, H, T, C).
"""

from __future__ import annotations

import math
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.ops.dropout import dropout

Array = jax.Array

NEG_INF = float("-inf")


def visible_mask(
    col: Array,
    counts: Array,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """THE visibility rule every attention path shares (broadcasting bool).

    A row with `counts` visible keys keeps column `col` iff col < counts
    and — under a sliding window — col is within the last `sliding_window`
    of them OR inside the `attn_sinks` always-visible prefix
    (StreamingLLM-style sinks). With sliding_window == 0 this is the plain
    causal/length mask, bit-identical to the pre-window repo. Used by the
    training paths here, the paged gather fallbacks
    (kernels/decode_attention.py) and the dense decode/prefill masks
    (models/gpt.py); the Pallas template spells the same expression as
    straight-line selects in-kernel (kernels/attention_template.py)."""
    keep = col < counts
    if sliding_window:
        w = col >= counts - sliding_window
        if attn_sinks:
            w |= col < attn_sinks
        keep &= w
    return keep


def naive_causal_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    dropout_rate: float = 0.0,
    key: tp.Optional[Array] = None,
    inference: bool = True,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Materialized-scores attention, fp32 softmax. (B,H,T,C) -> (B,H,T,C).
    sliding_window/attn_sinks restrict each row to its windowed visible set
    (visible_mask); 0 is the reference causal mask, unchanged."""
    *_, T, C = q.shape
    rows = jnp.arange(T)[:, None]
    cols = jnp.arange(T)[None, :]
    # row t sees count = t + 1 keys; cols < rows + 1 == tril
    mask = visible_mask(cols, rows + 1, sliding_window, attn_sinks)
    scores = jnp.einsum("bhqc,bhkc->bhqk", q, k)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32) / math.sqrt(C), axis=-1)
    probs = probs.astype(q.dtype)
    probs = dropout(probs, dropout_rate, key, inference)
    return jnp.einsum("bhqk,bhkc->bhqc", probs, v)


def blockwise_causal_attention(
    q: Array,
    k: Array,
    v: Array,
    block_size: int = 512,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Online-softmax causal attention with O(T * block) memory.

    Scans over KV blocks for each Q block, keeping running (max, sum, acc)
    statistics in float32. Equivalent to the naive path up to fp summation
    order. Block pairs entirely above the diagonal are masked out (compute is
    not skipped — under `lax.scan` the shape must be static; the Pallas kernel
    is the one that actually skips them).
    """
    B, H, T, C = q.shape
    blk = min(block_size, T)
    T_orig = T
    if T % blk != 0:
        # Pad to a block multiple (arbitrary-length prompts in prefill). The
        # causal mask zeroes padded keys for real queries; padded query rows
        # are sliced off below.
        pad = blk - T % blk
        q, k, v = (jnp.pad(a, ((0, 0), (0, 0), (0, pad), (0, 0))) for a in (q, k, v))
        T = T + pad
    n_blk = T // blk
    scale = 1.0 / math.sqrt(C)

    qb = q.reshape(B, H, n_blk, blk, C)
    kb = k.reshape(B, H, n_blk, blk, C)
    vb = v.reshape(B, H, n_blk, blk, C)

    # Row/col indices within a (blk, blk) tile, used to build per-pair masks.
    row_ids = jnp.arange(blk)[:, None]
    col_ids = jnp.arange(blk)[None, :]

    def q_block_fn(qi: int, q_i: Array) -> Array:
        # q_i: (B, H, blk, C)
        def kv_step(carry, j):
            acc, m, denom = carry  # (B,H,blk,C) f32, (B,H,blk) f32, (B,H,blk) f32
            k_j = kb[:, :, j]
            v_j = vb[:, :, j]
            s = jnp.einsum("bhqc,bhkc->bhqk", q_i, k_j).astype(jnp.float32) * scale
            # causal (optionally windowed) mask on GLOBAL indices: row
            # g_row sees count = g_row + 1 keys (visible_mask above)
            gmask = visible_mask(
                j * blk + col_ids,
                qi * blk + row_ids + 1,
                sliding_window,
                attn_sinks,
            )
            s = jnp.where(gmask & (j <= qi), s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            # guard: fully-masked rows keep m_new == -inf; exp(-inf - -inf) → use where
            alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            denom_new = denom * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkc->bhqc", p.astype(q.dtype), v_j
            ).astype(jnp.float32)
            return (acc_new, m_new, denom_new), None

        # Derive the init from q_i (not fresh constants) so that inside an
        # enclosing shard_map the carry inherits q's varying-manual-axes
        # annotation — a constant init trips scan's carry-type check there
        # (the Ulysses-inside-ZeRO-3 composition hits exactly this).
        # Known trade-off (ADVICE r4): non-finite q makes this init NaN
        # (inf*0), so the max/denom guards no longer protect fully-masked
        # rows in that case — harmless, since non-finite q already poisons
        # the output, and the train step's health gate catches it. If a
        # newer JAX drops the varying-axes restriction, revert to constant
        # inits.
        zeros_c = (q_i * 0).astype(jnp.float32)  # (B, H, blk, C)
        zeros_r = jnp.sum(zeros_c, axis=-1)  # (B, H, blk)
        init = (zeros_c, zeros_r + NEG_INF, zeros_r)
        (acc, _, denom), _ = jax.lax.scan(kv_step, init, jnp.arange(n_blk))
        # max() guards fully-masked (padded) query rows against 0/0 NaN.
        return (acc / jnp.maximum(denom, 1e-30)[..., None]).astype(q.dtype)

    if n_blk <= 8:
        outs = [q_block_fn(qi, qb[:, :, qi]) for qi in range(n_blk)]
        out = jnp.stack(outs, axis=2)
    else:
        # Long sequences (the 32K config's non-TPU path is 32+ Q blocks): one
        # rolled body instead of n_blk unrolled copies of the KV scan in HLO
        # — bounds compile time and program size; identical math (q_block_fn
        # only uses qi in elementwise index comparisons).
        out = jax.lax.map(
            lambda qi: q_block_fn(qi, qb[:, :, qi]), jnp.arange(n_blk)
        ).transpose(1, 2, 0, 3, 4)
    out = out.reshape(B, H, T, C)
    return out[:, :, :T_orig]


def flash_kernel_usable(T: int, block_size: int) -> bool:
    """True when the Pallas kernel can serve this shape on this backend
    (callers needing arbitrary T or non-TPU hosts get the blockwise path)."""
    import importlib

    fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")
    blk = min(block_size, T)
    return T % blk == 0 and (jax.default_backend() == "tpu" or fa.RUN_INTERPRET_OFF_TPU)


def flash_block_sizes(T: int, block_size: int) -> tp.Tuple[int, int]:
    """(block_q, block_k) for the flash kernel — the single place the tile
    policy lives. KV blocks use the largest block the sequence allows;
    Q tiles prefer 512 (keeps the f32 score tile + scratch inside VMEM,
    measured fastest on v5e) but fall back to block_k when 512 does not
    divide T (e.g. T=768)."""
    bk = min(block_size, T)
    bq = min(512, bk)
    if T % bq:
        bq = bk
    return bq, bk


def multihead_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    impl: str = "naive",
    dropout_rate: float = 0.0,
    key: tp.Optional[Array] = None,
    inference: bool = False,
    block_size: int = 512,
    layout: str = "bhtc",
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Dispatch causal attention; output layout matches the input layout.

    layout: 'bhtc' (head-major, what the naive/blockwise math uses) or
    'bthc' (sequence-major — the layout the fused QKV projection produces;
    the flash kernel consumes it natively, so the training hot path never
    transposes heads).
    impl: 'naive' (materialized T×T, reference semantics), 'blockwise'
    (O(T) jnp online softmax), or 'flash' (Pallas TPU kernel).
    Attention-probability dropout (reference model.py:78) is only supported
    on the naive path; the fused kernels take dropout_rate == 0 (all
    openwebtext-scale reference configs train with dropout 0.0).
    """
    if layout not in ("bhtc", "bthc"):
        raise ValueError(f"unknown attention layout {layout!r}")
    if impl not in ("naive", "blockwise", "flash", "ring", "ulysses"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if impl in ("ring", "ulysses"):
        # The mesh-bound sequence-parallel implementations are injected by
        # the training runtime (GPT.hidden attn_fn). Reached without one —
        # sampling or evaluating such a checkpoint on a single host — the
        # unsharded math is identical to blockwise online softmax.
        impl = "blockwise"
    if impl != "naive" and dropout_rate != 0.0 and not inference:
        raise NotImplementedError(f"attention dropout requires impl='naive', got {impl!r}")
    if sliding_window and impl == "flash":
        # the flash kernel carries no window mask (GPTConfig validates this
        # at construction; defensive for direct callers)
        raise NotImplementedError(
            "sliding_window requires impl 'naive' or 'blockwise'"
        )

    T = q.shape[2] if layout == "bhtc" else q.shape[1]
    blk = min(block_size, T)
    if impl == "flash":
        import importlib

        # the real module (the package re-exports a same-named function)
        fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")

        if flash_kernel_usable(T, block_size):
            bq, bk = flash_block_sizes(T, block_size)
            if layout == "bthc":
                return fa.flash_attention_bthc(q, k, v, bq, bk)
            return fa.flash_attention(q, k, v, bq, bk)
        # Arbitrary prompt lengths (KV-cache prefill) and non-TPU backends
        # take the equivalent blockwise path — same online softmax, plain jnp.
        impl = "blockwise"

    if layout == "bthc":  # naive/blockwise math is head-major
        q, k, v = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    if impl == "naive":
        out = naive_causal_attention(
            q, k, v, dropout_rate=dropout_rate, key=key, inference=inference,
            sliding_window=sliding_window, attn_sinks=attn_sinks,
        )
    else:
        out = blockwise_causal_attention(
            q, k, v, block_size=blk,
            sliding_window=sliding_window, attn_sinks=attn_sinks,
        )
    return out.transpose(0, 2, 1, 3) if layout == "bthc" else out
