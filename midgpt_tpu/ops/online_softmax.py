"""Online-softmax combine primitives — the one copy of the numerically
stable merge math shared by every attention path that splits the softmax
reduction:

  * the Pallas paged-attention kernel template
    (kernels/attention_template.py): per-page running-statistics update and
    finalize inside kernel bodies, for plain decode and multi-row verify;
  * the flash-attention forward kernels (kernels/flash_attention.py): the
    same per-KV-block update over (block_q, block_k) score tiles;
  * ring attention (parallel/ring_attention.py): merging NORMALIZED
    per-shard (out, lse) partials as K/V shards rotate past;
  * split-K paged attention: merging per-partition RAW (m, l, acc)
    partials emitted by independent grid slices (kernel path) or scan
    iterations (gather fallback).

Everything here is pure jnp on float32 statistics, so the same functions
trace inside Pallas kernel bodies (applied to values loaded from refs),
shard_map bodies, and plain jit.

Masking uses a large-negative FINITE score (`MASK`), with the running max
seeded at `M_INIT > MASK`: `exp(MASK - m)` underflows to exactly 0, so
fully-masked rows and partitions contribute nothing and no NaN-scrubbing
selects are needed in hot loops. A partition that never saw a valid key
carries exactly `(M_INIT, 0, 0)` and drops out of `merge_partials`;
`finalize` turns an all-zero weight row into a 0 output (not NaN).
Callers that pass true -inf scores get the same guarantees: `exp(-inf - m)`
is exactly 0 and `finalize` guards the 0/0 (tests/test_online_softmax.py).
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array

# Finite stand-ins for -inf (see module docstring). These are the canonical
# definitions; kernels/flash_attention.py re-exports them for its callers.
MASK = -1.0e30
M_INIT = -0.5e30


def online_block(
    m: Array, l: Array, s: Array
) -> tp.Tuple[Array, Array, Array, Array]:
    """Fold one raw f32 score block into running statistics (m, l).

    `s` carries the key axis last; `m`/`l` match `s.shape[:-1]`. Returns
    `(m_new, alpha, p, l_new)`; the caller applies its own PV contraction
    and rescales its accumulator as `acc = acc * alpha[..., None] + pv` —
    the contraction shape is the only thing that differs between callers
    (flash q-tiles, decode heads, verify head×row tiles), so it stays
    outside this helper.
    """
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # underflows to 0 on the first visit (M_INIT)
    p = jnp.exp(s - m_new[..., None])  # masked entries underflow to 0
    l_new = l * alpha + jnp.sum(p, axis=-1)
    return m_new, alpha, p, l_new


def merge_normalized(
    m: Array, l: Array, acc: Array, out_s: Array, lse_s: Array
) -> tp.Tuple[Array, Array, Array]:
    """Merge an already-NORMALIZED partial (out_s, lse_s) into raw (m, l, acc).

    The ring-attention step: a visiting K/V shard's softmax is complete, so
    its output re-enters the running sum with weight `exp(lse_s - m_new)`.
    Pass `lse_s = MASK` for a partial that must contribute nothing (e.g. a
    future shard under causal ordering): its beta underflows to exactly 0.
    """
    m_new = jnp.maximum(m, lse_s)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(lse_s - m_new)
    acc = acc * alpha[..., None] + out_s.astype(jnp.float32) * beta[..., None]
    l = l * alpha + beta
    return m_new, l, acc


def merge_partials(
    m: Array, l: Array, acc: Array, axis: int = 0
) -> tp.Tuple[Array, Array, Array]:
    """Reduce stacked RAW split-K partials along `axis`.

    Each slice along `axis` is an independent online-softmax sweep over a
    disjoint span of keys: m_i its running max, l_i its (unnormalized)
    weight sum, acc_i its weighted-value accumulator. The merged stats are

        m = max_i m_i,   l = sum_i l_i * exp(m_i - m),
        acc = sum_i acc_i * exp(m_i - m),

    after which `finalize` recovers the exact softmax over the union of the
    spans. An all-masked partition carries (M_INIT, 0, 0) and contributes
    exactly 0.
    """
    axis = axis % m.ndim
    m_tot = jnp.max(m, axis=axis)
    w = jnp.exp(m - jnp.expand_dims(m_tot, axis))
    l_tot = jnp.sum(l * w, axis=axis)
    acc_tot = jnp.sum(acc * jnp.expand_dims(w, axis=-1), axis=axis)
    return m_tot, l_tot, acc_tot


def finalize(
    m: Array, l: Array, acc: Array, dtype=None
) -> tp.Tuple[Array, Array]:
    """(out, lse) from final raw statistics.

    Rows with l == 0 — nothing visible: an inactive slot, a fully-masked
    row, every partition masked — emit 0 output and `lse = MASK` rather
    than NaN. Rows with l > 0 divide by l exactly (the `maximum` guard is
    a bitwise no-op there), so callers that can prove l >= 1 (ring
    attention seeds its running sum with a complete local softmax) lose
    nothing by sharing this finalize.
    """
    safe_l = jnp.maximum(l, 1e-30)
    out = acc / safe_l[..., None]
    if dtype is not None:
        out = out.astype(dtype)
    lse = jnp.where(l > 0, m + jnp.log(safe_l), MASK)
    return out, lse
