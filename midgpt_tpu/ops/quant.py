"""Symmetric int8 quantization for the paged KV cache.

TPU decode is HBM-bandwidth-bound (the FlashAttention IO argument,
PAPERS.md), and the paged K/V pool is the dominant recurring HBM stream of
the serving engine: every decode step re-reads every cached key and value.
Storing the pool as int8 with f32 absmax scales halves that traffic vs
bf16 and doubles pages-per-byte, at the cost of one rounding step per
write and one multiply per read (both negligible next to the QK^T/PV
matmuls). Scale granularity is per written K/V vector per head — one f32
per (head, position) quantized over the head_dim axis — which is the
finest granularity the scatter write paths admit (a page fills
incrementally, so a genuinely per-page scale would have to requantize
previously written columns, destroying the zero-in-loop-pool-copy
aliasing property the serving engine is built on; see
models/gpt.py PagedKVCache).

Quantization is symmetric absmax with round-to-nearest:

    scale = max(|x|) / 127        (over the head_dim axis)
    q     = clip(round(x / scale), -127, 127)  as int8
    x~    = q * scale             (dequantization, exact in f32)

An all-zero vector stores scale 0 and q 0, so it dequantizes to exact
zeros (the division guards against 0/0). -128 is never produced, so the
code space is symmetric and |x~ - x| <= scale / 2 elementwise.

The write paths (GPT.decode_step_paged / prefill_paged_chunk /
verify_step_paged) quantize on scatter; the read paths dequantize either
inside the Pallas kernels (kernels/decode_attention.py — int8 pages and
scales are fetched into VMEM and widened there, so HBM only ever moves
int8) or right after the XLA page gather on CPU.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array

# 127, not 128: symmetric code space — q = -128 can never round out of
# clip(-127, 127), so dequantization never overshoots the recorded absmax.
Q8_MAX = 127.0


def quantize_q8(x: Array) -> tp.Tuple[Array, Array]:
    """Quantize over the LAST axis: x (..., C) -> (q int8 (..., C), scale
    f32 (...)). Round-to-nearest (GC008: a bare truncating cast is exactly
    the bug this helper exists to prevent)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / Q8_MAX
    safe = jnp.where(scale > 0.0, scale, 1.0)  # all-zero vector -> q = 0
    q = jnp.clip(jnp.round(xf / safe[..., None]), -Q8_MAX, Q8_MAX).astype(
        jnp.int8
    )
    return q, scale


def dequantize_q8(q: Array, scale: Array) -> Array:
    """Exact inverse map: q (..., C) int8, scale (...) f32 -> f32 (..., C).

    int8 * f32 is exact in f32 (both operands are exactly representable),
    so every reader that dequantizes the same (q, scale) pair — Pallas
    kernel, XLA gather fallback, test oracle — sees bit-identical values.
    """
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
