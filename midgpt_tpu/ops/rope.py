"""Rotary position embeddings, GPT-J interleaved style.

Matches reference layers.py:79-99: pairs are interleaved ([a b c d] rotates to
[-b a -d c]), the sin/cos tables use base 10000 over even channel indices, and
the table is duplicated across each pair so rotation is applied at full head
dim. The table is computed in float32 with jnp (constant-folded by XLA under
jit for static T — the reference computes it in host numpy, reference
layers.py:79-82, which is the same thing after tracing) and cast to the
activation dtype at the point of use.

`positions` is explicit so the KV-cache decode path can rotate a single new
token at its absolute position.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

Array = jax.Array


def rope_table(head_dim: int, length: int, base: float = 10000.0) -> tp.Tuple[Array, Array]:
    """(sin, cos) tables of shape (length, head_dim // 2), float32."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = jnp.arange(length, dtype=jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.sin(angles), jnp.cos(angles)


def rotate_interleaved(x: Array) -> Array:
    """[a b c d] -> [-b a -d c] over the trailing axis."""
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack((-x2, x1), axis=-1).reshape(x.shape)


def _duplicate_pairs(t: Array) -> Array:
    """(..., C/2) -> (..., C) by repeating each element twice (interleaved)."""
    return jnp.stack((t, t), axis=-1).reshape(t.shape[:-1] + (t.shape[-1] * 2,))


def apply_rope(
    x: Array,
    sin: Array,
    cos: Array,
    positions: tp.Optional[Array] = None,
    style: str = "interleaved",
) -> Array:
    """Rotate `x` (..., T, head_dim) by the (sin, cos) tables.

    If `positions` (shape (T,)) is given, rows of the tables are gathered at
    those absolute positions; otherwise the first T rows are used.
    `style` as in `apply_rope_bthc`."""
    if positions is not None:
        sin = jnp.take(sin, positions, axis=0)
        cos = jnp.take(cos, positions, axis=0)
    else:
        sin = sin[: x.shape[-2]]
        cos = cos[: x.shape[-2]]
    if style == "split":
        sin = _tile_halves(sin).astype(x.dtype)
        cos = _tile_halves(cos).astype(x.dtype)
        return x * cos + rotate_half(x) * sin
    sin = _duplicate_pairs(sin).astype(x.dtype)
    cos = _duplicate_pairs(cos).astype(x.dtype)
    return x * cos + rotate_interleaved(x) * sin


def apply_rope_positions(
    x: Array,
    sin: Array,
    cos: Array,
    positions: Array,
    style: str = "interleaved",
) -> Array:
    """Rotate `x` (B, T, H, C) with PER-TOKEN absolute positions (B, T).

    The continuous-batching decode path runs B independent requests at B
    different write positions in one step; `apply_rope_bthc` broadcasts one
    (T,) position vector over the batch, this gathers a (B, T) table slice
    instead. Same elementwise rotation, so for equal positions it is
    bit-identical to `apply_rope_bthc` (pinned by tests/test_rope.py)."""
    sin = jnp.take(sin, positions, axis=0)  # (B, T, C/2)
    cos = jnp.take(cos, positions, axis=0)
    if style == "split":
        sin = _tile_halves(sin).astype(x.dtype)[:, :, None, :]  # (B, T, 1, C)
        cos = _tile_halves(cos).astype(x.dtype)[:, :, None, :]
        return x * cos + rotate_half(x) * sin
    sin = _duplicate_pairs(sin).astype(x.dtype)[:, :, None, :]
    cos = _duplicate_pairs(cos).astype(x.dtype)[:, :, None, :]
    return x * cos + rotate_interleaved(x) * sin


def rotate_half(x: Array) -> Array:
    """[a b | c d] -> [-c -d | a b] over the trailing axis (contiguous
    halves — the TPU-friendly form: two static slices instead of the
    stride-2 gathers of the interleaved rotation)."""
    h1, h2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate((-h2, h1), axis=-1)


def _tile_halves(t: Array) -> Array:
    """(..., C/2) -> (..., C) by concatenating the table with itself."""
    return jnp.concatenate((t, t), axis=-1)


def split_permutation(head_dim: int):
    """Index array p with p[i]=2i, p[i+C/2]=2i+1: gathering a head's C axis
    by p moves interleaved pair (2i, 2i+1) to positions (i, i+C/2), turning
    the reference's interleaved rotation into `rotate_half` with the SAME
    angles (rope_table's frequency order is already the even-channel order).
    Exactness of the conjugation is pinned by tests/test_rope.py."""
    import numpy as np

    p = np.empty((head_dim,), np.int32)
    half = head_dim // 2
    p[:half] = np.arange(half) * 2
    p[half:] = np.arange(half) * 2 + 1
    return p


def apply_rope_bthc(
    x: Array,
    sin: Array,
    cos: Array,
    positions: tp.Optional[Array] = None,
    style: str = "interleaved",
) -> Array:
    """Rotate `x` of shape (B, T, H, C) — sequence at axis 1, heads at axis 2.

    Same math as `apply_rope`, with the tables broadcast over the head axis
    instead of the sequence axis sitting next to head_dim. This is the layout
    the fused QKV projection produces; using it end-to-end (projection → RoPE
    → flash kernel → merge heads) eliminates all head transposes.

    style='interleaved' is the reference rotation (layers.py:79-99).
    style='split' expects the C axis pre-permuted by `split_permutation`
    (models/gpt.py permutes the q/k projection rows in-graph) and applies
    the mathematically-identical rotate-half form — measured 12.3 ms/step
    cheaper on the 124M v5e bench (RESULTS §4a r5): the interleaved form's
    stride-2 pair gathers cost real copy passes in forward AND backward."""
    if positions is not None:
        sin = jnp.take(sin, positions, axis=0)
        cos = jnp.take(cos, positions, axis=0)
    else:
        sin = sin[: x.shape[1]]
        cos = cos[: x.shape[1]]
    if style == "split":
        sin = _tile_halves(sin).astype(x.dtype)[:, None, :]  # (T, 1, C)
        cos = _tile_halves(cos).astype(x.dtype)[:, None, :]
        return x * cos + rotate_half(x) * sin
    sin = _duplicate_pairs(sin).astype(x.dtype)[:, None, :]  # (T, 1, C)
    cos = _duplicate_pairs(cos).astype(x.dtype)[:, None, :]
    return x * cos + rotate_interleaved(x) * sin
