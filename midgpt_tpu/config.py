"""Experiment configuration (mirrors reference train.py:26-44 + launch.py persistence).

Configs are plain frozen dataclasses; named presets live in
`midgpt_tpu/configs/*.py` as modules exposing a module-level `config`, loaded
by name (same UX as reference launch.py:25-27). `to_json`/`from_json` give the
rundir round-trip that sample-time reconstruction depends on (reference
launch.py:55-57, sample.py:49-65).
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import typing as tp

from midgpt_tpu.models.gpt import GPTConfig


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical 5D device mesh. Axis sizes of -1 are inferred at runtime.

    The reference hard-codes Mesh((n_devices // 8, 8), ('replica', 'data'))
    (reference train.py:130) — i.e. batch over both axes, params over the
    8-wide axis. Here the axes are named for their role: batch shards over
    ('data', 'fsdp'), params over 'fsdp', the sequence axis over 'sp'
    (context parallelism — ring or Ulysses attention; 1 unless one of them
    is on), the block projections' feature axes over 'tp' (Megatron tensor
    parallelism, parallel/tp.py), and the LAYER axis over 'pp' (GPipe
    pipeline stages, parallel/pipeline.py) — both 1 unless enabled.
    """

    data: int = -1  # -1: infer as n_devices // (fsdp * sp * tp * pp * ep)
    fsdp: int = 8
    sp: int = 1
    tp: int = 1  # tensor parallelism (Megatron column/row, parallel/tp.py)
    pp: int = 1  # pipeline parallelism (GPipe over stages, parallel/pipeline.py)
    ep: int = 1  # expert parallelism (MoE expert axis, models/gpt.py MoEParams)


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    rundir: str
    data_dir: str
    learning_rate: float
    batch_size: int  # GLOBAL batch size across all devices
    warmup_steps: int
    min_lr: float
    lr_decay_steps: int
    max_steps: int
    beta2: float
    weight_decay: float
    eval_interval: int
    param_dtype: str  # 'float32'
    compute_dtype: str  # 'bfloat16'
    g_accum_iters: int
    shard_model: bool
    model_config: GPTConfig
    mesh: MeshConfig = MeshConfig()
    eval_steps: int = 200  # batches per eval (reference train.py:110)
    # Max eval batches materialized on host / staged to device at once
    # (training/train.py evaluate): bounds host memory to
    # eval_host_chunk x local_batch x T int32 per split pass.
    eval_host_chunk: int = 25
    log_interval: int = 20
    seed: int = 0
    data_seed: int = 1337  # seeded, resumable data sampler (reference has none)
    fsdp_min_size: int = 2**18  # shard only params bigger than this (reference model.py:171)
    # Token-chunk size of the fused lm_head+CE loss (ops/loss.py): bounds the
    # f32 logits buffer to chunk×V instead of B·T×V.
    loss_chunk_tokens: int = 8192
    # Recompute chunk logits in backward (caps live memory at one chunk x V
    # buffer). None = auto (on past 8 chunks per microbatch — ops/loss.py);
    # False forces storing the bf16 chunk logits (faster at single-chip
    # scales), True forces recompute for memory-tight shapes.
    loss_remat_chunks: tp.Optional[bool] = None
    # FSDP collective authoring: 'gspmd' = sharding constraints, compiler
    # chooses collectives (reference parity); 'shard_map' = explicit per-layer
    # all-gather / grad reduce-scatter (parallel/shard_map_fsdp.py).
    fsdp_mode: str = "gspmd"
    # MoE router load-balance auxiliary loss (Switch Transformer eq. 4-6):
    # training loss becomes CE + moe_aux_coef * aux, with aux the
    # layer-mean of E * sum_e P_e * f_e (models/gpt.py _moe_gates). 0.0
    # (default) keeps the loss byte-identical to the pre-knob path — the
    # aux computation is never requested, so XLA never sees it (zero-impact
    # pin in tests/test_moe.py). Switch uses 1e-2.
    moe_aux_coef: float = 0.0
    # With mesh.tp > 1: also shard wte/lm_head's vocab axis over 'tp'
    # (Megatron vocab-parallel embedding + CE, parallel/tp.py). No effect at
    # tp=1.
    tp_vocab: bool = True
    # With mesh.pp > 1: number of GPipe microbatches per step (0 = one per
    # pipeline stage). More microbatches shrink the pipeline bubble
    # (pp-1 of M+pp-1 ticks) at the cost of smaller per-tick matmuls.
    pipeline_microbatches: int = 0
    # 'gpipe' (reverse-AD backward, stash grows with microbatches) or
    # '1f1b' (interleaved fwd/bwd, 2*pp-slot stash independent of
    # microbatch count — parallel/pipeline.py make_pipeline_loss_and_grad).
    pipeline_schedule: str = "gpipe"
    # ---- robustness (midgpt_tpu/robustness, docs/ROBUSTNESS.md) ----
    # Constant added to the loop iteration before it indexes the positional
    # data sampler / dropout-key stream. The supervisor advances it on a
    # divergence rollback so the resumed run samples PAST the poisoned data
    # window; 0 (default) is the plain trajectory.
    data_step_offset: int = 0
    # Divergence-restart budget of supervisor.supervise (0 disables
    # rollback: the first divergence raises straight through, the pre-PR
    # behavior).
    max_restarts: int = 2
    # Base of the supervisor's exponential restart backoff (sleep
    # restart_backoff_sec * 2**attempt between rollbacks).
    restart_backoff_sec: float = 1.0
    # Verified checkpoints kept on disk. 2 (not 1): the previous checkpoint
    # must outlive the next save's verification, or a crash mid-save can
    # destroy the only good state.
    ckpt_max_to_keep: int = 2
    # Retry budget / backoff base for the synchronous part of a checkpoint
    # save (transient TensorStore/filesystem failures).
    ckpt_write_retries: int = 3
    ckpt_retry_backoff_sec: float = 0.5
    # Poll the preemption flag every N steps. 1 is free single-process; on
    # multihost every check is a tiny cross-host all-gather (robustness/
    # preempt.py), so large fleets may want a coarser cadence.
    preempt_check_interval: int = 1
    # Fault-injection plan ("kind[@step][*times],..." — robustness/faults.py),
    # activated once per supervised run; "" (default) injects nothing.
    fault_plan: str = ""
    # Hung-step watchdog (robustness/watchdog.py): deadline in seconds armed
    # around each of the train loop's device syncs (the t_land force points).
    # 0.0 (default) disables the guard entirely — the sync is a plain call,
    # no thread, no clock read. Production tunnel runs want ~300s (a few
    # compiles' worth of slack above the longest healthy step).
    watchdog_deadline_s: float = 0.0
    # What an expired watchdog does after dumping the flight recorder:
    # 'raise' raises StepHangError (the supervisor restarts from the last
    # verified checkpoint, like a divergence); 'exit' hard-exits with
    # watchdog.EXIT_CODE for a cluster layer that restarts whole processes.
    watchdog_escalate: str = "raise"
    # Topology-change policy when a supervised run resumes onto a mesh with
    # a different device count than the ledger recorded (elastic resume —
    # docs/ROBUSTNESS.md "Elastic resume & watchdog"): 'same' (default)
    # refuses loudly; 'any' re-derives the data/fsdp axes and restores the
    # checkpoint through the new mesh's shardings.
    on_resume_mesh: str = "same"
    # Grace budget for the SIGTERM emergency save, seconds from the signal's
    # arrival. If the step boundary where the save WOULD start is already
    # past the budget, the save is skipped loudly (ledger note + flight-
    # recorder dump) instead of being killed mid-write and leaving an
    # unverified partial. 0.0 (default) = unbounded (always attempt).
    preempt_grace_s: float = 0.0
    # ---- speculative decoding (sampling/spec.py, docs/SERVING.md) ----
    # Self-draft depth for sampling/serving: the first spec_layers blocks of
    # the model (sharing its embeddings/lm_head) propose tokens that the
    # full model verifies in one batched paged forward. 0 (default)
    # disables speculation — plain continuous-batching decode. Training is
    # untouched by these knobs; sample.py --spec_layers overrides.
    spec_layers: int = 0
    # Bounds of the per-slot adaptive draft length k (both powers of two,
    # like the decode-chunk buckets): the serve scheduler doubles/halves a
    # slot's k from its recent acceptance EMA within [spec_k_min,
    # spec_k_max]; spec_adapt=False pins k at spec_k_max.
    spec_k_max: int = 4
    spec_k_min: int = 1
    spec_adapt: bool = True
    # Paged-KV-cache storage dtype for the serving engine (sampling/serve.py
    # ServeEngine cache_dtype; docs/SERVING.md "Quantized KV cache").
    # 'bf16' (default) stores pages in bf16; 'int8' stores them int8 with
    # f32 absmax scales in a small side buffer — decode-attention HBM
    # traffic halves and a byte-budgeted pool admits 2x the pages. Training
    # is untouched; sample.py --kv_dtype overrides.
    kv_cache_dtype: str = "bf16"
    debug: bool = False

    def __post_init__(self):
        # Fail at construction, not at trace time deep inside the first step:
        # attention-probability dropout exists only on the naive path
        # (ops/attention.py dispatch).
        mc = self.model_config
        if not (0.0 < self.beta2 < 1.0):
            # beta2 >= 1 makes adam's bias correction divide by zero on step
            # 1 — a NaN source INSIDE the optimizer that the train step's
            # grad-norm health check cannot see (its soundness induction
            # assumes the chain maps finite state+grads to finite updates).
            raise ValueError(f"beta2={self.beta2} must be in (0, 1)")
        if mc.qkv_proj not in ("fused", "split3"):
            # A typo here would silently fall back to the fused lowering AND
            # bypass the tp auto-switch (training/train.py) — fail loudly.
            raise ValueError(f"unknown qkv_proj {mc.qkv_proj!r} ('fused' or 'split3')")
        if mc.rope_style not in ("interleaved", "split"):
            # A typo would silently run the interleaved rotation on weights
            # the caller expected permuted (or vice versa) — wrong math that
            # trains; fail at construction like qkv_proj.
            raise ValueError(
                f"unknown rope_style {mc.rope_style!r} ('interleaved' or 'split')"
            )
        if mc.rope_style == "split" and mc.head_dim % 2 != 0:
            raise ValueError("rope_style='split' needs an even head_dim")
        if mc.attn_layout not in ("seq", "head"):
            raise ValueError(
                f"unknown attn_layout {mc.attn_layout!r} ('seq' or 'head')"
            )
        if self.fsdp_mode not in ("gspmd", "shard_map"):
            # A typo would silently run the GSPMD dispatch (train.py
            # branches on == 'shard_map' else gspmd) — fail at construction
            # like qkv_proj/rope_style.
            raise ValueError(
                f"unknown fsdp_mode {self.fsdp_mode!r} ('gspmd' or 'shard_map')"
            )
        if mc.dropout > 0.0 and mc.attn_impl != "naive":
            raise ValueError(
                f"attn_impl={mc.attn_impl!r} does not support attention "
                f"dropout (dropout={mc.dropout}); use attn_impl='naive' or "
                "set dropout=0.0"
            )
        tp = self.mesh.tp
        if tp == -1:
            tp = 1  # the documented "infer at runtime" sentinel (make_mesh)
        if tp < 1:
            raise ValueError(f"mesh.tp={tp} must be >= 1 (or -1 to infer)")
        if tp > 1:
            # Megatron sharding needs whole heads / whole MLP columns per
            # tp shard, and composes only with the GSPMD schedule for now.
            if mc.n_head % tp != 0:
                raise ValueError(f"n_head={mc.n_head} not divisible by mesh.tp={tp}")
            if mc.kv_heads % tp != 0:
                # GQA: the wkv column shard and the serving pool both split
                # on whole KV heads (parallel/tp.py, parallel/serve_tp.py).
                raise ValueError(
                    f"n_kv_heads={mc.kv_heads} not divisible by mesh.tp={tp} "
                    "— tp shards whole KV heads"
                )
            if (4 * mc.n_embd) % tp != 0:
                raise ValueError(f"4*n_embd={4 * mc.n_embd} not divisible by mesh.tp={tp}")
            if self.tp_vocab and mc.vocab_size % tp != 0 and self.mesh.pp in (1, -1):
                # Under pp the pipeline never vocab-shards (its CE runs on
                # gathered heads; pipeline_param_specs keeps wte/lm_head
                # tp-replicated), so tp_vocab is inert there — don't reject
                # a config the pp x tp path runs correctly.
                raise ValueError(
                    f"vocab_size={mc.vocab_size} not divisible by mesh.tp={tp} "
                    "(set tp_vocab=False or pad the vocab)"
                )
            if self.fsdp_mode == "shard_map":
                # r5: the explicit ZeRO-3 body composes with tp (auto-axis
                # GSPMD inside, parallel/shard_map_fsdp.py) — but not yet
                # together with its sequence-parallel schedules.
                if self.mesh.sp not in (1, -1) or mc.attn_impl in ("ring", "ulysses"):
                    raise ValueError(
                        "fsdp_mode='shard_map' with mesh.tp > 1 does not "
                        "compose with sequence parallelism yet (set sp=1 "
                        "and a non-ring/ulysses attn_impl)"
                    )
        pp = self.mesh.pp
        if pp == -1:
            pp = 1
        if pp < 1:
            raise ValueError(f"mesh.pp={pp} must be >= 1 (or -1 to infer)")
        if self.pipeline_microbatches < 0:
            raise ValueError(f"pipeline_microbatches={self.pipeline_microbatches} must be >= 0")
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r} "
                "('gpipe' or '1f1b')"
            )
        if self.pipeline_schedule == "1f1b" and self.mesh.tp not in (1, -1):
            raise ValueError(
                "pipeline_schedule='1f1b' does not compose with mesh.tp > 1 "
                "yet (its backward is hand-written; use 'gpipe')"
            )
        if pp > 1:
            # GPipe composes with 'data', 'fsdp' (v2: stage weights shard,
            # per-layer gathers in the stage scan) and 'tp' (r5: the
            # Megatron axes of the stage weights shard over a GSPMD 'auto'
            # axis inside the pipeline shard_map — parallel/pipeline.py).
            # sp composition is future work.
            if mc.n_layer % pp != 0:
                raise ValueError(f"n_layer={mc.n_layer} not divisible by mesh.pp={pp}")
            if mc.dropout != 0.0:
                raise ValueError("mesh.pp > 1 requires dropout=0.0")
            if self.fsdp_mode != "gspmd":
                raise ValueError("mesh.pp > 1 requires fsdp_mode='gspmd'")
            if self.mesh.sp not in (1, -1):
                raise ValueError(
                    "mesh.pp > 1 does not compose with mesh.sp > 1 yet "
                    "(set sp=1)"
                )
            if mc.attn_impl in ("ring", "ulysses"):
                raise ValueError("mesh.pp > 1 does not compose with sequence parallelism yet")
            mb = self.pipeline_microbatches or pp
            # Necessary but not sufficient: the runtime constraint is on the
            # per-data-shard LOCAL batch, unknowable here (data may be -1);
            # make_pipeline_loss raises a config-pointing ValueError then.
            if self.batch_size % mb != 0:
                raise ValueError(
                    f"batch_size={self.batch_size} not divisible by "
                    f"pipeline_microbatches={mb}"
                )
        if self.moe_aux_coef != 0.0:
            if mc.n_experts == 0:
                raise ValueError(
                    f"moe_aux_coef={self.moe_aux_coef} needs a routed MLP "
                    "(n_experts > 0)"
                )
            if self.fsdp_mode != "gspmd" or self.mesh.pp not in (1, -1):
                # The aux term threads through GPT.hidden(return_moe_aux=True),
                # which only the implicit-GSPMD loss calls; the shard_map and
                # pipeline bodies have their own layer loops. Fail loudly
                # instead of silently training without balance pressure.
                raise ValueError(
                    "moe_aux_coef requires fsdp_mode='gspmd' and mesh.pp == 1 "
                    "(the aux term is only folded into the implicit-GSPMD loss)"
                )
        ep = self.mesh.ep
        if ep == -1:
            ep = 1
        if mc.n_experts < 0:
            raise ValueError(f"n_experts={mc.n_experts} must be >= 0")
        if mc.n_experts > 0:
            if not (1 <= mc.moe_top_k <= mc.n_experts):
                raise ValueError(
                    f"moe_top_k={mc.moe_top_k} must be in [1, n_experts="
                    f"{mc.n_experts}]"
                )
            if pp > 1:
                raise ValueError(
                    "MoE (n_experts > 0) does not compose with mesh.pp > 1 yet"
                )
        if ep > 1:
            if mc.n_experts == 0 or mc.n_experts % ep != 0:
                raise ValueError(
                    f"mesh.ep={ep} needs n_experts ({mc.n_experts}) divisible by it"
                )
            if self.fsdp_mode != "gspmd":
                raise ValueError("mesh.ep > 1 requires fsdp_mode='gspmd'")
        sp = self.mesh.sp
        if sp == -1:
            sp = 1
        if not 0 <= self.spec_layers < mc.n_layer:
            # spec_layers == n_layer would "draft" with the target itself —
            # all cost, no amortization — and deeper is shape-invalid.
            raise ValueError(
                f"spec_layers={self.spec_layers} must be in [0, n_layer="
                f"{mc.n_layer})"
            )
        for k_name, k_val in (("spec_k_max", self.spec_k_max),
                              ("spec_k_min", self.spec_k_min)):
            if k_val < 1 or k_val & (k_val - 1):
                # non-pow2 k would mint a fresh draft+verify program pair
                # per value instead of riding the bucketed compile set
                # (sampling/serve.py _spec_round)
                raise ValueError(f"{k_name}={k_val} must be a power of two")
        if self.spec_k_min > self.spec_k_max:
            raise ValueError(
                f"spec_k_min={self.spec_k_min} > spec_k_max={self.spec_k_max}"
            )
        if self.kv_cache_dtype not in ("bf16", "int8"):
            # A typo would silently serve from a bf16 pool the operator
            # believed was quantized (half the expected page capacity at a
            # byte budget) — fail at construction like the other enums.
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r} "
                "('bf16' or 'int8')"
            )
        if self.data_step_offset < 0:
            # A negative offset would re-sample windows already consumed
            # before the rollback — the exact data the skip exists to avoid.
            raise ValueError(f"data_step_offset={self.data_step_offset} must be >= 0")
        if self.max_restarts < 0:
            raise ValueError(f"max_restarts={self.max_restarts} must be >= 0")
        if self.ckpt_max_to_keep < 1:
            raise ValueError(f"ckpt_max_to_keep={self.ckpt_max_to_keep} must be >= 1")
        if self.ckpt_write_retries < 1:
            raise ValueError(f"ckpt_write_retries={self.ckpt_write_retries} must be >= 1")
        if self.preempt_check_interval < 1:
            raise ValueError(
                f"preempt_check_interval={self.preempt_check_interval} must be >= 1"
            )
        if self.restart_backoff_sec < 0 or self.ckpt_retry_backoff_sec < 0:
            raise ValueError("backoff seconds must be >= 0")
        if self.watchdog_deadline_s < 0:
            # Negative would arm a guard that expires before the first poll
            # — every step "hangs". 0 is the documented off switch.
            raise ValueError(
                f"watchdog_deadline_s={self.watchdog_deadline_s} must be "
                ">= 0 (0 disables the watchdog)"
            )
        if self.watchdog_escalate not in ("raise", "exit"):
            raise ValueError(
                f"unknown watchdog_escalate {self.watchdog_escalate!r} "
                "('raise' or 'exit')"
            )
        if self.on_resume_mesh not in ("same", "any"):
            raise ValueError(
                f"unknown on_resume_mesh {self.on_resume_mesh!r} "
                "('same' or 'any')"
            )
        if self.preempt_grace_s < 0:
            raise ValueError(
                f"preempt_grace_s={self.preempt_grace_s} must be >= 0 "
                "(0 = unbounded)"
            )
        if mc.attn_impl == "ulysses":
            # Ulysses re-shards heads over sp (after any tp head sharding):
            # every (tp, sp) device needs whole heads.
            if sp > 1 and mc.n_head % (tp * sp) != 0:
                raise ValueError(
                    f"attn_impl='ulysses' needs n_head % (tp*sp) == 0, got "
                    f"n_head={mc.n_head}, tp={tp}, sp={sp}"
                )

    def replace(self, **kw) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)


def to_json(config: ExperimentConfig) -> str:
    return json.dumps(dataclasses.asdict(config), indent=2)


_NESTED: tp.Dict[str, type] = {"model_config": GPTConfig, "mesh": MeshConfig}


def from_json(text: str) -> ExperimentConfig:
    raw = json.loads(text)
    for name, cls in _NESTED.items():
        if name in raw and isinstance(raw[name], dict):
            known = {f.name for f in dataclasses.fields(cls)}
            raw[name] = cls(**{k: v for k, v in raw[name].items() if k in known})
    known = {f.name for f in dataclasses.fields(ExperimentConfig)}
    return ExperimentConfig(**{k: v for k, v in raw.items() if k in known})


def load_config(name: str) -> ExperimentConfig:
    """Load a named preset from midgpt_tpu.configs (e.g. 'shakespeare_char')."""
    module = importlib.import_module(f"midgpt_tpu.configs.{name}")
    return module.config
