from midgpt_tpu.utils.pytree import pytree_dataclass
from midgpt_tpu.utils.precision import cast_floating

__all__ = ["pytree_dataclass", "cast_floating"]
