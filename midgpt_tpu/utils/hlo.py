"""Compiled-HLO introspection helpers shared by tests and tools.

Used by the structural pins that keep scheduling claims honest:
tests/test_shard_map_fsdp.py (gather/compute dataflow independence),
tests/test_configs_compile.py (at-scale configs lower), and
tools/check_overlap_tpu.py (TPU async-collective behavior). One parser and
one abstract-lowering scaffold so the pins can't drift apart.
"""

from __future__ import annotations

import re
import typing as tp


_HLO_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")


def hlo_computations(txt: str) -> tp.Dict[str, tp.List[str]]:
    """Parse post-optimization HLO text into {computation: instruction lines}.

    Computation headers look like `%name (args) -> type {` (ENTRY-prefixed
    for main, `%` optional across jax/XLA versions); instructions are the
    lines until the closing `}` (tolerated indented). A header encountered
    while a computation is still open — a malformed dump missing its closing
    brace — starts the new computation rather than silently glomming its
    instructions onto the previous one; braces *inside* instruction lines
    (layout annotations `{1,0}`, nested constant literals `{ {1,2} }`,
    metadata) never open or close a computation. Edge cases pinned by
    tests/test_hlo_utils.py.
    """
    comps: tp.Dict[str, tp.List[str]] = {}
    name = None
    for raw in txt.splitlines():
        line = raw.strip()
        m = _HLO_HEADER_RE.match(line)
        if m and line.endswith("{"):
            name = m.group(1)
            comps[name] = []
        elif line == "}":
            name = None
        elif name is not None:
            comps[name].append(line)
    return comps


def while_body_names(txt: str) -> tp.Set[str]:
    """Names of computations used as a while-loop body (``body=%name``)."""
    return set(re.findall(r"body=%([\w.\-]+)", txt))


# jax renamed the shard_map trace scopes: modern HLO metadata reads
# `jvp()/shard_map/...`, older releases `jvp(jit(shmap_body))/...`. Every
# structural pin matches through these helpers so the spelling difference
# can't silently turn a pin vacuous.


def in_shard_map_scope(line: str) -> bool:
    """Is this HLO instruction annotated as coming from a shard_map body?"""
    return "/shard_map/" in line or "shmap_body)" in line


def is_forward_shmap_line(line: str) -> bool:
    """Forward (jvp, not transpose(jvp)) shard_map provenance."""
    return in_shard_map_scope(line) and "jvp(" in line and "transpose(" not in line


def is_forward_body(lines: tp.Sequence[str]) -> bool:
    """Forward (jvp) vs backward (transpose(jvp)) scan-body classification,
    shared by tests/test_shard_map_fsdp.py and tools/check_overlap_tpu.py so
    the two overlap pins can't drift on what they call 'forward'."""
    return any(is_forward_shmap_line(l) and "while" in l for l in lines)


def lower_abstract_train_step(config, mesh=None):
    """Lower the full training step against ABSTRACT sharded inputs.

    No buffers are materialized, so this works for 7B-class configs on a
    CPU test host and for AOT device topologies (tools/check_overlap_tpu.py
    passes a mesh built from jax.experimental.topologies devices).
    Param/optimizer sharding specs follow the same rule selection as
    training/train.py init_state (pipeline rule under pp>1, else the
    Megatron-tp rule, which reduces to plain FSDP at tp=1).
    """
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.fsdp import named_shardings
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.optim import make_optimizer
    from midgpt_tpu.training.train import make_train_step

    if mesh is None:
        mesh = make_mesh(config.mesh)
    mc = config.model_config
    optimizer, _ = make_optimizer(config)

    if mesh.shape["pp"] > 1:
        from midgpt_tpu.parallel.pipeline import pipeline_param_specs as spec_rule
    else:
        from midgpt_tpu.parallel.tp import tp_param_specs

        spec_rule = functools.partial(tp_param_specs, vocab_parallel=config.tp_vocab)

    abstract_params = jax.eval_shape(
        lambda k: GPT.init(mc, k), jax.random.PRNGKey(0)
    )
    param_specs = spec_rule(
        abstract_params, mesh, config.shard_model, config.fsdp_min_size
    )
    params_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, jnp.float32, sharding=s),
        abstract_params,
        named_shardings(param_specs, mesh),
    )
    opt_abs = jax.eval_shape(optimizer.init, params_abs)
    opt_specs = spec_rule(opt_abs, mesh, config.shard_model, config.fsdp_min_size)
    opt_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        opt_abs,
        named_shardings(opt_specs, mesh),
    )

    step, _, _ = make_train_step(config, optimizer, mesh, param_specs)
    G, B, T = config.g_accum_iters, config.batch_size, mc.block_size
    data_sh = NamedSharding(mesh, batch_spec(shard_seq=mesh.shape["sp"] > 1))
    x_abs = jax.ShapeDtypeStruct((G, B, T), jnp.int32, sharding=data_sh)
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return step.lower(params_abs, opt_abs, x_abs, x_abs, key_abs)
