"""Mixed-precision policy helpers.

The training recipe (matching reference src/train.py:39-40,83: fp32 master
params, per-step cast to bf16 compute, fp32 softmax and loss) is expressed by
casting floating-point pytree leaves; integer leaves pass through untouched.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp


def cast_floating(tree: tp.Any, dtype: tp.Any) -> tp.Any:
    """Cast every floating-point array leaf of `tree` to `dtype`."""
    dtype = jnp.dtype(dtype)

    def cast(x):
        if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)
