"""Version-compat shims for the jax APIs this repo uses.

The repo targets current jax, but the container images it runs in pin older
releases (observed: 0.4.37, where `jax.shard_map` is still
`jax.experimental.shard_map.shard_map`, the CPU device count is an XLA flag
rather than a config option, and the Mosaic params class carries a TPU
prefix). Everything version-dependent resolves here, once, so call sites
stay on the modern spelling.
"""

from __future__ import annotations

import os

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, *, mesh, in_specs, out_specs, **kw):
        """Modern keyword surface on the experimental implementation:
        `check_vma` was `check_rep`, and `axis_names` (the MANUAL axes) is
        the complement of the old `auto` frozenset. check_rep is forced off
        — the old replication checker has no rule for the `name` primitive
        (jax.ad_checkpoint.checkpoint_name, used by the remat policies), and
        it is a diagnostics-only pass."""
        kw.pop("check_vma", None)
        kw["check_rep"] = False
        if "axis_names" in kw:
            auto = frozenset(mesh.axis_names) - frozenset(kw.pop("axis_names"))
            if auto and jax.default_backend() == "cpu":
                # Observed on 0.4.37: lowering a partial-manual body on the
                # CPU backend dies in an XLA CHECK (the AllReducePromotion
                # family — the same pass the full-manual tp=1 path already
                # sidesteps, parallel/pipeline.py auto_tp_shard_map_kwargs).
                # A Python error keeps the test suite running; a CHECK
                # abort would take the whole process with it.
                raise NotImplementedError(
                    "partial-manual shard_map (GSPMD 'auto' axes) aborts in "
                    "XLA CPU on this jax build; tp>1 shard_map compositions "
                    "need a TPU backend or a newer jax here"
                )
            kw["auto"] = auto
        return _shard_map_experimental(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


def axis_size(axis_name) -> int:
    """`jax.lax.axis_size` (added ~0.5); older releases spell it as a psum
    of the literal 1 over the axis (statically evaluated, no collective)."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def set_cpu_device_count(n: int) -> None:
    """Request `n` virtual CPU devices. MUST run before first backend use.

    Modern jax has a config option; older jax only honors the XLA host
    platform flag (the pre-config mechanism — same effect)."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        )


def tpu_compiler_params(**kw):
    """pltpu.CompilerParams across the TPUCompilerParams rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)
