"""Pytree dataclasses: the framework's minimal module system.

Model/optimizer state are plain dataclasses of jax.Arrays registered as
pytrees via `jax.tree_util.register_dataclass`. This keeps the framework
dependency-light and plays perfectly with jit/scan/shard_map: params are just
data, functions are just functions. (The reference reaches the same place via
Equinox modules — reference src/model.py — but a module framework buys nothing
on TPU where everything must be a traced pytree anyway.)
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax

_T = tp.TypeVar("_T")


def pytree_dataclass(cls: tp.Optional[type] = None, *, meta_fields: tp.Sequence[str] = ()):
    """Decorator: dataclass registered as a jax pytree.

    Fields named in ``meta_fields`` are static (hashed into the treedef);
    everything else is a child pytree.
    """

    def wrap(c: type) -> type:
        c = dataclasses.dataclass(c)
        fields = [f.name for f in dataclasses.fields(c)]
        data_fields = tuple(f for f in fields if f not in meta_fields)
        jax.tree_util.register_dataclass(c, data_fields, tuple(meta_fields))
        return c

    return wrap(cls) if cls is not None else wrap


def tree_size(tree: tp.Any) -> int:
    """Total number of array elements in a pytree."""
    return sum(x.size for x in jax.tree.leaves(tree) if hasattr(x, "size"))


def tree_bytes(tree: tp.Any) -> int:
    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "size") and hasattr(x, "dtype")
    )
