from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams

__all__ = ["GPT", "GPTConfig", "GPTParams"]
