"""Decoder-only GPT as a plain pytree + pure functions, TPU-first.

Architecture parity with the reference (for val-loss parity; see SURVEY.md §7):
  * pre-norm residual blocks with *weightless* RMSNorm (eps 1e-6 in blocks,
    1e-5 for the final norm — reference model.py:94-95,133)
  * fused QKV projection, QK-LayerNorm per head (learned scale, no bias,
    eps 1e-6 — reference model.py:52-53,64-65)
  * GPT-J interleaved rotary embeddings (reference layers.py:79-99)
  * bias-free Linears, truncated-normal(±2σ)/sqrt(fan_in) init (reference
    layers.py:49-50); embedding init N(0, 1/sqrt(D)) (reference model.py:134)
  * init-only weight tying: wte and lm_head start from the same array but are
    independent leaves that diverge from step 1 (reference model.py:135-138)
  * GELU MLP with 4x expansion (reference model.py:17-31)
  * fp32 softmax inside attention; logits returned in compute dtype and cast
    to fp32 by the loss (reference model.py:74-77, train.py:76)

TPU-first structure (different from the reference's Equinox modules):
  * Block parameters are stacked along a leading layer axis; the forward pass
    is ONE `jax.lax.scan` over that axis with `jax.checkpoint` per block
    (compile time O(1) in depth, remat bounds activation memory). The
    reference reaches the same shape via eqx.filter_vmap + filter scan
    (model.py:130-132,149-155); here it is the native representation.
  * The forward runs on a full (B, T) batch — batch semantics live in the
    model, not an outer vmap, so sharding constraints and Pallas kernels see
    the batched shapes they tile over.
  * Everything is shape-static and key-explicit: jit-safe by construction.
"""

from __future__ import annotations

import dataclasses
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from midgpt_tpu.ops.attention import multihead_attention
from midgpt_tpu.ops.dropout import dropout
from midgpt_tpu.ops.norms import head_layer_norm, rms_norm
from midgpt_tpu.ops.quant import dequantize_q8, quantize_q8
from midgpt_tpu.ops.rope import apply_rope, apply_rope_bthc, rope_table
from midgpt_tpu.utils.pytree import pytree_dataclass

Array = jax.Array
KeyArray = jax.Array


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model shape (mirrors reference model.py:108-115)."""

    block_size: int  # max sequence length
    vocab_size: int
    n_layer: int
    n_head: int
    n_embd: int
    dropout: float = 0.0
    # TPU knobs (not part of the reference config surface):
    # 'ring' / 'ulysses' = sequence parallelism over the mesh 'sp' axis
    # (parallel/ring_attention.py: K/V shards rotate by ppermute;
    # parallel/ulysses.py: one all-to-all trades the sequence sharding for a
    # head sharding and attention runs dense); the runtime injects the
    # mesh-bound implementation via the attn_fn hook on GPT.hidden.
    attn_impl: str = "naive"  # 'naive' | 'blockwise' | 'flash' | 'ring' | 'ulysses'
    # Tile size for the blockwise/flash/ring/ulysses paths. 1024 measured 7
    # MFU points faster than 512 on the 124M flash training step (v5e,
    # RESULTS §4a) and matches the ring's tuned per-pair tile.
    attn_block_size: int = 1024
    remat: bool = True  # checkpoint each block inside the layer scan
    # What the per-block checkpoint may keep instead of recomputing in bwd:
    #   'none'  — save nothing (full recompute; minimum memory)
    #   'dots'  — save outputs of matmuls with no batch dims (the QKV/out/MLP
    #             projections; attention internals still recompute — they're
    #             cheap under flash and their T×T buffers are what remat is
    #             protecting against)
    #   'flash' — 'dots' plus the flash kernel's residuals (rotated q/k/v,
    #             attention output and log-sum-exp): backward recomputes
    #             nothing of attention — no transposes, no RoPE/QK-norm
    #             replay, no forward-kernel re-run — at the cost of saving
    #             ~4 (B,T,D)-sized buffers per layer
    remat_policy: str = "dots"
    scan_unroll: int = 1  # unroll factor of the layer scan
    # QKV projection lowering of the (3, D, D) weight (see _project_qkv):
    # 'fused' = one (BT,D)x(D,3D) matmul (best MXU shape, default);
    # 'split3' = batched per-third einsum (required under tensor parallelism
    # — auto-selected by the training runtime when mesh tp > 1).
    qkv_proj: str = "fused"
    # RoPE lowering. 'interleaved' computes the reference rotation directly
    # (reference layers.py:79-99). 'split' computes the SAME function via a
    # per-head permutation of the q/k projection rows applied in-graph
    # (checkpoints stay in reference convention) + the contiguous
    # rotate-half form — mathematically identical (QK^T is invariant under
    # a shared permutation of the C axis; pinned by test_rope/test_model),
    # and measured 12.3 ms/step faster on the 124M v5e bench (RESULTS §4a
    # r5: the interleaved form's stride-2 gathers cost copy passes in fwd
    # AND bwd). Per-run choice recorded in config.json, so restores and
    # sampling stay consistent.
    rope_style: str = "interleaved"
    # Internal activation layout of the attention fast paths (flash kernel /
    # injected ring/ulysses — both consume head-major):
    #   'seq'  — project to (B,T,H,C), transpose to the kernel and back
    #            (the r1-r4 structure).
    #   'head' — project DIRECTLY to (B,H,T,C) (einsum btd,xhcd->xbhtc),
    #            QK-norm + RoPE in head-major, kernel without transposes,
    #            and merge+output-projection as ONE contraction
    #            (bhtc,dhc->btd). Same math, same params, same checkpoints —
    #            only the einsum axis order changes; kills the per-layer
    #            head-transpose copies the profiler showed (~12% of the r5
    #            124M step was relayout copies, RESULTS §4a).
    # The naive/blockwise reference paths always use 'seq'.
    attn_layout: str = "seq"
    # Mixture-of-experts MLP (MoEParams): 0 = dense (reference semantics);
    # E > 0 replaces every block's MLP with E experts, top-k routed.
    n_experts: int = 0
    moe_top_k: int = 2
    # Decode-time layer loop lowering (decode_step / decode_step_paged):
    #   False — Python-unrolled DUS chain: the KV cache aliases through the
    #           decode loop carry with ZERO full-cache copies per token
    #           (the r5 restructure, pinned by test_sampling.py), but the
    #           decode program size and trace/compile time grow linearly
    #           with n_layer — fine at 12 layers, noticeably slower to
    #           compile per chunk length at the 32-layer 7B shapes.
    #   True  — rolled lax.scan over layers: O(1) compile in depth, at the
    #           measured cost of 2 full-cache copies per decode step at the
    #           inner/outer carry boundary (RESULTS §1 r5). The deep
    #           llama7b configs set this.
    decode_layer_scan: bool = False
    # Grouped-query attention (GQA/MQA): number of K/V heads. None = MHA
    # (n_kv_heads == n_head, the reference layout — params, checkpoints and
    # compiled programs are byte-identical to the pre-GQA repo). Set to a
    # divisor of n_head to share each K/V head across n_head / n_kv_heads
    # query heads (query head h reads K/V head h // group); 1 = MQA. Every
    # KV buffer in the repo — dense KVCache, paged pools + int8 scale side
    # buffers, trie/spill entries — shrinks to (.., n_kv_heads, ..) geometry,
    # which is THE slots-per-HBM-byte lever (stacks with int8's 2x).
    n_kv_heads: tp.Optional[int] = None
    # Sliding-window attention: each query attends to its last
    # `sliding_window` keys (plus the first `attn_sinks` sink tokens —
    # StreamingLLM-style attention sinks, PAPERS.md). 0 = full causal
    # attention. A row with `count` visible keys attends to columns
    # [count - sliding_window, count) ∪ [0, min(attn_sinks, count)).
    # Training support: attn_impl 'naive' or 'blockwise' (the flash/ring/
    # ulysses kernels have no window mask — validated below). Serving:
    # every paged path masks by the same rule, and the engine reclaims
    # pages that fall fully behind the window (sampling/serve.py).
    sliding_window: int = 0
    attn_sinks: int = 0

    def __post_init__(self):
        if self.n_kv_heads is not None:
            if self.n_kv_heads < 1 or self.n_head % self.n_kv_heads:
                raise ValueError(
                    f"n_kv_heads={self.n_kv_heads} must be a positive divisor "
                    f"of n_head={self.n_head} (each K/V head serves a whole "
                    "group of query heads)"
                )
        if self.sliding_window != 0 and not (
            0 < self.sliding_window < self.block_size
        ):
            raise ValueError(
                f"sliding_window={self.sliding_window} must be 0 (full "
                f"attention) or in [1, block_size={self.block_size})"
            )
        if self.attn_sinks < 0:
            raise ValueError(f"attn_sinks={self.attn_sinks} must be >= 0")
        if self.attn_sinks > 0 and self.sliding_window == 0:
            raise ValueError(
                "attn_sinks > 0 requires sliding_window > 0 (sinks are the "
                "always-visible prefix OF a windowed mask; full attention "
                "already sees them)"
            )
        if self.sliding_window > 0:
            if self.attn_sinks + self.sliding_window > self.block_size:
                raise ValueError(
                    f"attn_sinks + sliding_window = "
                    f"{self.attn_sinks + self.sliding_window} exceeds "
                    f"block_size={self.block_size}"
                )
            if self.attn_impl not in ("naive", "blockwise"):
                raise ValueError(
                    f"sliding_window requires attn_impl 'naive' or "
                    f"'blockwise' (got {self.attn_impl!r}: the flash/ring/"
                    "ulysses training kernels carry no window mask)"
                )

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        """Number of K/V heads (n_head unless GQA/MQA is on)."""
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_head

    @property
    def kv_groups(self) -> int:
        """Query heads per K/V head (1 = MHA)."""
        return self.n_head // self.kv_heads


@pytree_dataclass
class AttentionParams:
    # (3, D, D) fused QKV projection with an explicit leading q/k/v axis.
    # This layout holds two properties at once that flat (3D, D) layouts
    # each break:
    #   * at tp=1 it reshapes (free: contiguous) to the flat stacked (3D, D)
    #     for ONE full-width matmul + contiguous split — the fast MXU path
    #     (a head-major interleaved flat layout costs ~1.7 MFU points at
    #     C=64, measured, RESULTS §4: its (B,T,H,3,C) unpack slices leave
    #     64-element lane runs);
    #   * Megatron TP shards axis 1 (output features, parallel/tp.py): each
    #     of q, k, v is column-sharded independently, so shard boundaries
    #     land between whole heads (D = H*C head-major) and the schedule is
    #     collective-free between the column- and row-parallel matmuls —
    #     sharding a flat stacked 3D axis would straddle the q/k/v
    #     boundaries. (The two lowerings: GPTConfig.qkv_proj.)
    # Shape-distinct from both flat layouts, so a checkpoint from either
    # fails loudly at restore instead of silently permuting rows.
    # The reference's flat stacked split (reference model.py:63-66) is a row
    # permutation of this; init rows are iid so the distribution is
    # identical.
    wqkv: Array
    wo: Array  # (D, D) output projection
    q_scale: Array  # (C,) QK-LayerNorm scale for queries
    k_scale: Array  # (C,) QK-LayerNorm scale for keys
    # GQA/MQA (config.n_kv_heads set): the K/V projection moves to its own
    # (2, n_kv_heads * C, D) leaf — k then v along the leading axis — and
    # wqkv shrinks to the (1, D, D) query projection. Separate leaves keep
    # both Megatron column shards clean at DIFFERENT head counts: wqkv's
    # output axis splits by whole query heads, wkv's by whole K/V heads
    # (parallel/tp.py; requires n_kv_heads % tp == 0). None for MHA — the
    # leaf vanishes from the pytree, so MHA params, checkpoints and
    # compiled programs are byte-identical to the pre-GQA repo, and a GQA
    # checkpoint fails loudly (missing/extra leaf) against an MHA config
    # instead of silently permuting rows.
    wkv: tp.Optional[Array] = None


@pytree_dataclass
class MLPParams:
    w_up: Array  # (4D, D)
    w_down: Array  # (D, 4D)


@pytree_dataclass
class MoEParams:
    """Top-k routed MLP (n_experts > 0) — beyond the reference's capability
    set (its MLP is dense only, reference model.py:17-31). Expert weights
    carry a leading E axis that shards over the mesh 'ep' axis
    (parallel/tp.py): each ep shard computes ITS experts for all tokens and
    the combine contraction psums over E — expert-sharded compute with no
    token dispatch (the right EP schedule for the masked-dense lowering
    below; an all-to-all token-dispatch form is the large-E upgrade path).
    At n_experts=1 the routed MLP is exactly the dense MLP (gate softmax
    over one expert is 1.0) — parity pinned by tests/test_moe.py."""

    router: Array  # (E, D) — token -> expert logits, x @ router.T
    experts_up: Array  # (E, 4D, D)
    experts_down: Array  # (E, D, 4D)


@pytree_dataclass
class BlockParams:
    attn: AttentionParams
    mlp: tp.Union[MLPParams, MoEParams]  # MoEParams iff config.n_experts > 0
    # Block RMSNorms are weightless (reference model.py:94-95): no leaves.


@pytree_dataclass
class GPTParams:
    wte: Array  # (V, D) token embedding
    blocks: BlockParams  # every leaf stacked with leading (n_layer,) axis
    lm_head: Array  # (V, D), applied as x @ lm_head.T; init-tied to wte


@pytree_dataclass
class KVCache:
    """Static-shape decode cache: (L, B, H, S, C) keys/values, filled up to
    `length`. The reference has no KV cache at all — its generate loop runs a
    full padded forward per token (reference sample.py:72-94); this is the
    named upgrade in BASELINE.json."""

    k: Array  # (n_layer, B, n_kv_heads, S, head_dim)
    v: Array  # (n_layer, B, n_kv_heads, S, head_dim)
    length: Array  # () int32: number of valid positions

    @staticmethod
    def init(config: "GPTConfig", batch_size: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (
            config.n_layer,
            batch_size,
            config.kv_heads,
            config.block_size,
            config.head_dim,
        )
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )


@pytree_dataclass
class PagedKVCache:
    """Paged decode cache for the continuous-batching serving engine.

    K/V live in a shared pool of fixed-size pages, (n_layer, n_kv_heads,
    num_pages, page_size, head_dim) per tensor — the head axis is the K/V
    head count, so GQA/MQA configs shrink every page (and its int8 scale
    rows) by the group factor, which is what turns the grouping into pages
    per HBM byte — and a request occupies
    whatever pages the host-side allocator (sampling/serve.py PageAllocator)
    hands it — so device memory holds O(sum of used lengths) instead of
    `n_slots * block_size` (the KVCache sizing above). Page 0 is the SINK:
    never allocated, it is what unallocated page-table entries (zeros) point
    at, so inactive/short slots READ it — always masked — while writes from
    inactive slots and pad positions are dropped via out-of-range page
    indices (XLA oob-scatter semantics; decode_step_paged /
    prefill_paged_chunk).

    The page table ((n_slots, max_pages) int32) and per-slot lengths are NOT
    part of this pytree: they are host-managed scheduler state passed into
    each serve step, so one compiled program serves any request mix — only
    the pool rides the jit carry (donated, updated in place; the
    no-full-cache-copies pin in tests/test_sampling.py covers it).

    page_size must be a multiple of 8 and head_dim a multiple of 128 — or
    span the full dim — for the Mosaic decode kernel's BlockSpec tiling
    (kernels/decode_attention.py); the XLA gather fallback has no such
    constraint.

    **Int8 storage mode** (dtype=jnp.int8): K/V pages are stored int8 with
    f32 absmax scales in small side buffers `k_scale`/`v_scale` of shape
    (n_layer, num_pages, n_kv_heads, page_size) — one scale per written K/V
    vector per head (ops/quant.py: a page fills incrementally through the
    scatter write paths, so scale granularity cannot be coarser than a
    position without requantizing already-written columns). The layout
    puts (n_head, page_size) last so the decode kernel's per-page scale
    block (1, n_head, page_size) spans both trailing dims — Mosaic-tiling
    clean with no in-kernel transpose. Decode-attention HBM traffic halves
    vs bf16 and pages-per-byte doubles; the side buffers add 4/head_dim
    (~3% at C=128) on top. Rollback interacts exactly like the pools:
    freeing a page orphans its scale entries too, and they are rewritten
    before they are next read (the write-before-read invariant,
    docs/SERVING.md). In bf16 mode both scale fields are None."""

    k: Array  # (n_layer, n_kv_heads, num_pages, page_size, head_dim)
    v: Array
    # int8 mode only: f32 absmax scales, (n_layer, num_pages, n_kv_heads,
    # page_size); None in bf16 mode (the leaves simply vanish from the
    # pytree, so bf16 programs are byte-identical to the pre-int8 repo).
    k_scale: tp.Optional[Array] = None
    v_scale: tp.Optional[Array] = None

    @staticmethod
    def init(
        config: "GPTConfig",
        num_pages: int,
        page_size: int = 8,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (
            config.n_layer,
            config.kv_heads,
            num_pages,
            page_size,
            config.head_dim,
        )
        if jnp.dtype(dtype) == jnp.int8:
            sshape = (config.n_layer, num_pages, config.kv_heads, page_size)
            return PagedKVCache(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(sshape, jnp.float32),
                v_scale=jnp.zeros(sshape, jnp.float32),
            )
        return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @staticmethod
    def page_bytes(config: "GPTConfig", page_size: int, dtype) -> int:
        """K+V bytes of ONE page across all layers/heads — the unit the
        byte-budgeted pool sizing divides by (sampling/serve.py
        `pool_hbm_bytes`). Deliberately excludes the int8 scale side
        buffers: the budget governs the page pools (what doubles), and the
        +4/head_dim side buffer is reported separately via
        ServeEngine.cache_hbm_bytes() so drivers see the true spend.
        Uses the K/V head count: a GQA page is group-factor smaller, so a
        fixed byte budget admits group-factor more pages."""
        per_tok = config.n_layer * config.kv_heads * config.head_dim
        return 2 * per_tok * page_size * jnp.dtype(dtype).itemsize

    @property
    def quantized(self) -> bool:
        return self.k.dtype == jnp.int8

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def num_pages(self) -> int:
        return self.k.shape[2]


def _paged_write(
    pool: Array,  # (L, H, P, ps, C) — K or V pages
    scales: tp.Optional[Array],  # (L, P, H, ps) f32, or None (bf16 mode)
    i: Array,  # () int — layer index
    write_pages: Array,  # (...,) int32 — physical page per written position
    offs: Array,  # (...,) int32 — in-page offset per written position
    val: Array,  # (..., H, C) — the K/V vectors to store
) -> tp.Tuple[Array, tp.Optional[Array]]:
    """ONE column scatter into the paged pool, quantizing iff `scales` is
    present — the single write path all three paged forwards share
    (decode_step_paged / prefill_paged_chunk / verify_step_paged), so the
    int8 and bf16 modes cannot drift structurally.

    The pool scatter is the advanced-indexing shape that lowers to an
    in-place aliasing scatter inside donated loop carries (i/write_pages/
    offs are the advanced indices, H and C ride as slices — the
    zero-in-loop-pool-copy pin, tests/test_sampling.py and
    tests/test_quant_cache.py). The scale scatter has the same advanced
    index tuple over its (L, P, H, ps) layout, so it aliases identically;
    out-of-range write_pages (inactive slots, pad positions) drop BOTH
    writes via XLA oob-scatter semantics."""
    if scales is None:
        pool = pool.at[i, :, write_pages, offs, :].set(val.astype(pool.dtype))
        return pool, None
    q, s = quantize_q8(val)  # (..., H, C) int8, (..., H) f32
    pool = pool.at[i, :, write_pages, offs, :].set(q)
    scales = scales.at[i, write_pages, :, offs].set(s)
    return pool, scales


def _layer_pages(
    pool: Array, scales: tp.Optional[Array], i: Array
) -> tp.Tuple[Array, tp.Optional[Array]]:
    """Layer i's pages (H, P, ps, C) and scales (P, H, ps) | None."""
    kp = jax.lax.dynamic_index_in_dim(pool, i, axis=0, keepdims=False)
    sp = (
        None
        if scales is None
        else jax.lax.dynamic_index_in_dim(scales, i, axis=0, keepdims=False)
    )
    return kp, sp


def _gather_layer_kv(
    pool_layer: Array,  # (H, P, ps, C)
    scales_layer: tp.Optional[Array],  # (P, H, ps) f32 | None
    page_rows: Array,  # (MP,) int32 — one slot's logical->physical pages
    out_dtype,
) -> Array:
    """Gather one slot's pages contiguous -> (H, MP*ps, C), dequantizing
    after the gather in int8 mode (the CPU sibling of the kernel's in-VMEM
    dequant). Used by prefill's inline attention; the batched variant
    lives in kernels/decode_attention.py."""
    H, _, ps, C = pool_layer.shape
    S = page_rows.shape[0] * ps
    g = jnp.take(pool_layer, page_rows, axis=1).reshape(H, S, C)
    if scales_layer is None:
        return g
    sg = jnp.take(scales_layer, page_rows, axis=0)  # (MP, H, ps)
    sg = sg.transpose(1, 0, 2).reshape(H, S)
    return dequantize_q8(g, sg).astype(out_dtype)


def _repeat_kv(config: "GPTConfig", a: Array, axis: int) -> Array:
    """Broadcast K/V heads to the query head count for GQA (no-op for MHA).

    Query head h reads K/V head h // kv_groups (consecutive grouping), so
    the repeat along the head axis places each K/V head's copies exactly at
    its group's query-head indices — the same convention the paged kernel
    template realizes as a free (B, H_q, R, C) -> (B, H_kv, G*R, C)
    reshape (kernels/attention_template.py)."""
    g = config.kv_groups
    return a if g == 1 else jnp.repeat(a, g, axis=axis)


def _remat_policy(name: str):
    if name == "none":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "dots_attn":
        # Projections AND the attention output: backward never re-runs the
        # flash forward kernel (attention is >half the block FLOPs at T=1024;
        # its own bwd already recomputes p from the saved lse).
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
    if name == "flash":
        # Everything attention-shaped: rotated q/k/v (head-major, named in
        # block_apply), the kernel's output and log-sum-exp (named in its
        # fwd rule). Backward starts attention AD directly at the saved
        # kernel residuals.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "q_rot", "k_rot", "v_proj", "attn_out", "attn_lse"
            ),
        )
    raise ValueError(
        f"unknown remat_policy {name!r} "
        "(expected 'none', 'dots', 'dots_attn' or 'flash')"
    )


def _linear_init(key: KeyArray, out_features: int, in_features: int) -> Array:
    """Truncated-normal(±2σ) scaled 1/sqrt(fan_in) (reference layers.py:49-50)."""
    w = jax.random.truncated_normal(key, -2.0, 2.0, (out_features, in_features))
    return w / math.sqrt(in_features)


class GPT:
    """Namespace of pure functions over (GPTConfig, GPTParams)."""

    @staticmethod
    def init(config: GPTConfig, key: KeyArray) -> GPTParams:
        block_key, embed_key = jax.random.split(key)
        D, C = config.n_embd, config.head_dim

        def init_block(k: KeyArray) -> BlockParams:
            k_attn, k_proj, k_up, k_down = jax.random.split(k, 4)
            if config.n_kv_heads is None:
                attn = AttentionParams(
                    # iid rows: the (3, D, D) reshape of a (3D, D) init is
                    # the same distribution as the reference's flat fused
                    # projection
                    wqkv=_linear_init(k_attn, 3 * D, D).reshape(3, D, D),
                    wo=_linear_init(k_proj, D, D),
                    q_scale=jnp.ones((C,)),
                    k_scale=jnp.ones((C,)),
                )
            else:
                # GQA: q at full width, k/v at n_kv_heads * C each (iid rows
                # again — one init per projection, split keys).
                KVD = config.kv_heads * C
                k_q, k_kv = jax.random.split(k_attn)
                attn = AttentionParams(
                    wqkv=_linear_init(k_q, D, D).reshape(1, D, D),
                    wo=_linear_init(k_proj, D, D),
                    q_scale=jnp.ones((C,)),
                    k_scale=jnp.ones((C,)),
                    wkv=_linear_init(k_kv, 2 * KVD, D).reshape(2, KVD, D),
                )
            if config.n_experts > 0:
                E = config.n_experts
                k_router, k_up, k_down = jax.random.split(k_up, 3)
                up = jax.vmap(lambda kk: _linear_init(kk, 4 * D, D))(
                    jax.random.split(k_up, E)
                )
                down = jax.vmap(lambda kk: _linear_init(kk, D, 4 * D))(
                    jax.random.split(k_down, E)
                )
                mlp = MoEParams(
                    router=_linear_init(k_router, E, D),
                    experts_up=up,
                    experts_down=down,
                )
            else:
                mlp = MLPParams(
                    w_up=_linear_init(k_up, 4 * D, D),
                    w_down=_linear_init(k_down, D, 4 * D),
                )
            return BlockParams(attn=attn, mlp=mlp)

        blocks = jax.vmap(init_block)(jax.random.split(block_key, config.n_layer))
        embed = jax.random.normal(embed_key, (config.vocab_size, D)) / math.sqrt(D)
        # Init-only tying: same values, independent leaves (reference model.py:135-138).
        return GPTParams(wte=embed, blocks=blocks, lm_head=embed)

    @staticmethod
    def _qkv_weights(
        config: GPTConfig, block: BlockParams
    ) -> tp.Tuple[Array, tp.Optional[Array], Array, Array]:
        """(wqkv, wkv | None, q_scale, k_scale), rope_style-adjusted.

        For rope_style='split', conjugate by the per-head C permutation on
        the WEIGHT side (one (2,D,D)-sized gather per layer, ~µs) instead of
        on the (B,T,H,C) activations (the expensive side): q/k emerge with
        interleaved pair (2i, 2i+1) at (i, i+C/2), so RoPE can use
        contiguous rotate-half. QK-norm and QK^T are permutation-invariant;
        v/att/wo untouched. Stored weights stay in the reference convention
        — checkpoints need no migration. Under GQA the same permutation
        applies to the q rows of wqkv (per query head) and the k rows of
        wkv[0] (per K/V head); wkv[1] (v) is untouched."""
        wqkv, wkv = block.attn.wqkv, block.attn.wkv
        q_scale, k_scale = block.attn.q_scale, block.attn.k_scale
        if config.rope_style == "split":
            from midgpt_tpu.ops.rope import split_permutation

            D, H, C = config.n_embd, config.n_head, config.head_dim
            perm = split_permutation(C)
            if wkv is None:
                wqk = wqkv[:2].reshape(2, H, C, D)[:, :, perm, :].reshape(2, D, D)
                wqkv = jnp.concatenate((wqk, wqkv[2:]), axis=0)
            else:
                HK, KVD = config.kv_heads, config.kv_heads * C
                wqkv = wqkv.reshape(H, C, D)[:, perm, :].reshape(1, D, D)
                wk = wkv[:1].reshape(HK, C, D)[:, perm, :].reshape(1, KVD, D)
                wkv = jnp.concatenate((wk, wkv[1:]), axis=0)
            q_scale, k_scale = q_scale[perm], k_scale[perm]
        return wqkv, wkv, q_scale, k_scale

    @staticmethod
    def _project_qkv_bhtc(
        config: GPTConfig, block: BlockParams, h: Array
    ) -> tp.Tuple[Array, Array, Array]:
        """h (B, T, D) -> q (B, H, T, C), k, v (B, H_kv, T, C), after
        QK-LayerNorm (no RoPE) — the attn_layout='head' projection: the
        head split rides the projection einsum's output axes instead of a
        separate transpose copy. Same contraction, same params. K/V come
        out at the K/V head count; GQA callers broadcast them to the query
        head count (_repeat_kv) only where an equal-heads kernel needs it."""
        H, C = config.n_head, config.head_dim
        wqkv, wkv, q_scale, k_scale = GPT._qkv_weights(config, block)
        if wkv is None:
            w = wqkv.reshape(3, H, C, config.n_embd)
            qkv = jnp.einsum("btd,xhcd->xbhtc", h, w)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            HK = config.kv_heads
            q = jnp.einsum(
                "btd,hcd->bhtc", h, wqkv.reshape(H, C, config.n_embd)
            )
            kv = jnp.einsum(
                "btd,xhcd->xbhtc", h, wkv.reshape(2, HK, C, config.n_embd)
            )
            k, v = kv[0], kv[1]
        q = head_layer_norm(q, q_scale)
        k = head_layer_norm(k, k_scale)
        return q, k, v

    @staticmethod
    def _project_qkv(
        config: GPTConfig, block: BlockParams, h: Array
    ) -> tp.Tuple[Array, Array, Array]:
        """h (B, T, D) -> q (B, T, H, C), k, v (B, T, H_kv, C) after
        QK-LayerNorm (no RoPE).

        Sequence-major (B, T, H, C) is the layout the fused projection
        produces with a plain reshape; the flash kernel consumes it natively,
        so the training hot path never materializes a head transpose.

        Two lowerings of the same (3, D, D) weight (see AttentionParams and
        GPTConfig.qkv_proj):
          'fused'  — reshape the weight flat (free: contiguous) and run ONE
                     (BT, D) x (D, 3D) matmul; best MXU shape, the default.
          'split3' — batched per-third einsum: under tensor parallelism the
                     flat reshape would mix the tp-sharded feature axis into
                     the merged 3D axis (a reshard); the batched form keeps
                     each third independently column-sharded, zero
                     collectives. The runtime selects this when mesh tp > 1
                     (training/train.py).

        GQA (config.n_kv_heads set, AttentionParams.wkv) keeps the same two
        lowerings: 'fused' concatenates the q and k/v weights into ONE
        (D + 2*H_kv*C, D) matmul with a contiguous split; 'split3' runs the
        q einsum and the batched k/v einsum separately so each stays
        independently column-sharded at its own head count. K/V emerge at
        the K/V head count — paged writes store them as-is, equal-heads
        attention kernels get them via _repeat_kv."""
        B, T, D = h.shape
        H, C = config.n_head, config.head_dim
        wqkv, wkv, q_scale, k_scale = GPT._qkv_weights(config, block)
        if wkv is None:
            HK = H
            if config.qkv_proj == "split3":
                qkv = jnp.einsum("btd,xed->btxe", h, wqkv)  # (B, T, 3, D)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            else:
                qkv = jnp.einsum("btd,ed->bte", h, wqkv.reshape(3 * D, D))
                q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            HK = config.kv_heads
            KVD = HK * C
            if config.qkv_proj == "split3":
                q = jnp.einsum("btd,ed->bte", h, wqkv[0])
                kv = jnp.einsum("btd,xed->btxe", h, wkv)  # (B, T, 2, KVD)
                k, v = kv[:, :, 0], kv[:, :, 1]
            else:
                w = jnp.concatenate(
                    [wqkv.reshape(D, D), wkv.reshape(2 * KVD, D)], axis=0
                )
                qkv = jnp.einsum("btd,ed->bte", h, w)
                q, k, v = jnp.split(qkv, [D, D + KVD], axis=-1)
        q = head_layer_norm(q.reshape(B, T, H, C), q_scale)
        k = head_layer_norm(k.reshape(B, T, HK, C), k_scale)
        v = v.reshape(B, T, HK, C)
        return q, k, v

    @staticmethod
    def _attn_out_and_mlp(
        config: GPTConfig,
        block: BlockParams,
        x: Array,  # (B, T, D) residual stream
        att: Array,  # (B, T, H, C), or (B, H, T, C) when head_major
        *,
        k_resid: tp.Optional[KeyArray] = None,
        k_mlp: tp.Optional[KeyArray] = None,
        inference: bool = True,
        head_major: bool = False,
        return_moe_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, Array]]:
        """Shared tail of a block: merge heads, output proj, MLP, residuals.

        With return_moe_aux (routed MLP only), returns (out, aux) where aux
        is the block's scalar load-balance term (_moe_gates)."""
        if head_major:
            # Merge + output projection as ONE contraction: wo's input axis
            # decomposes as (H, C) in the merged order, so this equals
            # reshape-merge + btd,ed->bte without the transpose copy.
            H, C = config.n_head, config.head_dim
            att = jnp.einsum(
                "bhtc,ehc->bte", att, block.attn.wo.reshape(config.n_embd, H, C)
            )
        else:
            B, T, H, C = att.shape
            att = att.reshape(B, T, config.n_embd)
            att = jnp.einsum("btd,ed->bte", att, block.attn.wo)
        att = dropout(att, config.dropout, k_resid, inference)
        x = x + att
        h = rms_norm(x)
        aux = None
        if config.n_experts > 0:
            if return_moe_aux:
                h, aux = GPT._moe_mlp(config, block.mlp, h, return_aux=True)
            else:
                h = GPT._moe_mlp(config, block.mlp, h)
        else:
            h = jax.nn.gelu(jnp.einsum("btd,ed->bte", h, block.mlp.w_up))
            h = jnp.einsum("bte,de->btd", h, block.mlp.w_down)
        h = dropout(h, config.dropout, k_mlp, inference)
        out = x + h
        return (out, aux) if return_moe_aux else out

    @staticmethod
    def _moe_gates(
        config: GPTConfig, mlp: "MoEParams", h: Array
    ) -> tp.Tuple[Array, Array]:
        """Router -> (gates (B, T, E) in h.dtype, load-balance aux () f32).

        Top-k selection goes through `jax.lax.top_k` INDICES, not a
        `logits >= kth` threshold: threshold masking admits MORE than k
        experts on exact logit ties — in the degenerate all-equal-logits
        state (a zero or collapsed router) every expert passes and routing
        silently turns dense (ADVICE r5). The index scatter keeps exactly k
        per token always (ties broken by lowest expert index,
        deterministic); for tie-free logits the masked set is identical, so
        gates are unchanged. Pinned by tests/test_moe.py.

        aux is the Switch-style load-balance term (Switch Transformer
        eq. 4-6, PAPERS.md): E * sum_e P_e * f_e with P_e the mean FULL
        softmax prob of expert e over tokens and f_e the mean top-k
        assignment fraction (divided by k so sum_e f_e = 1). Balanced
        routing gives exactly 1.0; a collapsed router approaches E/k * k
        terms -> > 1. It is differentiable through P_e only (f_e is a hard
        count), which is what makes it push probability mass toward
        under-assigned experts. Dead code (freely eliminated) unless the
        caller requests it — training folds it in behind
        ExperimentConfig.moe_aux_coef."""
        E = config.n_experts
        K = min(config.moe_top_k, E)
        logits = jnp.einsum("btd,ed->bte", h, mlp.router).astype(jnp.float32)
        probs_full = jax.nn.softmax(logits, axis=-1)  # (B, T, E) f32
        if K < E:
            idx = jax.lax.top_k(logits, K)[1]  # (B, T, K)
            assign = jnp.any(
                jax.nn.one_hot(idx, E, dtype=jnp.bool_), axis=-2
            )  # (B, T, E): exactly K True per token
            logits = jnp.where(assign, logits, -jnp.inf)
        else:
            assign = jnp.ones(logits.shape, jnp.bool_)
        gates = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
        mean_prob = jnp.mean(probs_full, axis=(0, 1))  # (E,)
        mean_assign = jnp.mean(assign.astype(jnp.float32), axis=(0, 1)) / K
        aux = E * jnp.sum(mean_prob * mean_assign)
        return gates, aux

    @staticmethod
    def _moe_mlp(
        config: GPTConfig,
        mlp: "MoEParams",
        h: Array,
        return_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, Array]]:
        """Top-k routed expert MLP, masked-dense lowering.

        out = sum_e gate_e(h) * down_e(gelu(up_e(h))) with gates from a
        top-k-masked softmax over router logits (fp32, like attention's
        softmax — selection semantics in _moe_gates). The gate folds into
        `up` (down_e is linear), so the only E-sized activation is the
        (B, T, E, 4D) up buffer — sharded over 'ep' along E when expert
        parallelism is on; the combine einsum's E contraction is the EP
        all-reduce GSPMD inserts. FLOPs are E/top_k x a dense MLP in this
        lowering (fine for the small-E regime; token-dispatch all-to-all is
        the large-E upgrade path). With return_aux, also returns the
        scalar load-balance term."""
        gates, aux = GPT._moe_gates(config, mlp, h)
        up = jax.nn.gelu(jnp.einsum("btd,efd->btef", h, mlp.experts_up))
        up = up * gates[..., None]
        out = jnp.einsum("btef,edf->btd", up, mlp.experts_down)
        return (out, aux) if return_aux else out

    @staticmethod
    def block_apply(
        config: GPTConfig,
        params: BlockParams,
        x: Array,  # (B, T, D)
        *,
        key: tp.Optional[KeyArray] = None,
        inference: bool = False,
        rope: tp.Optional[tp.Tuple[Array, Array]] = None,
        positions: tp.Optional[Array] = None,
        attn_fn: tp.Optional[tp.Callable[[Array, Array, Array], Array]] = None,
        return_moe_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, Array]]:
        C = config.head_dim
        if rope is None:
            rope = rope_table(C, x.shape[1])
        sin, cos = rope
        if key is not None:
            k_attn_drop, k_resid, k_mlp = jax.random.split(key, 3)
        else:
            k_attn_drop = k_resid = k_mlp = None

        with jax.named_scope("attn"):
            att, head_major = GPT._attention(
                config, params, x, sin, cos, positions, attn_fn,
                k_attn_drop, inference,
            )
        with jax.named_scope("mlp"):
            return GPT._attn_out_and_mlp(
                config, params, x, att, k_resid=k_resid, k_mlp=k_mlp,
                inference=inference, head_major=head_major,
                return_moe_aux=return_moe_aux,
            )

    @staticmethod
    def _call_flash(config, T: int, q: Array, k: Array, v: Array) -> Array:
        """Invoke the Pallas kernel on head-major (B,H,T,C) q/k/v, naming
        the post-rope tensors for the 'flash' remat policy: with q/k/v
        saved here and out/lse saved in the kernel's fwd rule, backward
        resumes attention AD from residuals instead of replaying
        transpose+RoPE+QK-norm+kernel. ONE definition for both attn_layout
        modes so their remat/block-size behavior cannot drift."""
        import importlib

        from midgpt_tpu.ops.attention import flash_block_sizes

        fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")
        bq, bk = flash_block_sizes(T, config.attn_block_size)
        q = checkpoint_name(q, "q_rot")
        k = checkpoint_name(k, "k_rot")
        v = checkpoint_name(v, "v_proj")
        return fa.flash_attention(q, k, v, bq, bk)

    @staticmethod
    def _attention(
        config, params, x, sin, cos, positions, attn_fn, k_attn_drop, inference
    ) -> tp.Tuple[Array, bool]:
        """QKV + RoPE + dispatched attention.

        Returns (att, head_major): (B, H, T, C) with head_major=True when
        the attn_layout='head' fast path ran, else (B, T, H, C) with False.
        The flag is static (a function of config + dispatch), so the caller
        branches at trace time."""
        from midgpt_tpu.ops.attention import flash_kernel_usable

        h = rms_norm(x)  # weightless, eps 1e-6
        flash_ok = (
            config.attn_impl == "flash"
            and (config.dropout == 0.0 or inference)  # kernel has no dropout;
            # the dispatcher below raises for flash+dropout (training)
            and flash_kernel_usable(x.shape[1], config.attn_block_size)
        )
        if config.attn_layout == "head" and (attn_fn is not None or flash_ok):
            # Head-major end to end: no transposes between projection,
            # kernel and merge (attn_layout docstring above).
            q, k, v = GPT._project_qkv_bhtc(config, params, h)  # (B,H,T,C)
            q = apply_rope(q, sin, cos, positions, style=config.rope_style)
            k = apply_rope(k, sin, cos, positions, style=config.rope_style)
            # GQA: the flash/injected kernels take equal head counts —
            # broadcast K/V heads to the query heads (post-RoPE, so the
            # rotation runs at the smaller K/V width).
            k = _repeat_kv(config, k, 1)
            v = _repeat_kv(config, v, 1)
            if attn_fn is not None:
                if config.dropout != 0.0 and not inference:
                    raise NotImplementedError(
                        f"injected attention (attn_impl={config.attn_impl!r}) "
                        "does not support attention-probability dropout; use "
                        "attn_impl='naive' or set dropout=0.0"
                    )
                att = checkpoint_name(attn_fn(q, k, v), "attn_out")
            else:
                att = GPT._call_flash(config, x.shape[1], q, k, v)
            return att, True

        q, k, v = GPT._project_qkv(config, params, h)  # (B, T, H, C)
        q = apply_rope_bthc(q, sin, cos, positions, style=config.rope_style)
        k = apply_rope_bthc(k, sin, cos, positions, style=config.rope_style)
        # GQA: broadcast K/V heads to the query head count for the
        # equal-heads training impls (post-RoPE: the rotation and QK-norm
        # already ran at the smaller K/V width).
        k = _repeat_kv(config, k, 2)
        v = _repeat_kv(config, v, 2)

        if attn_fn is not None:
            # Runtime-injected attention (e.g. mesh-bound ring attention for
            # sequence parallelism) — head-major like the kernels.
            if config.dropout != 0.0 and not inference:
                raise NotImplementedError(
                    f"injected attention (attn_impl={config.attn_impl!r}) does "
                    "not support attention-probability dropout; use "
                    "attn_impl='naive' or set dropout=0.0"
                )
            att = attn_fn(
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
            )
            att = checkpoint_name(att, "attn_out").transpose(0, 2, 1, 3)
        elif flash_ok:
            att = GPT._call_flash(
                config,
                x.shape[1],
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
            )
            att = att.transpose(0, 2, 1, 3)
        else:
            att = multihead_attention(
                q,
                k,
                v,
                impl=config.attn_impl,
                dropout_rate=config.dropout,
                key=k_attn_drop,
                inference=inference,
                block_size=config.attn_block_size,
                layout="bthc",
                sliding_window=config.sliding_window,
                attn_sinks=config.attn_sinks,
            )
            att = checkpoint_name(att, "attn_out")
        return att, False

    @staticmethod
    def hidden(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (B, T) int
        *,
        key: tp.Optional[KeyArray] = None,
        inference: bool = False,
        layer_transform: tp.Optional[tp.Callable[[BlockParams], BlockParams]] = None,
        attn_fn: tp.Optional[tp.Callable[[Array, Array, Array], Array]] = None,
        positions: tp.Optional[Array] = None,
        rope_len: tp.Optional[int] = None,
        return_moe_aux: bool = False,
    ) -> tp.Union[Array, tp.Tuple[Array, Array]]:
        """Backbone forward -> final-normed hidden states (B, T, D).

        `return_moe_aux` (routed MLP configs only) additionally returns the
        MoE load-balance term averaged over layers — a () f32 scalar the
        training loss folds in as `moe_aux_coef * aux`
        (ExperimentConfig.moe_aux_coef). Off by default, so the aux
        computation is dead code in every other caller.

        `positions` (shape (T,), absolute) + `rope_len` (static table length
        covering the largest position) let a sequence-parallel caller run the
        backbone on a LOCAL sequence shard: tokens are pointwise in T except
        attention (replaced via attn_fn) and RoPE, which these two arguments
        make shard-aware (shard g passes positions g*Tl + arange(Tl)).

        `attn_fn` (optional) replaces the config-dispatched attention with a
        runtime-bound implementation — the sequence-parallel path passes the
        mesh-bound ring attention here (attention is the only op that mixes
        information across T; everything else is token-pointwise, so GSPMD
        keeps those ops sharded over 'sp' without collectives).

        The lm_head projection is applied by `apply` (full logits, inference)
        or fused into the chunked loss (training — ops/loss.py
        fused_linear_cross_entropy, which avoids the (B*T, V) f32 buffer).

        `layer_transform` is applied to each layer's BlockParams slice inside
        the scan body, before use. The explicit-FSDP path
        (parallel/shard_map_fsdp.py) passes the per-layer all-gather here, so
        under `jax.checkpoint` the gathered weights are rematerialized (ZeRO-3
        re-gather) rather than saved, and AD of the gather transposes to the
        per-layer grad reduce-scatter."""
        B, T = tokens.shape
        C = config.head_dim
        if key is not None:
            drop_key, layers_key = jax.random.split(key)
            layer_keys = jax.random.split(layers_key, config.n_layer)
        else:
            drop_key, layer_keys = None, None

        # jax.named_scope boundaries (embed / block / attn / mlp / final_norm)
        # label the profiler trace like reference model.py:28,55,97,140 —
        # tools/profile_summary.py groups exclusive op times by them.
        with jax.named_scope("embed"):
            x = jnp.take(params.wte, tokens, axis=0)  # (B, T, D)
            x = dropout(x, config.dropout, drop_key, inference)

        # shared fp32 table, constant-folded under jit; rope_len covers the
        # global sequence when T is a local shard of it
        rope = rope_table(C, rope_len or T)

        if return_moe_aux and config.n_experts == 0:
            raise ValueError("return_moe_aux requires a routed MLP (n_experts > 0)")

        def block_fn(x, block_and_key):
            block, k = block_and_key
            if layer_transform is not None:
                block = layer_transform(block)
            with jax.named_scope("block"):
                out = GPT.block_apply(
                    config, block, x, key=k, inference=inference, rope=rope,
                    positions=positions, attn_fn=attn_fn,
                    return_moe_aux=return_moe_aux,
                )
            # ys carry the per-layer aux scalar only when requested, so the
            # default path's scan signature (and its compiled HLO) is
            # unchanged.
            return out if return_moe_aux else (out, None)

        if config.remat:
            block_fn = jax.checkpoint(block_fn, policy=_remat_policy(config.remat_policy))
        x, aux = jax.lax.scan(
            block_fn, x, (params.blocks, layer_keys), unroll=config.scan_unroll
        )

        with jax.named_scope("final_norm"):
            x = rms_norm(x, eps=1e-5)  # final norm (reference model.py:133,156)
        return (x, jnp.mean(aux)) if return_moe_aux else x

    @staticmethod
    def apply(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (B, T) int
        *,
        key: tp.Optional[KeyArray] = None,
        inference: bool = False,
    ) -> Array:
        """Forward pass -> logits (B, T, V) in the params' floating dtype."""
        x = GPT.hidden(config, params, tokens, key=key, inference=inference)
        return jnp.einsum("btd,vd->btv", x, params.lm_head)

    # ------------------------------------------------------------------
    # KV-cached decoding. inference-only (no dropout keys).
    # ------------------------------------------------------------------

    @staticmethod
    def prefill(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (B, T) with T <= block_size
        cache: KVCache,
    ) -> tp.Tuple[Array, KVCache]:
        """Run the prompt through the model, filling cache positions [0, T).

        Returns (logits (B, T, V), cache with length=T)."""
        B, T = tokens.shape
        S, C = config.block_size, config.head_dim
        x = jnp.take(params.wte, tokens, axis=0)
        sin, cos = rope_table(C, S)
        rope = (sin[:T], cos[:T])

        def block_fn(x, block: BlockParams):
            h = rms_norm(x)
            q, k, v = GPT._project_qkv(config, block, h)  # k/v (B, T, HK, C)
            qr = apply_rope_bthc(q, rope[0], rope[1], style=config.rope_style)
            kr = apply_rope_bthc(k, rope[0], rope[1], style=config.rope_style)
            att = multihead_attention(
                qr, _repeat_kv(config, kr, 2), _repeat_kv(config, v, 2),
                impl=config.attn_impl, inference=True,
                block_size=config.attn_block_size, layout="bthc",
                sliding_window=config.sliding_window,
                attn_sinks=config.attn_sinks,
            )
            x = GPT._attn_out_and_mlp(config, block, x, att)
            # cache stores post-norm, post-RoPE keys and raw values,
            # head-major, at the K/V head count
            return x, (kr.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

        x, (k_layers, v_layers) = jax.lax.scan(block_fn, x, params.blocks)
        pad = [(0, 0), (0, 0), (0, 0), (0, S - T), (0, 0)]
        new_cache = KVCache(
            k=jnp.pad(k_layers.astype(cache.k.dtype), pad),
            v=jnp.pad(v_layers.astype(cache.v.dtype), pad),
            length=jnp.asarray(T, jnp.int32),
        )
        x = rms_norm(x, eps=1e-5)
        logits = jnp.einsum("btd,vd->btv", x, params.lm_head)
        return logits, new_cache

    @staticmethod
    def decode_step(
        config: GPTConfig,
        params: GPTParams,
        token: Array,  # (B,) int — the newest token
        cache: KVCache,
    ) -> tp.Tuple[Array, KVCache]:
        """One incremental decode step at position cache.length.

        Precondition: cache.length < config.block_size. The cache is
        static-shape; at a full cache the dynamic_update_slice would clamp to
        the last slot and silently corrupt it, so callers (sampling engine)
        must stop or fall back to windowed forward before that.

        Returns (logits (B, V) for the next token, updated cache)."""
        B = token.shape[0]
        L, S, C = config.n_layer, config.block_size, config.head_dim
        HK = config.kv_heads
        pos = cache.length  # () int32
        x = jnp.take(params.wte, token[:, None], axis=0)  # (B, 1, D)
        sin, cos = rope_table(C, S)
        positions = pos[None]  # (1,)

        # The cache is threaded through an UNROLLED layer loop and updated
        # by a per-token COLUMN write. The r1-r4 structure (cache as scan
        # xs, new cache re-stacked from per-layer ys) forced XLA to copy
        # BOTH full (L, B, H, S, C) buffers every decode step inside the
        # chunked decode loop — measured 2.5 ms/token of pure copy at
        # 124M/B=8 on v5e, a third of the whole step (RESULTS §, r5) —
        # plus per-layer stacked-slot rebuilds. A rolled scan still pays 2
        # full-cache copies/step at the inner/outer carry boundary
        # (verified on compiled HLO); the unrolled DUS chain rides the
        # decode loop's carry and aliases in place. L is static and small,
        # so the unroll is cheap to trace (decode has no remat concerns).
        def block_fn(carry, block_and_idx):
            x, ck_all, cv_all = carry  # caches (L, B, HK, S, C)
            block, i = block_and_idx
            h = rms_norm(x)
            q, k, v = GPT._project_qkv(config, block, h)  # k/v (B, 1, HK, C)
            q = apply_rope_bthc(
                q, sin, cos, positions, style=config.rope_style
            ).transpose(0, 2, 1, 3)
            k = apply_rope_bthc(
                k, sin, cos, positions, style=config.rope_style
            ).transpose(0, 2, 1, 3)
            v = v.transpose(0, 2, 1, 3)  # (B, HK, 1, C); q (B, H, 1, C)
            ck_all = jax.lax.dynamic_update_slice(
                ck_all, k.astype(ck_all.dtype)[None], (i, 0, 0, pos, 0)
            )
            cv_all = jax.lax.dynamic_update_slice(
                cv_all, v.astype(cv_all.dtype)[None], (i, 0, 0, pos, 0)
            )
            ck = jax.lax.dynamic_slice(
                ck_all, (i, 0, 0, 0, 0), (1, B, HK, S, C)
            )[0]
            cv = jax.lax.dynamic_slice(
                cv_all, (i, 0, 0, 0, 0), (1, B, HK, S, C)
            )[0]
            # GQA: the cache holds HK heads — broadcast to the query heads
            # for the score/PV contractions (reads only, the cache itself
            # stays at K/V geometry).
            ck = _repeat_kv(config, ck, 1)
            cv = _repeat_kv(config, cv, 1)
            scores = jnp.einsum("bhqc,bhkc->bhqk", q, ck)  # (B, H, 1, S)
            col = jnp.arange(S)[None, None, None, :]
            valid = col <= pos
            if config.sliding_window:
                # Row `pos` sees count = pos + 1 keys: keep the last
                # `sliding_window` of them plus the `attn_sinks` prefix.
                keep = col > pos - config.sliding_window
                if config.attn_sinks:
                    keep |= col < config.attn_sinks
                valid &= keep
            scores = jnp.where(valid, scores, float("-inf"))
            probs = jax.nn.softmax(
                scores.astype(jnp.float32) / math.sqrt(C), axis=-1
            ).astype(q.dtype)
            att = jnp.einsum("bhqk,bhkc->bhqc", probs, cv)
            x = GPT._attn_out_and_mlp(config, block, x, att.transpose(0, 2, 1, 3))
            return (x, ck_all, cv_all), None

        carry = GPT._decode_layer_loop(config, block_fn, (x, cache.k, cache.v), params.blocks)
        x, k_new, v_new = carry
        x = rms_norm(x, eps=1e-5)
        logits = jnp.einsum("btd,vd->btv", x, params.lm_head)[:, 0]
        new_cache = KVCache(k=k_new, v=v_new, length=pos + 1)
        return logits, new_cache

    @staticmethod
    def _decode_layer_loop(config: GPTConfig, block_fn, carry, blocks):
        """Drive `block_fn(carry, (layer_params, layer_idx))` over all layers.

        Two lowerings, selected by `config.decode_layer_scan` (trade-off
        documented on the config field):

          * Python unroll (default) — the KV cache buffers thread straight
            through the unrolled DUS chain, so inside a chunked decode loop
            they alias the loop carry with ZERO full-cache copies per token
            (the r5 restructure; structural pin in tests/test_sampling.py).
            Cost: the traced decode program is O(n_layer) ops — at 12
            layers that is noise, at the 32-layer 7B shapes each chunk
            length costs noticeably more trace+compile time.
          * Rolled `lax.scan` — O(1) program size in depth (one traced
            block), at the measured cost of 2 full-cache copies per decode
            step at the inner/outer scan carry boundary (RESULTS §1 r5:
            XLA cannot alias a while-loop carry into an enclosing loop's
            carry slot). The deep llama7b configs set this: for them,
            compile latency dominates interactive use and the copies are
            amortized by the much larger per-layer compute.

        Both run the SAME block_fn (layer index arrives as a traced scalar
        either way), so the two lowerings cannot drift numerically — pinned
        by the decode_layer_scan parity test in tests/test_sampling.py."""
        if config.decode_layer_scan:
            idx = jnp.arange(config.n_layer)
            carry, _ = jax.lax.scan(block_fn, carry, (blocks, idx))
            return carry
        for i in range(config.n_layer):
            layer = jax.tree.map(lambda a: a[i], blocks)
            carry, _ = block_fn(carry, (layer, jnp.asarray(i)))
        return carry

    # ------------------------------------------------------------------
    # Paged decoding (continuous-batching serving engine, sampling/serve.py)
    # ------------------------------------------------------------------

    @staticmethod
    def decode_step_paged(
        config: GPTConfig,
        params: GPTParams,
        token: Array,  # (B,) int — each slot's newest token
        cache: "PagedKVCache",
        page_table: Array,  # (B, max_pages) int32 — logical -> physical page
        lengths: Array,  # (B,) int32 — tokens already in slot b's cache
        active: Array,  # (B,) bool — False: slot is empty / mid-prefill
        attn_impl: str = "auto",
        mesh=None,  # Optional[Mesh] — tp serving mesh (parallel/serve_tp.py)
        split_k: int = 1,  # key-sequence partitions per slot (static)
    ) -> tp.Tuple[Array, "PagedKVCache"]:
        """One decode step for B independent requests at B different positions.

        Slot b writes its token's K/V at logical position lengths[b] (page
        page_table[b, lengths[b] // page_size], in-page offset lengths[b] %
        page_size) and attends to its own lengths[b] + 1 valid tokens through
        the page table — the paged counterpart of `decode_step`, with the
        SAME per-layer op order (project, per-position RoPE, column write,
        mask-then-f32-softmax attention), so the two agree token-for-token
        (parity pin in tests/test_sampling.py). Inactive slots (empty or
        mid-prefill) have their writes DROPPED (redirected out of range —
        their page rows may hold real prefilled K/V) and attend to a single
        garbage key, producing finite logits the scheduler ignores.

        The layer loop goes through `_decode_layer_loop` (decode_layer_scan
        applies). Attention dispatches per `attn_impl` — 'auto' is the
        Pallas page-table kernel on TPU, the XLA gather fallback elsewhere
        (kernels/decode_attention.py). On a tp>1 serving mesh `mesh` routes
        the kernel through its per-shard shard_map (heads split over 'tp');
        everything else in this function is spelled in plain jnp on the
        batch/feature axes, so GSPMD partitions it from the head-sharded
        pool and megatron param shardings alone — the only activation
        collectives are the two per-layer megatron all-reduces
        (_attn_out_and_mlp's wo and w_down contractions), pinned by the
        analysis/hlo_audit.py tp census.

        Returns (logits (B, V), cache with the B new K/V columns written)."""
        from midgpt_tpu.kernels.decode_attention import paged_attention
        from midgpt_tpu.ops.rope import apply_rope_positions

        B = token.shape[0]
        C = config.head_dim
        ps = cache.page_size
        pos = lengths  # (B,) write positions
        active_i = active.astype(jnp.int32)
        # Valid keys per slot: the just-written token makes it lengths + 1
        # for active slots; inactive slots get 1 (the sink page's slot 0) so
        # the gather fallback's softmax never sees an all-masked row (NaN).
        attn_counts = jnp.maximum(active_i * (pos + 1), 1)
        # Inactive slots must not write at all — their page-table row is
        # real scheduler state (a mid-prefill slot's pages hold its already
        # prefilled K/V, which a sink-style write at position 0 would
        # corrupt). Redirect them past the pool so the scatter drops them.
        write_pages = jnp.where(
            active,
            jnp.take_along_axis(page_table, (pos // ps)[:, None], axis=1)[:, 0],
            cache.num_pages,
        )  # (B,)
        offs = pos % ps
        x = jnp.take(params.wte, token[:, None], axis=0)  # (B, 1, D)
        sin, cos = rope_table(C, config.block_size)
        positions = pos[:, None]  # (B, 1) — per-slot absolute positions

        def block_fn(carry, block_and_idx):
            x, ck_all, cv_all, cks_all, cvs_all = carry  # pools (L,H,P,ps,C)
            block, i = block_and_idx
            h = rms_norm(x)
            q, k, v = GPT._project_qkv(config, block, h)  # k/v (B, 1, HK, C)
            q = apply_rope_positions(q, sin, cos, positions, style=config.rope_style)
            k = apply_rope_positions(k, sin, cos, positions, style=config.rope_style)
            # q1 (B, H, C); k1/v1 (B, HK, C) — written at K/V geometry, the
            # kernel/gather handles the query-group broadcast.
            q1, k1, v1 = q[:, 0], k[:, 0], v[:, 0]
            # Advanced-indexing scatter (quantizing in int8 mode): one
            # (B,)-indexed column write per pool — i/write_pages/offs are
            # the advanced indices (result dims (B, H, C) lead), the H and
            # C axes ride as slices. In the decode loop carry this lowers
            # to an in-place scatter, not a pool copy (pinned) — scale
            # side buffers included (_paged_write).
            ck_all, cks_all = _paged_write(
                ck_all, cks_all, i, write_pages, offs, k1
            )
            cv_all, cvs_all = _paged_write(
                cv_all, cvs_all, i, write_pages, offs, v1
            )
            kp, ksp = _layer_pages(ck_all, cks_all, i)
            vp, vsp = _layer_pages(cv_all, cvs_all, i)
            att = paged_attention(
                q1, kp, vp, page_table, attn_counts, impl=attn_impl,
                k_scale=ksp, v_scale=vsp, mesh=mesh, split_k=split_k,
                sliding_window=config.sliding_window,
                attn_sinks=config.attn_sinks,
            )  # (B, H, C)
            x = GPT._attn_out_and_mlp(config, block, x, att[:, None])
            return (x, ck_all, cv_all, cks_all, cvs_all), None

        carry = GPT._decode_layer_loop(
            config,
            block_fn,
            (x, cache.k, cache.v, cache.k_scale, cache.v_scale),
            params.blocks,
        )
        x, k_new, v_new, ks_new, vs_new = carry
        x = rms_norm(x, eps=1e-5)
        logits = jnp.einsum("btd,vd->btv", x, params.lm_head)[:, 0]
        return logits, PagedKVCache(
            k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new
        )

    @staticmethod
    def verify_step_paged(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (B, K1) int — [t_last, d_1, .., d_k] per slot
        cache: "PagedKVCache",
        page_table: Array,  # (B, max_pages) int32
        lengths: Array,  # (B,) int32 — tokens already in slot b's cache
        active: Array,  # (B,) bool
        attn_impl: str = "auto",
        mesh=None,  # Optional[Mesh] — tp serving mesh (parallel/serve_tp.py)
        split_k: int = 1,  # key-sequence partitions per slot (static)
    ) -> tp.Tuple[Array, "PagedKVCache"]:
        """Score K1 = k+1 candidate tokens per slot in ONE batched paged
        forward — the target side of speculative decoding (sampling/spec.py).

        Slot b's token t sits at absolute position lengths[b] + t: its K/V
        is written there (same advanced-index scatter as decode_step_paged)
        and its query attends to lengths[b] + t + 1 keys through the page
        table — all K1 rows are written before the gather, so the per-row
        count IS the causal mask (kernels/decode_attention.py
        paged_verify_attention). Row t's logits score the token at position
        lengths[b] + t + 1, i.e. row 0 judges d_1 and row K1-1 supplies the
        bonus distribution.

        Positions past the accepted prefix hold REJECTED speculative K/V
        after the caller's rollback — that is deliberate: rollback is
        host-side only (length counters reset, tail pages freed), the pool
        is never rewritten, and the stale columns are masked by every later
        read until the slot grows back over them (write-before-read, the
        page-aligned rollback invariant, docs/SERVING.md). Inactive slots
        write nothing (out-of-range redirect) and attend to the single sink
        key. Same per-layer op order as decode_step_paged, so greedy
        speculative serving stays token-identical to plain paged decode
        (pinned by tests/test_spec.py).

        Precondition (scheduler-enforced): lengths[b] + K1 <= block_size and
        the page table covers position lengths[b] + K1 - 1 for active slots.

        Returns (logits (B, K1, V), cache with the B*K1 columns written)."""
        from midgpt_tpu.kernels.decode_attention import paged_verify_attention
        from midgpt_tpu.ops.rope import apply_rope_positions

        B, K1 = tokens.shape
        C = config.head_dim
        ps = cache.page_size
        t_idx = jnp.arange(K1, dtype=jnp.int32)
        positions = lengths[:, None] + t_idx[None, :]  # (B, K1)
        active_i = active.astype(jnp.int32)
        attn_counts = jnp.maximum(active_i[:, None] * (positions + 1), 1)
        write_pages = jnp.where(
            active[:, None],
            jnp.take_along_axis(page_table, positions // ps, axis=1),
            cache.num_pages,
        )  # (B, K1); inactive writes dropped via XLA oob-scatter semantics
        offs = positions % ps
        x = jnp.take(params.wte, tokens, axis=0)  # (B, K1, D)
        sin, cos = rope_table(C, config.block_size)

        def block_fn(carry, block_and_idx):
            x, ck_all, cv_all, cks_all, cvs_all = carry  # pools (L,H,P,ps,C)
            block, i = block_and_idx
            h = rms_norm(x)
            q, k, v = GPT._project_qkv(config, block, h)  # k/v (B, K1, HK, C)
            q = apply_rope_positions(q, sin, cos, positions, style=config.rope_style)
            k = apply_rope_positions(k, sin, cos, positions, style=config.rope_style)
            # (B, K1)-indexed column scatter: i scalar x write_pages x offs
            # broadcast to (B, K1) result dims, H and C ride as slices — the
            # same in-place-aliasing shape as the decode/prefill scatters
            # (quantizing in int8 mode, scale buffers riding along).
            ck_all, cks_all = _paged_write(
                ck_all, cks_all, i, write_pages, offs, k
            )
            cv_all, cvs_all = _paged_write(
                cv_all, cvs_all, i, write_pages, offs, v
            )
            kp, ksp = _layer_pages(ck_all, cks_all, i)
            vp, vsp = _layer_pages(cv_all, cvs_all, i)
            att = paged_verify_attention(
                q, kp, vp, page_table, attn_counts, impl=attn_impl,
                k_scale=ksp, v_scale=vsp, mesh=mesh, split_k=split_k,
                sliding_window=config.sliding_window,
                attn_sinks=config.attn_sinks,
            )  # (B, K1, H, C)
            x = GPT._attn_out_and_mlp(config, block, x, att.astype(x.dtype))
            return (x, ck_all, cv_all, cks_all, cvs_all), None

        carry = GPT._decode_layer_loop(
            config,
            block_fn,
            (x, cache.k, cache.v, cache.k_scale, cache.v_scale),
            params.blocks,
        )
        x, k_new, v_new, ks_new, vs_new = carry
        x = rms_norm(x, eps=1e-5)
        logits = jnp.einsum("btd,vd->btv", x, params.lm_head)
        return logits, PagedKVCache(
            k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new
        )

    @staticmethod
    def prefill_paged_chunk(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (1, T_c) int — one request's prompt chunk, padded
        start: Array,  # () int32 — absolute position of tokens[0, 0]
        n_valid: Array,  # () int32 — real tokens in this chunk (rest is pad)
        cache: "PagedKVCache",
        page_table: Array,  # (1, max_pages) int32
    ) -> tp.Tuple[Array, "PagedKVCache"]:
        """Prefill ONE request's prompt chunk [start, start + n_valid) into
        its pages, attending causally to the chunk itself plus everything
        the slot already holds ([0, start) — earlier chunks).

        Chunking is what lets the scheduler interleave long-prompt admission
        with running decodes: each serve round spends at most T_c prompt
        tokens of work before the batch decodes again (docs/SERVING.md).
        T_c is static — the engine pads the tail chunk and passes n_valid;
        pad positions are redirected to an out-of-range page index so the
        scatter DROPS them (XLA oob-scatter semantics) instead of clobbering
        allocated pages, and pad logits are garbage the caller ignores.

        Attention here is an XLA gather path only: the slot's pages are
        gathered contiguous ONCE per layer and all T_c chunk rows attend to
        that buffer under per-row length masks (the Pallas decode kernel's
        one-query-row online-softmax shape doesn't fit a chunk — a
        chunked-prefill kernel is the TPU upgrade path, docs/SERVING.md).

        Returns (logits (1, T_c, V), updated cache)."""
        _, T_c = tokens.shape
        C = config.head_dim
        ps = cache.page_size
        t_idx = jnp.arange(T_c, dtype=jnp.int32)
        positions = start + t_idx  # (T_c,)
        valid = t_idx < n_valid
        # Pad writes go out of range -> dropped by the scatter.
        write_pages = jnp.where(
            valid,
            jnp.take(page_table[0], positions // ps, axis=0),
            cache.num_pages,
        )
        offs = positions % ps
        x = jnp.take(params.wte, tokens, axis=0)  # (1, T_c, D)
        sin, cos = rope_table(C, config.block_size)
        # The chunk attends to attn_count = start + t + 1 keys at row t; pad
        # rows clamp to the last valid count (their output is discarded).
        attn_counts = jnp.minimum(positions, start + n_valid - 1) + 1  # (T_c,)

        def block_fn(carry, block_and_idx):
            x, ck_all, cv_all, cks_all, cvs_all = carry
            block, i = block_and_idx
            h = rms_norm(x)
            q, k, v = GPT._project_qkv(config, block, h)  # k/v (1, T_c, HK, C)
            qr = apply_rope_bthc(q, sin, cos, positions, style=config.rope_style)
            kr = apply_rope_bthc(k, sin, cos, positions, style=config.rope_style)
            # kr[0]/v[0] are (T_c, H, C) — the advanced-index scatter's
            # broadcast dims (i scalar x write_pages x offs -> (T_c,)) lead,
            # H and C ride as slices, so that's the update shape verbatim
            # (quantized with per-vector scales in int8 mode).
            ck_all, cks_all = _paged_write(
                ck_all, cks_all, i, write_pages, offs, kr[0]
            )
            cv_all, cvs_all = _paged_write(
                cv_all, cvs_all, i, write_pages, offs, v[0]
            )
            kp, ksp = _layer_pages(ck_all, cks_all, i)
            vp, vsp = _layer_pages(cv_all, cvs_all, i)
            # Gather the slot's pages contiguous ONCE (dequantizing after
            # the gather in int8 mode); every chunk row attends to the same
            # buffer under its own length mask (same
            # mask-then-scale-then-f32-softmax order as decode_step).
            kg = _gather_layer_kv(kp, ksp, page_table[0], x.dtype)
            vg = _gather_layer_kv(vp, vsp, page_table[0], x.dtype)
            # GQA: gathered buffers are (HK, S, C) — broadcast to the query
            # head count for the per-row masked attention.
            kg = _repeat_kv(config, kg, 0)
            vg = _repeat_kv(config, vg, 0)
            S = kg.shape[1]
            scores = jnp.einsum("thc,hsc->hts", qr[0].astype(kg.dtype), kg)
            col = jnp.arange(S)[None, None, :]
            ok = col < attn_counts[None, :, None]
            if config.sliding_window:
                keep = col >= attn_counts[None, :, None] - config.sliding_window
                if config.attn_sinks:
                    keep |= col < config.attn_sinks
                ok &= keep
            scores = jnp.where(ok, scores, float("-inf"))
            probs = jax.nn.softmax(
                scores.astype(jnp.float32) / math.sqrt(C), axis=-1
            ).astype(kg.dtype)
            att = jnp.einsum("hts,hsc->thc", probs, vg)  # (T_c, H, C)
            x = GPT._attn_out_and_mlp(config, block, x, att[None].astype(x.dtype))
            return (x, ck_all, cv_all, cks_all, cvs_all), None

        carry = GPT._decode_layer_loop(
            config,
            block_fn,
            (x, cache.k, cache.v, cache.k_scale, cache.v_scale),
            params.blocks,
        )
        x, k_new, v_new, ks_new, vs_new = carry
        x = rms_norm(x, eps=1e-5)
        logits = jnp.einsum("btd,vd->btv", x, params.lm_head)
        return logits, PagedKVCache(
            k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new
        )

    @staticmethod
    def count_params(params: GPTParams) -> int:
        """Parameter count excluding the duplicated tied embedding
        (reference model.py:161-164)."""
        total = sum(x.size for x in jax.tree.leaves(params))
        return total - params.lm_head.size
