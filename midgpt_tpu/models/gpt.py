"""Decoder-only GPT as a plain pytree + pure functions, TPU-first.

Architecture parity with the reference (for val-loss parity; see SURVEY.md §7):
  * pre-norm residual blocks with *weightless* RMSNorm (eps 1e-6 in blocks,
    1e-5 for the final norm — reference model.py:94-95,133)
  * fused QKV projection, QK-LayerNorm per head (learned scale, no bias,
    eps 1e-6 — reference model.py:52-53,64-65)
  * GPT-J interleaved rotary embeddings (reference layers.py:79-99)
  * bias-free Linears, truncated-normal(±2σ)/sqrt(fan_in) init (reference
    layers.py:49-50); embedding init N(0, 1/sqrt(D)) (reference model.py:134)
  * init-only weight tying: wte and lm_head start from the same array but are
    independent leaves that diverge from step 1 (reference model.py:135-138)
  * GELU MLP with 4x expansion (reference model.py:17-31)
  * fp32 softmax inside attention; logits returned in compute dtype and cast
    to fp32 by the loss (reference model.py:74-77, train.py:76)

TPU-first structure (different from the reference's Equinox modules):
  * Block parameters are stacked along a leading layer axis; the forward pass
    is ONE `jax.lax.scan` over that axis with `jax.checkpoint` per block
    (compile time O(1) in depth, remat bounds activation memory). The
    reference reaches the same shape via eqx.filter_vmap + filter scan
    (model.py:130-132,149-155); here it is the native representation.
  * The forward runs on a full (B, T) batch — batch semantics live in the
    model, not an outer vmap, so sharding constraints and Pallas kernels see
    the batched shapes they tile over.
  * Everything is shape-static and key-explicit: jit-safe by construction.
"""

from __future__ import annotations

import dataclasses
import math
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.ops.attention import multihead_attention
from midgpt_tpu.ops.dropout import dropout
from midgpt_tpu.ops.norms import head_layer_norm, rms_norm
from midgpt_tpu.ops.rope import apply_rope, rope_table
from midgpt_tpu.utils.pytree import pytree_dataclass

Array = jax.Array
KeyArray = jax.Array


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model shape (mirrors reference model.py:108-115)."""

    block_size: int  # max sequence length
    vocab_size: int
    n_layer: int
    n_head: int
    n_embd: int
    dropout: float = 0.0
    # TPU knobs (not part of the reference config surface):
    attn_impl: str = "naive"  # 'naive' | 'blockwise' | 'flash'
    attn_block_size: int = 512  # tile size for blockwise/flash paths
    remat: bool = True  # checkpoint each block inside the layer scan
    scan_unroll: int = 1  # unroll factor of the layer scan

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head


@pytree_dataclass
class AttentionParams:
    wqkv: Array  # (3D, D) fused QKV projection, applied as W @ x
    wo: Array  # (D, D) output projection
    q_scale: Array  # (C,) QK-LayerNorm scale for queries
    k_scale: Array  # (C,) QK-LayerNorm scale for keys


@pytree_dataclass
class MLPParams:
    w_up: Array  # (4D, D)
    w_down: Array  # (D, 4D)


@pytree_dataclass
class BlockParams:
    attn: AttentionParams
    mlp: MLPParams
    # Block RMSNorms are weightless (reference model.py:94-95): no leaves.


@pytree_dataclass
class GPTParams:
    wte: Array  # (V, D) token embedding
    blocks: BlockParams  # every leaf stacked with leading (n_layer,) axis
    lm_head: Array  # (V, D), applied as x @ lm_head.T; init-tied to wte


def _linear_init(key: KeyArray, out_features: int, in_features: int) -> Array:
    """Truncated-normal(±2σ) scaled 1/sqrt(fan_in) (reference layers.py:49-50)."""
    w = jax.random.truncated_normal(key, -2.0, 2.0, (out_features, in_features))
    return w / math.sqrt(in_features)


class GPT:
    """Namespace of pure functions over (GPTConfig, GPTParams)."""

    @staticmethod
    def init(config: GPTConfig, key: KeyArray) -> GPTParams:
        block_key, embed_key = jax.random.split(key)
        D, C = config.n_embd, config.head_dim

        def init_block(k: KeyArray) -> BlockParams:
            k_attn, k_proj, k_up, k_down = jax.random.split(k, 4)
            attn = AttentionParams(
                wqkv=_linear_init(k_attn, 3 * D, D),
                wo=_linear_init(k_proj, D, D),
                q_scale=jnp.ones((C,)),
                k_scale=jnp.ones((C,)),
            )
            mlp = MLPParams(
                w_up=_linear_init(k_up, 4 * D, D),
                w_down=_linear_init(k_down, D, 4 * D),
            )
            return BlockParams(attn=attn, mlp=mlp)

        blocks = jax.vmap(init_block)(jax.random.split(block_key, config.n_layer))
        embed = jax.random.normal(embed_key, (config.vocab_size, D)) / math.sqrt(D)
        # Init-only tying: same values, independent leaves (reference model.py:135-138).
        return GPTParams(wte=embed, blocks=blocks, lm_head=embed)

    @staticmethod
    def block_apply(
        config: GPTConfig,
        params: BlockParams,
        x: Array,  # (B, T, D)
        *,
        key: tp.Optional[KeyArray] = None,
        inference: bool = False,
        rope: tp.Optional[tp.Tuple[Array, Array]] = None,
        positions: tp.Optional[Array] = None,
    ) -> Array:
        B, T, D = x.shape
        H, C = config.n_head, config.head_dim
        if rope is None:
            rope = rope_table(C, T)
        sin, cos = rope
        if key is not None:
            k_attn_drop, k_resid, k_mlp = jax.random.split(key, 3)
        else:
            k_attn_drop = k_resid = k_mlp = None

        # --- attention sublayer ---
        h = rms_norm(x)  # weightless, eps 1e-6
        qkv = jnp.einsum("btd,ed->bte", h, params.attn.wqkv)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, C).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, H, C).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, H, C).transpose(0, 2, 1, 3)
        q = head_layer_norm(q, params.attn.q_scale)
        k = head_layer_norm(k, params.attn.k_scale)
        q = apply_rope(q, sin, cos, positions)
        k = apply_rope(k, sin, cos, positions)
        att = multihead_attention(
            q,
            k,
            v,
            impl=config.attn_impl,
            dropout_rate=config.dropout,
            key=k_attn_drop,
            inference=inference,
            block_size=config.attn_block_size,
        )
        att = att.transpose(0, 2, 1, 3).reshape(B, T, D)
        att = jnp.einsum("btd,ed->bte", att, params.attn.wo)
        att = dropout(att, config.dropout, k_resid, inference)
        x = x + att

        # --- MLP sublayer ---
        h = rms_norm(x)
        h = jax.nn.gelu(jnp.einsum("btd,ed->bte", h, params.mlp.w_up))
        h = jnp.einsum("bte,de->btd", h, params.mlp.w_down)
        h = dropout(h, config.dropout, k_mlp, inference)
        return x + h

    @staticmethod
    def apply(
        config: GPTConfig,
        params: GPTParams,
        tokens: Array,  # (B, T) int
        *,
        key: tp.Optional[KeyArray] = None,
        inference: bool = False,
    ) -> Array:
        """Forward pass -> logits (B, T, V) in the params' floating dtype."""
        B, T = tokens.shape
        C = config.head_dim
        if key is not None:
            drop_key, layers_key = jax.random.split(key)
            layer_keys = jax.random.split(layers_key, config.n_layer)
        else:
            drop_key, layer_keys = None, None

        x = jnp.take(params.wte, tokens, axis=0)  # (B, T, D)
        x = dropout(x, config.dropout, drop_key, inference)

        rope = rope_table(C, T)  # shared fp32 table, constant-folded under jit

        def block_fn(x, block_and_key):
            block, k = block_and_key
            return (
                GPT.block_apply(
                    config, block, x, key=k, inference=inference, rope=rope
                ),
                None,
            )

        if config.remat:
            block_fn = jax.checkpoint(block_fn)
        x, _ = jax.lax.scan(
            block_fn, x, (params.blocks, layer_keys), unroll=config.scan_unroll
        )

        x = rms_norm(x, eps=1e-5)  # final norm (reference model.py:133,156)
        return jnp.einsum("btd,vd->btv", x, params.lm_head)

    @staticmethod
    def count_params(params: GPTParams) -> int:
        """Parameter count excluding the duplicated tied embedding
        (reference model.py:161-164)."""
        total = sum(x.size for x in jax.tree.leaves(params))
        return total - params.lm_head.size
