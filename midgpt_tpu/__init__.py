"""midgpt_tpu — a TPU-native GPT pretraining and sampling framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the reference
midGPT harness (see SURVEY.md): decoder-only GPT pretraining with rotary
embeddings, weightless RMSNorm, QK-LayerNorm, independent weight decay, bf16
compute over fp32 master params, gradient accumulation, FSDP sharding over a
named TPU mesh, async Orbax checkpointing, and KV-cached sampling.

TPU-first design notes:
  * The model is a plain pytree of arrays (no module framework): transformer
    blocks are *stacked* along a leading layer axis and the forward pass is a
    single `jax.lax.scan` with per-block `jax.checkpoint` — one fused XLA
    program, compile time independent of depth.
  * Parallelism is expressed as `jax.sharding` PartitionSpecs over a named
    mesh ('data', 'fsdp', 'sp', 'tp'); XLA GSPMD inserts all ICI collectives.
  * The attention hot op dispatches over implementations (naive T×T,
    blockwise O(T) online-softmax; Pallas flash kernel and ring-attention
    context parallelism land here as they are built).
"""

from midgpt_tpu.config import ExperimentConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams

__version__ = "0.1.0"
__all__ = ["ExperimentConfig", "GPT", "GPTConfig", "GPTParams", "__version__"]
