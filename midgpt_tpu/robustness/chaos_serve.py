"""Serving chaos scenarios: inject one of the serving fault kinds into a
seeded trace and assert the engine DEGRADES instead of breaking.

The training chaos harness (tools/chaos_run.py + robustness/faults.py)
proves recovery end to end by running the real supervisor against injected
failures. This module is the serving twin: `run_serving_chaos` runs the
same seeded request trace twice — once fault-free for reference, once with
a fault plan armed — and checks the three degradation invariants the chaos
gate (tests/test_chaos_serve.py, `chaos_run.py --serve`) enforces:

  1. **Alive** — the engine (and, for client faults, the async front door)
     finishes the trace; no fault kind may crash the process.
  2. **Conserved** — every pool page is back on the free list afterwards
     (`free_count == num_pages - 1`), whatever was shed/killed/poisoned.
  3. **Isolated** — greedy token streams of UNAFFECTED requests are
     bit-identical to the fault-free run (greedy determinism pin,
     tests/test_chaos_serve.py). "Affected" is fault-specific and
     engine-reported: `poisoned_uids` for poisoned_page, non-"ok" statuses
     for sheds/timeouts/cancels. kill_mid_decode affects NOBODY — its
     recovery is recompute preemption, which is parity-preserving — so
     there every request must match. kill_overlapped_round is its
     round-overlap twin (docs/SERVING.md "Round-overlap dispatch"): the
     engine runs with `overlap="double"`, the fault drops the IN-FLIGHT
     dispatched round's handle un-settled mid host phase, and the same
     recompute-preemption path must regenerate every lost token — the
     reference pass stays un-overlapped, so the parity check also re-proves
     that overlap itself is bit-exact.

Two model-ops scenarios ride the same harness (sampling/ops.py,
docs/ROBUSTNESS.md "Zero-downtime model ops") with a THREE-sided parity
check instead of invariant 3's two-sided one:

  * `hot_swap_mid_decode@k` — verified-checkpoint weights (saved and
    restored through the real training/checkpoint.py manifest path) are
    staged at round k and flip blue/green: zero streams dropped, streams
    finished before the flip bit-match a fault-free OLD-weights pass,
    streams admitted after bit-match a fault-free NEW-weights pass, pool +
    trie conserved across the flip.
  * `pool_resize@j,pool_resize@k` — the pool grows then shrinks mid-trace
    (engine `resize_plan`), on an int8 cache so the scale side buffers
    must migrate with their pages: conservation holds at every boundary
    (asserted inside resize_pool AND after the drain) and EVERY stream is
    bit-identical to a no-resize pass — a resize affects nobody.

The fleet scenarios (sampling/fleet.py, `_run_fleet_chaos`) run the trace
through TWO replicas behind a FleetRouter with its shared host-RAM spill
tier and extend all three invariants across replicas and tiers:
`engine_crash@k` kills the busiest replica at router round k — zero
accepted streams drop, failover replays bit-match the single-engine
reference; `handoff_stall` / `spill_corrupt` hit the spill path — a
stalled transport falls back to re-prefill and a corrupt page is caught
by the take-side checksum, either way never a token mismatch — with
cross-tier page conservation (assert_fleet_conserved) after the drain.

The cross-process kinds (`proc_kill9` / `conn_drop` / `wire_corrupt` /
`wire_stall`, `_run_proc_fleet_chaos`) run the same fleet gate with the
replica boundary promoted to real worker PROCESSES behind the framed
socket transport (sampling/fleet_proc.py): a hard `kill -9` of a worker
mid-decode must be detected purely through the wire and produce the exact
engine_crash failover story — zero drops, cross-process bit-parity,
ledgers closing across the boundary — while the pure wire faults must be
absorbed by the transport's checksum/deadline/retry machinery invisibly.

Faults are deterministic for a seeded trace: round-keyed kinds fire on the
engine's round counter (`kill_mid_decode@7` = round 7), slow_client keys on
the victim uid, submit_storm keys on the arrival index at which the burst
lands. This module is import-light glue; the faults it arms live in the one
registry every chaos path shares.

Every scenario runs its fault pass under a flight recorder and leaves a
postmortem (`flight_recorder.json` + `.prom`): in `trace_dir` when the
caller gave one, and in a fresh temp dir — path appended to the failing
AssertionError — when an invariant breaks without one.
"""

from __future__ import annotations

import asyncio
import subprocess
import tempfile
import typing as tp

import numpy as np

from midgpt_tpu.obs import Observability
from midgpt_tpu.robustness import faults

# Storm burst: how many clone requests the submit_storm fault slams into
# the engine at its arrival index (sized to overrun the default backlog
# budget below several times over).
STORM_SIZE = 8
# Backlog budget armed for storm scenarios — small enough that the burst
# MUST shed, big enough that the base trace admits.
STORM_BACKLOG_PAGES = 24
# Grow-then-shrink targets for the pool_resize scenario, applied in plan
# order from the 29-page base geometry below. 43/37 are fresh geometries:
# pool size is a program-key dim and the recompile pins count from
# pristine/warm baselines in the same pytest process (see _engine).
RESIZE_TARGETS = [43, 37]


def _tiny_cfg():
    from midgpt_tpu.models.gpt import GPTConfig

    return GPTConfig(
        block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32
    )


def _tiny_model(seed: int):
    import jax

    from midgpt_tpu.models.gpt import GPT

    cfg = _tiny_cfg()
    return cfg, GPT.init(cfg, jax.random.PRNGKey(seed))


def _trace(cfg, seed: int, n_requests: int, shared: bool = False):
    rng = np.random.default_rng(seed)
    out = []
    # `shared` (the evict_shared_prefix scenario): template-heavy traffic —
    # two 16-token system prompts with short unique tails — so the prefix
    # trie holds HOT shared nodes for the fault to flush. Drawn only in
    # shared mode: the plain scenarios' seeded traces must stay the exact
    # RNG stream their step-keyed fault plans were tuned against.
    templates = [
        rng.integers(0, cfg.vocab_size, 16).astype(np.int32) for _ in range(2)
    ] if shared else []
    for i in range(n_requests):
        if shared:
            tail = rng.integers(
                0, cfg.vocab_size, int(rng.integers(2, 6))
            ).astype(np.int32)
            prompt = np.concatenate([templates[i % 2], tail])
            m = int(rng.integers(6, 16))
        else:
            # draw order (t0, m, prompt) is load-bearing: the plain
            # scenarios' step-keyed fault plans were tuned against it
            t0 = int(rng.integers(4, 24))
            m = int(rng.integers(6, 16))
            prompt = rng.integers(0, cfg.vocab_size, t0).astype(np.int32)
        out.append((prompt, m))
    return out


def _engine(
    cfg, params, *, max_backlog_pages=None, clock=None, prefix=False,
    obs=None, cache_dtype=None, overlap="off",
):
    import jax.numpy as jnp

    from midgpt_tpu.sampling.serve import ServeEngine

    kw: tp.Dict[str, tp.Any] = {}
    if clock is not None:
        kw["clock"] = clock
    if obs is not None:
        kw["obs"] = obs
    return ServeEngine(
        cfg,
        params,
        max_slots=3,
        page_size=8,
        # NOT 25: the pool size is a program-key dim, and the recompile pin
        # (tests/test_recompile_pins.py) counts compiles of the 25-page f32
        # program set from a pristine baseline — chaos runs in the same
        # pytest process must not pre-warm that exact geometry.
        num_pages=29,
        prefill_chunk=16,
        decode_chunk=4,
        temperature=0.0,
        cache_dtype=jnp.float32 if cache_dtype is None else cache_dtype,
        max_backlog_pages=max_backlog_pages,
        prefix_cache=prefix,
        overlap=overlap,
        **kw,
    )


def _run_plain(eng, trace, storm: bool):
    """Drive the engine synchronously. Returns (uid -> trace index,
    n_storm_shed). With `storm`, each arrival consults the submit_storm
    fault (step = arrival index) and, when it fires, slams STORM_SIZE
    clones of that request in at once — the admitted ones compete for the
    pool like real duplicate traffic, the rest must shed."""
    from midgpt_tpu.sampling.serve import BackpressureError

    uid_to_idx: tp.Dict[int, int] = {}
    storm_shed = 0
    for idx, (prompt, m) in enumerate(trace):
        if storm and faults.should_fire("submit_storm", step=idx):
            for _ in range(STORM_SIZE):
                try:
                    eng.submit(prompt, m)  # clones: excluded from parity
                except BackpressureError:
                    storm_shed += 1
        try:
            uid_to_idx[eng.submit(prompt, m)] = idx
        except BackpressureError:
            storm_shed += 1
    eng.run()
    return uid_to_idx, storm_shed


def _run_trickle(eng, trace, arrival_stride: int = 2):
    """Drive the engine with STAGGERED arrivals — one submission every
    `arrival_stride` rounds — instead of _run_plain's upfront burst, so a
    mid-trace model op deterministically has traffic on BOTH sides of its
    boundary (the hot-swap gate needs post-flip admissions). Greedy
    streams are batch-composition-independent, so parity against an
    upfront-submitted reference pass is still exact — the same property
    the preemption/disagg parity gates lean on, pinned end to end in
    tests/test_chaos_serve.py (hot-swap and pool-resize gates)."""
    uid_to_idx: tp.Dict[int, int] = {}
    pending = list(enumerate(trace))
    r = 0
    while pending or not eng.idle:
        if pending and r % arrival_stride == 0:
            idx, (prompt, m) = pending.pop(0)
            uid_to_idx[eng.submit(prompt, m)] = idx
        eng.step()
        r += 1
        assert r < 10_000, "trickle drive did not converge"
    return uid_to_idx


def _run_server(eng, trace):
    """Drive the engine through the async front door, one consumer task
    per request, collecting delivered tokens (what a client actually saw —
    the thing slow-client sheds must not corrupt for anyone else)."""
    from midgpt_tpu.sampling.server import AsyncServeServer

    delivered: tp.Dict[int, tp.List[int]] = {}
    uid_to_idx: tp.Dict[int, int] = {}

    async def main():
        server = AsyncServeServer(
            eng, max_buffered_tokens=4, submit_retries=1, idle_poll_s=0.001
        )
        driver = asyncio.create_task(server.run())

        async def consume(uid):
            delivered[uid] = []
            async for tok in server.stream(uid):
                delivered[uid].append(tok)

        consumers = []
        for idx, (prompt, m) in enumerate(trace):
            uid = await server.submit(prompt, m)
            uid_to_idx[uid] = idx
            consumers.append(asyncio.create_task(consume(uid)))
        await asyncio.gather(*consumers)
        await server.drain()
        await driver

    asyncio.run(main())
    return uid_to_idx, delivered


# -- shared scenario scaffolding (one builder, one postmortem policy) ------


def _reference_pass(cfg, params, trace, *, prefix=False, cache_dtype=None):
    """Fault-free pass -> {trace index: full reference token array}. Also
    warms every jit shape, so the fault pass's timings/timeouts cannot
    hinge on compile stalls. Clears the registry first: a previously armed
    plan must never leak into a reference."""
    faults.clear()
    ref = _engine(cfg, params, prefix=prefix, cache_dtype=cache_dtype)
    ref_uids, _ = _run_plain(ref, trace, storm=False)
    return {
        idx: np.asarray(ref.finished[uid].tokens)
        for uid, idx in ref_uids.items()
    }


def _armed_engine(cfg, params, fault_plan, **engine_kw):
    """Arm `fault_plan` and build the engine-under-fault with its flight
    recorder — the ONE construction point every scenario shares (each
    fault kind used to re-spell this pair). Returns (eng, obs, armed)."""
    faults.clear()
    armed = faults.activate_plan(fault_plan)
    obs = Observability()
    eng = _engine(cfg, params, obs=obs, **engine_kw)
    return eng, obs, armed


def _run_scenario(obs, trace_dir, body):
    """Run `body()` — the fault pass PLUS its invariant checks — under the
    postmortem policy: dump the flight recorder into `trace_dir` when the
    caller asked for one, and on ANY failure even without one (fresh temp
    dir, path appended to the exception) so a broken invariant always
    leaves a loadable trace. Returns body's summary with "trace" set."""
    try:
        summary = body()
    except BaseException as e:
        d = trace_dir or tempfile.mkdtemp(prefix="midgpt_chaos_postmortem_")
        path = obs.dump(d)
        e.args = tuple(
            [f"{e.args[0]}\n[flight recorder: {path}]"] + list(e.args[1:])
        ) if e.args else (f"[flight recorder: {path}]",)
        raise
    summary["trace"] = None if trace_dir is None else obs.dump(trace_dir)
    return summary


def _assert_drained_conserved(eng) -> int:
    """Invariant 2 (+ serviceability): engine drained, every page either
    free or retained by the trie with zero live references. Returns the
    trie page count for the summary."""
    assert eng.idle, "engine left work behind"
    trie_pages = (
        0 if eng.prefix_cache is None else eng.prefix_cache.page_count()
    )
    assert (
        eng.allocator.free_count + trie_pages == eng.allocator.num_pages - 1
    ), (
        f"page leak: {eng.allocator.free_count} free + {trie_pages} trie of "
        f"{eng.allocator.num_pages - 1} allocatable"
    )
    if eng.prefix_cache is not None:
        dangling = eng.prefix_cache.referenced_page_count()
        assert dangling == 0, f"{dangling} trie refcount(s) outlived the drain"
    return trie_pages


def run_serving_chaos(
    fault_plan: str, *, seed: int = 0, n_requests: int = 5,
    trace_dir: tp.Optional[str] = None,
) -> tp.Dict[str, tp.Any]:
    """Run the scenario (module docstring); returns the summary dict that
    `chaos_run.py --serve` emits as its JSON line. Raises AssertionError
    when a degradation invariant breaks — that IS the chaos verdict.

    The fault pass always runs under a flight recorder (midgpt_tpu/obs/):
    with `trace_dir` the Chrome trace + .prom metrics land there
    unconditionally; without one they land in a temp dir only when an
    invariant fails (the path rides the AssertionError)."""
    if any(
        k in fault_plan
        for k in ("proc_kill9", "conn_drop", "wire_corrupt", "wire_stall")
    ):
        return _run_proc_fleet_chaos(
            fault_plan, seed=seed, n_requests=n_requests, trace_dir=trace_dir
        )
    if any(
        k in fault_plan
        for k in ("engine_crash", "handoff_stall", "spill_corrupt")
    ):
        return _run_fleet_chaos(
            fault_plan, seed=seed, n_requests=n_requests, trace_dir=trace_dir
        )
    if "hot_swap_mid_decode" in fault_plan:
        return _run_hot_swap_chaos(
            fault_plan, seed=seed, n_requests=n_requests, trace_dir=trace_dir
        )
    if "pool_resize" in fault_plan:
        return _run_pool_resize_chaos(
            fault_plan, seed=seed, n_requests=n_requests, trace_dir=trace_dir
        )
    cfg, params = _tiny_model(seed)
    uses_server = "slow_client" in fault_plan
    uses_storm = "submit_storm" in fault_plan
    # The trie-flush fault needs a trie: both passes run with the prefix
    # cache ON over a template-shared trace, so the reference pass also
    # proves the cache itself is parity-clean before the flush is judged.
    uses_prefix = "evict_shared_prefix" in fault_plan
    # The overlap-kill fault needs an in-flight dispatched round to drop:
    # only the fault pass runs double-buffered — the reference stays plain,
    # so invariant 3 doubles as an overlap-on-vs-off greedy parity check.
    uses_overlap = "kill_overlapped_round" in fault_plan
    trace = _trace(cfg, seed + 1, n_requests, shared=uses_prefix)

    ref_tokens = _reference_pass(cfg, params, trace, prefix=uses_prefix)
    eng, obs, armed = _armed_engine(
        cfg, params, fault_plan,
        max_backlog_pages=STORM_BACKLOG_PAGES if uses_storm else None,
        prefix=uses_prefix,
        overlap="double" if uses_overlap else "off",
    )

    def body() -> tp.Dict[str, tp.Any]:
        delivered: tp.Optional[tp.Dict[int, tp.List[int]]] = None
        storm_shed = 0
        if uses_server:
            uid_to_idx, delivered = _run_server(eng, trace)
        else:
            uid_to_idx, storm_shed = _run_plain(eng, trace, storm=uses_storm)
        fired = faults.fired_counts()
        faults.clear()

        _assert_drained_conserved(eng)

        # -- invariant 3: unaffected greedy streams are bit-identical ----
        affected = set(eng.poisoned_uids)
        statuses: tp.Dict[str, int] = {}
        parity_checked = parity_ok = 0
        for uid, idx in uid_to_idx.items():
            fr = eng.finished.get(uid)
            assert fr is not None, f"request {uid} vanished"
            statuses[fr.status] = statuses.get(fr.status, 0) + 1
            if fr.status != "ok":
                affected.add(uid)  # shed/timeout/slow_client: partial by design
            if uid in affected:
                continue
            parity_checked += 1
            if np.array_equal(np.asarray(fr.tokens), ref_tokens[idx]):
                parity_ok += 1
            if delivered is not None:
                # What the client consumed must be a prefix of the reference
                # generation — streaming may trail the engine, never diverge.
                prompt_len = len(trace[idx][0])
                got = np.asarray(delivered[uid], np.int32)
                want = ref_tokens[idx][prompt_len:prompt_len + len(got)]
                assert np.array_equal(got, want), (
                    f"delivered stream diverged for request {uid}"
                )
        assert parity_ok == parity_checked, (
            f"greedy parity broke on {parity_checked - parity_ok} unaffected "
            f"request(s)"
        )
        assert sum(fired.values()) >= min(1, len(armed)), "no armed fault fired"
        if fired.get("kill_overlapped_round"):
            assert eng.overlap_kills >= 1, (
                "overlap kill fired but no in-flight round was ever dropped"
            )

        return {
            "mode": "serve",
            "fault_plan": fault_plan,
            "faults_fired": fired,
            "n_requests": n_requests,
            "statuses": statuses,
            "overlap_mode": eng.overlap,
            "overlap_kills": eng.overlap_kills,
            "shed": eng.shed + storm_shed,
            "timeouts": eng.timeouts,
            "cancelled": eng.cancelled,
            "decode_kills": eng.decode_kills,
            "preemptions": eng.preemptions,
            "poisoned": len(eng.poisoned_uids),
            "parity_checked": parity_checked,
            "parity_ok": parity_ok,
            "pages_conserved": True,
            "prefix_cache": eng.prefix_cache is not None,
            "prefix_reclaimed": eng.prefix_evictions,
            "prefix_hit_rate": eng.prefix_stats()["hit_rate"],
        }

    return _run_scenario(obs, trace_dir, body)


# -- fleet scenarios (sampling/fleet.py) -----------------------------------


def _fleet_router(cfg, params, obs, n_replicas: int = 2):
    """The fleet-under-fault: `n_replicas` prefix-cached greedy engines
    behind a FleetRouter with its shared spill tier (the router attaches
    it). Same per-replica shape as _engine except the pool: 31 is a fresh
    program-key geometry — not 25 (recompile-pin baseline), 27 (loadgen),
    29 (single-engine chaos), or 43/37 (resize targets)."""
    import jax.numpy as jnp

    from midgpt_tpu.sampling.fleet import FleetRouter
    from midgpt_tpu.sampling.serve import ServeEngine

    engines = [
        ServeEngine(
            cfg,
            params,
            max_slots=3,
            page_size=8,
            num_pages=31,
            prefill_chunk=16,
            decode_chunk=4,
            temperature=0.0,
            cache_dtype=jnp.float32,
            prefix_cache=True,
            obs=obs,
            obs_tid=f"replica{i}",
        )
        for i in range(n_replicas)
    ]
    return FleetRouter(engines)


def _run_fleet_chaos(fault_plan, *, seed, n_requests, trace_dir):
    """Fleet degradation gate (docs/ROBUSTNESS.md "Fleet serving &
    failover"): run the shared-template trace through a 2-replica fleet
    with `fault_plan` armed and assert the three invariants extended
    across replicas and tiers —

      1. Alive: the FLEET finishes the trace; killing a replica mid-trace
         (engine_crash) drops ZERO accepted streams — they fail over.
      2. Conserved, cross-tier: every alive replica obeys the pool law
         and the spill ledger closes (assert_fleet_conserved), including
         through the spill_corrupt discard path.
      3. Bit-identical: EVERY stream — survivors and failover replays —
         matches a fault-free single-engine reference pass. A corrupt or
         stalled spill page may cost a re-prefill, never a token.

    The spill-path kinds (handoff_stall / spill_corrupt) need resident
    spilled pages to bite on, which organic pressure only produces at
    pool sizes that make the trace nondeterministically tight. Instead
    the scenario STAGES the tier: the first request runs alone, then
    every replica's trie is force-flushed (the same reclaim the
    evict_shared_prefix fault models), spilling its pages to the host
    tier deterministically; the remaining same-template requests then
    consult the tier on admission — where the armed stall refuses the
    first useful run and the armed corruption is caught by the take-side
    checksum."""
    from midgpt_tpu.sampling.fleet import assert_fleet_conserved

    cfg, params = _tiny_model(seed)
    trace = _trace(cfg, seed + 1, n_requests, shared=True)
    ref_tokens = _reference_pass(cfg, params, trace, prefix=True)

    faults.clear()
    armed = faults.activate_plan(fault_plan)
    obs = Observability()
    router = _fleet_router(cfg, params, obs)
    stage_spill = any(
        k in fault_plan for k in ("handoff_stall", "spill_corrupt")
    )

    def body() -> tp.Dict[str, tp.Any]:
        uid_to_idx: tp.Dict[int, int] = {}
        pending = list(enumerate(trace))
        if stage_spill and pending:
            idx, (prompt, m) = pending.pop(0)
            uid_to_idx[router.submit(prompt, m)] = idx
            router.run()
            for i, rep in enumerate(router.engines):
                if router.alive[i]:
                    rep._evict_shared_prefix_fault()
        r = 0
        while pending or not router.idle:
            if pending:
                idx, (prompt, m) = pending.pop(0)
                # trickled one per round (like _run_trickle): a mid-trace
                # crash deterministically finds accepted streams in flight
                uid_to_idx[router.submit_retry(prompt, m)] = idx
            router.step()
            r += 1
            assert r < 10_000, "fleet drive did not converge"
        fired = faults.fired_counts()
        faults.clear()

        # -- invariant 2, extended across replicas AND tiers -------------
        assert_fleet_conserved(router, "after drain")
        for i, rep in enumerate(router.engines):
            if router.alive[i]:
                _assert_drained_conserved(rep)

        # -- invariants 1 + 3: zero drops, every stream bit-identical ----
        statuses: tp.Dict[str, int] = {}
        parity_checked = parity_ok = 0
        for uid, idx in uid_to_idx.items():
            fr = router.finished.get(uid)
            assert fr is not None, f"accepted stream {uid} vanished"
            statuses[fr.status] = statuses.get(fr.status, 0) + 1
            assert fr.status == "ok", (
                f"accepted stream {uid} dropped with status {fr.status!r}"
            )
            parity_checked += 1
            if np.array_equal(np.asarray(fr.tokens), ref_tokens[idx]):
                parity_ok += 1
        assert parity_ok == parity_checked, (
            f"greedy parity broke on {parity_checked - parity_ok} "
            f"stream(s) vs the fault-free single-engine pass"
        )
        assert sum(fired.values()) >= min(1, len(armed)), "no armed fault fired"
        if fired.get("engine_crash"):
            assert router.failovers >= 1, "crash fired but nobody died"
            assert router.failed_over_streams >= 1, (
                "crash fired with no accepted streams to fail over — "
                "the gate proved nothing"
            )
        if fired.get("handoff_stall"):
            assert router.spill.stall_fallbacks >= 1, (
                "stall armed but no consult ever fell back to re-prefill"
            )
        if fired.get("spill_corrupt"):
            assert router.spill.corrupt_discarded >= 1, (
                "corruption armed but never caught by the take-side checksum"
            )

        return {
            "mode": "serve",
            "fault_plan": fault_plan,
            "faults_fired": fired,
            "n_requests": n_requests,
            "statuses": statuses,
            "shed": sum(e.shed for e in router.engines),
            "timeouts": sum(e.timeouts for e in router.engines),
            "cancelled": sum(e.cancelled for e in router.engines),
            "decode_kills": sum(e.decode_kills for e in router.engines),
            "preemptions": sum(e.preemptions for e in router.engines),
            "poisoned": 0,
            "parity_checked": parity_checked,
            "parity_ok": parity_ok,
            "pages_conserved": True,
            "prefix_cache": True,
            "prefix_reclaimed": sum(
                e.prefix_evictions for e in router.engines
            ),
            "prefix_hit_rate": router.prefix_hit_rate(),
            "fleet_size": len(router.engines),
            "alive": sum(router.alive),
            "failovers": router.failovers,
            "failed_over_streams": router.failed_over_streams,
            "dropped_streams": 0,
            "spill": router.spill.stats(),
        }

    return _run_scenario(obs, trace_dir, body)


# -- cross-process fleet scenarios (sampling/fleet_proc.py) ----------------


def proc_worker_spec(seed: int, *, cpu_devices: int = 1) -> tp.Dict[str, tp.Any]:
    """Worker spec matching the chaos fleet geometry: the same tiny model
    at the same seed (same-seed GPT.init on the same pinned CPU backend =>
    bit-identical params in every process, the foundation of cross-process
    greedy parity — pinned end to end by tests/test_fleet_proc.py) and the
    31-page fleet pool. Workers have their OWN jit
    caches, so 31 collides with nothing in the parent (the program-key
    geometry ledger in _fleet_router's docstring is per-process)."""
    import dataclasses as _dc

    from midgpt_tpu.sampling.fleet_proc import parent_jax_config

    return {
        "model": _dc.asdict(_tiny_cfg()),
        "seed": seed,
        "engine": {
            "max_slots": 3,
            "page_size": 8,
            "num_pages": 31,
            "prefill_chunk": 16,
            "decode_chunk": 4,
            "cache_dtype": "float32",
        },
        "cpu_devices": cpu_devices,
        "jax_config": parent_jax_config(),
    }


def _proc_reference_pass(port, trace):
    """Fault-free single-engine pass driven over the wire on an
    already-spawned worker (same spec, same pinned CPU backend as the
    fleet workers) -> {trace index: full token array}. Running the
    reference in-parent would compare across BACKENDS whenever the parent
    sits on the real TPU (chaos_run.py without MIDGPT_PLATFORM) —
    worker-vs-worker keeps the parity claim about the process boundary,
    not about TPU-vs-CPU matmul bit patterns. Upfront submission (vs the
    fleet drive's trickle) is fine: greedy streams are
    batch-composition-independent, the same property every other parity
    gate leans on (tests/test_fleet_proc.py runs this gate non-slow)."""
    from midgpt_tpu.sampling.fleet_proc import connect_replica

    faults.clear()
    rep = connect_replica(port)
    uid_to_idx = {}
    for idx, (prompt, m) in enumerate(trace):
        uid_to_idx[rep.submit(prompt, m)] = idx
    r = 0
    while not rep.idle:
        rep.step()
        r += 1
        assert r < 10_000, "proc reference drive did not converge"
    ref = {
        idx: np.asarray(rep.finished[uid].tokens)
        for uid, idx in uid_to_idx.items()
    }
    rep.close()
    return ref


def _run_proc_fleet_chaos(fault_plan, *, seed, n_requests, trace_dir):
    """Cross-process fleet degradation gate (docs/ROBUSTNESS.md
    "Cross-process fleet"): the _run_fleet_chaos invariants with the
    replica boundary promoted to a real OS process boundary — two worker
    PROCESSES (fleet_proc.spawn_worker, each its own jax backend and jit
    cache) behind a FleetRouter speaking the framed socket transport.

      1. Alive: `proc_kill9` SIGKILLs the busiest worker mid-decode and
         the fleet still finishes every accepted stream — detection flows
         purely through the wire (ReplicaGoneError -> consecutive-failure
         health check -> the same _crash failover as engine_crash), zero
         drops, bounded requeue then structured shed.
      2. Conserved, across the process boundary: alive workers run the
         pool law + spill ledger IN-process over the `conserve` RPC
         (assert_fleet_conserved dispatches), and the router-side tier
         ledger closes.
      3. Bit-identical: every stream — survivors and failover replays —
         matches a fault-free single-engine reference served by its own
         worker process (_proc_reference_pass), proving params, prefill,
         and decode agree bit-for-bit across process boundaries.

    The wire kinds (`conn_drop` / `wire_corrupt` / `wire_stall`) must be
    absorbed by the transport invisibly: same zero-drop, same parity, plus
    the per-kind transport counter proving the fault actually bit
    (reconnects / corrupt_frames / deadline_expiries).

    The router process must also compile NOTHING: the parent's jit census
    (ServeEngine.compile_stats) is snapshotted up front and pinned
    unchanged after the drive — the whole scenario runs without a single
    parent-process engine program. Pinned by tests/test_fleet_proc.py
    (kill-and-survive representative + slow wire-kind scenarios)."""
    from midgpt_tpu.sampling.fleet import FleetRouter, assert_fleet_conserved
    from midgpt_tpu.sampling.fleet_proc import connect_replica, spawn_workers
    from midgpt_tpu.sampling.serve import ServeEngine

    cfg = _tiny_cfg()
    trace = _trace(cfg, seed + 1, n_requests, shared=True)
    compiles_before = ServeEngine.compile_stats()
    spec = proc_worker_spec(seed)
    procs = []
    try:
        # all three workers (reference + 2 replicas) spawn CONCURRENTLY:
        # jax import + engine build overlap, and the fleet workers keep
        # warming while the reference pass drives worker 0
        procs = spawn_workers(spec, 3)
        ref_tokens = _proc_reference_pass(procs[0][1], trace)
        procs[0][0].kill()

        faults.clear()
        armed = faults.activate_plan(fault_plan)
        obs = Observability()
        replicas = [
            connect_replica(port, retry_base_s=0.05, obs=obs)
            for _, port in procs[1:]
        ]
        router = FleetRouter(replicas)

        def body() -> tp.Dict[str, tp.Any]:
            uid_to_idx: tp.Dict[int, int] = {}
            pending = list(enumerate(trace))
            r = 0
            while pending or not router.idle:
                if pending:
                    idx, (prompt, m) = pending.pop(0)
                    # trickled one per round (like _run_fleet_chaos): the
                    # round-keyed kill deterministically lands mid-decode
                    uid_to_idx[router.submit_retry(prompt, m)] = idx
                router.step()
                r += 1
                # wider guard than the in-process drive: kill -9 detection
                # costs max_consecutive_failures failed rounds first
                assert r < 20_000, "proc fleet drive did not converge"
            fired = faults.fired_counts()
            faults.clear()

            # -- invariant 2, across the process boundary ---------------
            assert_fleet_conserved(router, "after proc drain")

            # -- invariants 1 + 3: zero drops, bit-parity cross-process -
            statuses: tp.Dict[str, int] = {}
            parity_checked = parity_ok = 0
            for uid, idx in uid_to_idx.items():
                fr = router.finished.get(uid)
                assert fr is not None, f"accepted stream {uid} vanished"
                statuses[fr.status] = statuses.get(fr.status, 0) + 1
                assert fr.status == "ok", (
                    f"accepted stream {uid} dropped with status "
                    f"{fr.status!r}"
                )
                parity_checked += 1
                if np.array_equal(np.asarray(fr.tokens), ref_tokens[idx]):
                    parity_ok += 1
            assert parity_ok == parity_checked, (
                f"greedy parity broke on {parity_checked - parity_ok} "
                "stream(s) vs the fault-free in-process reference"
            )
            assert sum(fired.values()) >= min(1, len(armed)), (
                "no armed fault fired"
            )
            transport = router.transport_stats()
            if fired.get("proc_kill9"):
                assert router.proc_failovers >= 1, (
                    "kill -9 fired but the wire never reported the death"
                )
                assert router.failed_over_streams >= 1, (
                    "kill -9 fired with no accepted streams to fail over "
                    "— the gate proved nothing"
                )
            if fired.get("conn_drop"):
                assert transport["reconnects"] >= 1, (
                    "connection dropped but no RPC ever reconnected"
                )
            if fired.get("wire_corrupt"):
                assert transport["corrupt_frames"] >= 1, (
                    "frame corruption armed but the checksum never "
                    "rejected one"
                )
            if fired.get("wire_stall"):
                assert transport["deadline_expiries"] >= 1, (
                    "stall armed but no RPC deadline ever expired"
                )

            # -- recompile pin: the router process compiled nothing -----
            compiles_after = ServeEngine.compile_stats()
            assert compiles_after == compiles_before, (
                f"router process compiled programs for proc replicas: "
                f"{compiles_before} -> {compiles_after}"
            )

            return {
                "mode": "serve",
                "fault_plan": fault_plan,
                "faults_fired": fired,
                "n_requests": n_requests,
                "statuses": statuses,
                "shed": router.router_shed,
                "timeouts": sum(e.timeouts for e in router.engines),
                "cancelled": sum(e.cancelled for e in router.engines),
                "decode_kills": sum(e.decode_kills for e in router.engines),
                "preemptions": sum(e.preemptions for e in router.engines),
                "poisoned": 0,
                "parity_checked": parity_checked,
                "parity_ok": parity_ok,
                "pages_conserved": True,
                "prefix_cache": True,
                "prefix_reclaimed": sum(
                    e.prefix_evictions for e in router.engines
                ),
                "prefix_hit_rate": router.prefix_hit_rate(),
                "fleet_size": len(router.engines),
                "alive": sum(router.alive),
                "failovers": router.failovers,
                "failed_over_streams": router.failed_over_streams,
                "dropped_streams": 0,
                "spill": router.spill.stats(),
                "procs": True,
                "proc_failovers": router.proc_failovers,
                "worker_pids": [rep.pid for rep in replicas],
                "transport": transport,
                "router_compiles_delta": 0,
            }

        return _run_scenario(obs, trace_dir, body)
    finally:
        faults.clear()
        for proc, _port in procs:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass


# -- model-ops scenarios (sampling/ops.py) ---------------------------------


def _verified_swap_weights(cfg, seed: int, root_dir: str):
    """Fresh weights through the REAL verified-checkpoint path: init at a
    different seed, save with the manifest-stamping CheckpointManager,
    restore via `restore_for_sampling`'s latest-verified-step path — the
    exact pipeline a production deploy would hand the hot-swap. Returns
    (restored params, step, "<step>:<sha12>" weights version)."""
    import os
    import types

    import jax

    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.sampling.engine import restore_for_sampling
    from midgpt_tpu.training.checkpoint import CheckpointManager

    ckpt_dir = os.path.join(root_dir, "swap_ckpt")
    mgr = CheckpointManager(ckpt_dir, save_interval_steps=1)
    mgr.save(7, {"params": GPT.init(cfg, jax.random.PRNGKey(seed + 101))},
             force=True)
    mgr.wait()
    version = mgr.weights_version(7)
    mgr.close()
    assert version is not None, "manifest missing after save barrier"
    # fsdp_min_size past any leaf size -> fully replicated shardings, which
    # stage_hot_swap then re-homes onto the live engine's own layout.
    shim = types.SimpleNamespace(
        model_config=cfg, fsdp_min_size=1 << 60, param_dtype="float32"
    )
    restored, step = restore_for_sampling(ckpt_dir, shim)
    return restored, step, version


def _run_hot_swap_chaos(
    fault_plan: str, *, seed: int, n_requests: int,
    trace_dir: tp.Optional[str],
) -> tp.Dict[str, tp.Any]:
    """Blue/green weight flip mid-trace (module docstring): three passes —
    fault-free on the OLD weights, fault-free on the NEW (restored)
    weights, then the fault pass — and a per-stream parity check against
    whichever side of the flip served it (`served_uids_at_flip`). Pinned
    by tests/test_chaos_serve.py::
    test_chaos_hot_swap_mid_decode_blue_green_parity."""
    cfg, params_old = _tiny_model(seed)
    root = trace_dir or tempfile.mkdtemp(prefix="midgpt_chaos_swap_")
    params_new, step, version = _verified_swap_weights(cfg, seed, root)
    trace = _trace(cfg, seed + 1, n_requests)

    ref_old = _reference_pass(cfg, params_old, trace)
    ref_new = _reference_pass(cfg, params_new, trace)
    eng, obs, armed = _armed_engine(cfg, params_old, fault_plan)
    eng.swap_source = lambda: {
        "params": params_new, "version": version, "config": cfg,
    }

    def body() -> tp.Dict[str, tp.Any]:
        uid_to_idx = _run_trickle(eng, trace)
        fired = faults.fired_counts()
        faults.clear()
        assert sum(fired.values()) >= min(1, len(armed)), "no armed fault fired"
        assert eng.hot_swaps == 1, f"swap never flipped ({eng.hot_swaps=})"
        assert eng.weights_version == version, (
            f"weights_version {eng.weights_version!r} != {version!r}"
        )
        _assert_drained_conserved(eng)

        swap = eng.swap_history[0]
        old_uids = set(swap["served_uids_at_flip"])
        statuses: tp.Dict[str, int] = {}
        parity = {"old": 0, "new": 0}
        for uid, idx in uid_to_idx.items():
            fr = eng.finished.get(uid)
            assert fr is not None, f"request {uid} dropped across the flip"
            statuses[fr.status] = statuses.get(fr.status, 0) + 1
            assert fr.status == "ok", (
                f"request {uid} degraded to {fr.status!r} — a hot swap must "
                "drop zero streams"
            )
            side = "old" if uid in old_uids else "new"
            want = (ref_old if side == "old" else ref_new)[idx]
            assert np.array_equal(np.asarray(fr.tokens), want), (
                f"greedy parity broke for request {uid} ({side}-weights side "
                "of the flip)"
            )
            parity[side] += 1
        assert parity["old"] and parity["new"], (
            f"flip landed outside the trace ({parity}) — tune the fault round"
        )
        return {
            "mode": "serve",
            "fault_plan": fault_plan,
            "faults_fired": fired,
            "n_requests": n_requests,
            "statuses": statuses,
            "weights_version": eng.weights_version,
            "checkpoint_step": step,
            "swap": {
                "staged_round": swap["staged_round"],
                "flip_round": swap["flip_round"],
                "in_flight_at_stage": len(swap["in_flight_at_stage"]),
                "swap_latency_s": swap["swap_latency_s"],
            },
            "parity_old_side": parity["old"],
            "parity_new_side": parity["new"],
            "dropped": 0,
            "pages_conserved": True,
        }

    return _run_scenario(obs, trace_dir, body)


def _run_pool_resize_chaos(
    fault_plan: str, *, seed: int, n_requests: int,
    trace_dir: tp.Optional[str],
) -> tp.Dict[str, tp.Any]:
    """Grow-then-shrink pool resize mid-trace (module docstring), on an
    int8 cache so the scale side buffers must migrate with their pages:
    conservation at every boundary and EVERY stream bit-identical to the
    no-resize reference — a resize affects nobody."""
    import jax.numpy as jnp

    cfg, params = _tiny_model(seed)
    trace = _trace(cfg, seed + 1, n_requests)

    ref_tokens = _reference_pass(cfg, params, trace, cache_dtype=jnp.int8)
    eng, obs, armed = _armed_engine(cfg, params, fault_plan,
                                    cache_dtype=jnp.int8)
    eng.resize_plan = list(RESIZE_TARGETS)

    def body() -> tp.Dict[str, tp.Any]:
        # Trickle arrivals: the grow-then-shrink plan spans two fault
        # rounds, so the trace must still be live at BOTH (upfront
        # submission can drain a small trace before the shrink round).
        uid_to_idx = _run_trickle(eng, trace)
        fired = faults.fired_counts()
        faults.clear()
        n_fired = sum(fired.values())
        assert n_fired >= min(1, len(armed)), "no armed fault fired"
        # resize_pool asserts conservation before AND after each migration;
        # this is the post-drain re-check.
        assert eng.resizes == n_fired, (
            f"{n_fired} pool_resize firings but {eng.resizes} resizes"
        )
        _assert_drained_conserved(eng)
        assert eng.cache.quantized and eng.cache.k_scale is not None

        statuses: tp.Dict[str, int] = {}
        parity_ok = 0
        for uid, idx in uid_to_idx.items():
            fr = eng.finished.get(uid)
            assert fr is not None, f"request {uid} dropped across a resize"
            statuses[fr.status] = statuses.get(fr.status, 0) + 1
            assert np.array_equal(np.asarray(fr.tokens), ref_tokens[idx]), (
                f"greedy parity broke for request {uid} across a live resize"
            )
            parity_ok += 1
        return {
            "mode": "serve",
            "fault_plan": fault_plan,
            "faults_fired": fired,
            "n_requests": n_requests,
            "statuses": statuses,
            "cache_dtype": "int8",
            "resizes": eng.resize_history,
            "pages_migrated": sum(
                r["pages_migrated"] for r in eng.resize_history
            ),
            "final_num_pages": eng.allocator.num_pages,
            "parity_checked": parity_ok,
            "parity_ok": parity_ok,
            "pages_conserved": True,
        }

    return _run_scenario(obs, trace_dir, body)
