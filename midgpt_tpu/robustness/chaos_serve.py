"""Serving chaos scenarios: inject one of the serving fault kinds into a
seeded trace and assert the engine DEGRADES instead of breaking.

The training chaos harness (tools/chaos_run.py + robustness/faults.py)
proves recovery end to end by running the real supervisor against injected
failures. This module is the serving twin: `run_serving_chaos` runs the
same seeded request trace twice — once fault-free for reference, once with
a fault plan armed — and checks the three degradation invariants the chaos
gate (tests/test_chaos_serve.py, `chaos_run.py --serve`) enforces:

  1. **Alive** — the engine (and, for client faults, the async front door)
     finishes the trace; no fault kind may crash the process.
  2. **Conserved** — every pool page is back on the free list afterwards
     (`free_count == num_pages - 1`), whatever was shed/killed/poisoned.
  3. **Isolated** — greedy token streams of UNAFFECTED requests are
     bit-identical to the fault-free run (greedy determinism pin,
     tests/test_chaos_serve.py). "Affected" is fault-specific and
     engine-reported: `poisoned_uids` for poisoned_page, non-"ok" statuses
     for sheds/timeouts/cancels. kill_mid_decode affects NOBODY — its
     recovery is recompute preemption, which is parity-preserving — so
     there every request must match.

Faults are deterministic for a seeded trace: kill_mid_decode/poisoned_page
key on the engine's round counter (`kill_mid_decode@7` = round 7),
slow_client keys on the victim uid, submit_storm keys on the arrival index
at which the burst lands. This module is import-light glue; the faults it
arms live in the one registry every chaos path shares.
"""

from __future__ import annotations

import asyncio
import typing as tp

import numpy as np

from midgpt_tpu.obs import Observability
from midgpt_tpu.robustness import faults

# Storm burst: how many clone requests the submit_storm fault slams into
# the engine at its arrival index (sized to overrun the default backlog
# budget below several times over).
STORM_SIZE = 8
# Backlog budget armed for storm scenarios — small enough that the burst
# MUST shed, big enough that the base trace admits.
STORM_BACKLOG_PAGES = 24


def _tiny_model(seed: int):
    import jax

    from midgpt_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(
        block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32
    )
    return cfg, GPT.init(cfg, jax.random.PRNGKey(seed))


def _trace(cfg, seed: int, n_requests: int, shared: bool = False):
    rng = np.random.default_rng(seed)
    out = []
    # `shared` (the evict_shared_prefix scenario): template-heavy traffic —
    # two 16-token system prompts with short unique tails — so the prefix
    # trie holds HOT shared nodes for the fault to flush. Drawn only in
    # shared mode: the plain scenarios' seeded traces must stay the exact
    # RNG stream their step-keyed fault plans were tuned against.
    templates = [
        rng.integers(0, cfg.vocab_size, 16).astype(np.int32) for _ in range(2)
    ] if shared else []
    for i in range(n_requests):
        if shared:
            tail = rng.integers(
                0, cfg.vocab_size, int(rng.integers(2, 6))
            ).astype(np.int32)
            prompt = np.concatenate([templates[i % 2], tail])
            m = int(rng.integers(6, 16))
        else:
            # draw order (t0, m, prompt) is load-bearing: the plain
            # scenarios' step-keyed fault plans were tuned against it
            t0 = int(rng.integers(4, 24))
            m = int(rng.integers(6, 16))
            prompt = rng.integers(0, cfg.vocab_size, t0).astype(np.int32)
        out.append((prompt, m))
    return out


def _engine(
    cfg, params, *, max_backlog_pages=None, clock=None, prefix=False, obs=None
):
    import jax.numpy as jnp

    from midgpt_tpu.sampling.serve import ServeEngine

    kw: tp.Dict[str, tp.Any] = {}
    if clock is not None:
        kw["clock"] = clock
    if obs is not None:
        kw["obs"] = obs
    return ServeEngine(
        cfg,
        params,
        max_slots=3,
        page_size=8,
        # NOT 25: the pool size is a program-key dim, and the recompile pin
        # (tests/test_recompile_pins.py) counts compiles of the 25-page f32
        # program set from a pristine baseline — chaos runs in the same
        # pytest process must not pre-warm that exact geometry.
        num_pages=29,
        prefill_chunk=16,
        decode_chunk=4,
        temperature=0.0,
        cache_dtype=jnp.float32,
        max_backlog_pages=max_backlog_pages,
        prefix_cache=prefix,
        **kw,
    )


def _run_plain(eng, trace, storm: bool):
    """Drive the engine synchronously. Returns (uid -> trace index,
    n_storm_shed). With `storm`, each arrival consults the submit_storm
    fault (step = arrival index) and, when it fires, slams STORM_SIZE
    clones of that request in at once — the admitted ones compete for the
    pool like real duplicate traffic, the rest must shed."""
    from midgpt_tpu.sampling.serve import BackpressureError

    uid_to_idx: tp.Dict[int, int] = {}
    storm_shed = 0
    for idx, (prompt, m) in enumerate(trace):
        if storm and faults.should_fire("submit_storm", step=idx):
            for _ in range(STORM_SIZE):
                try:
                    eng.submit(prompt, m)  # clones: excluded from parity
                except BackpressureError:
                    storm_shed += 1
        try:
            uid_to_idx[eng.submit(prompt, m)] = idx
        except BackpressureError:
            storm_shed += 1
    eng.run()
    return uid_to_idx, storm_shed


def _run_server(eng, trace):
    """Drive the engine through the async front door, one consumer task
    per request, collecting delivered tokens (what a client actually saw —
    the thing slow-client sheds must not corrupt for anyone else)."""
    from midgpt_tpu.sampling.server import AsyncServeServer

    delivered: tp.Dict[int, tp.List[int]] = {}
    uid_to_idx: tp.Dict[int, int] = {}

    async def main():
        server = AsyncServeServer(
            eng, max_buffered_tokens=4, submit_retries=1, idle_poll_s=0.001
        )
        driver = asyncio.create_task(server.run())

        async def consume(uid):
            delivered[uid] = []
            async for tok in server.stream(uid):
                delivered[uid].append(tok)

        consumers = []
        for idx, (prompt, m) in enumerate(trace):
            uid = await server.submit(prompt, m)
            uid_to_idx[uid] = idx
            consumers.append(asyncio.create_task(consume(uid)))
        await asyncio.gather(*consumers)
        await server.drain()
        await driver

    asyncio.run(main())
    return uid_to_idx, delivered


def run_serving_chaos(
    fault_plan: str, *, seed: int = 0, n_requests: int = 5,
    trace_dir: tp.Optional[str] = None,
) -> tp.Dict[str, tp.Any]:
    """Run the scenario (module docstring); returns the summary dict that
    `chaos_run.py --serve` emits as its JSON line. Raises AssertionError
    when a degradation invariant breaks — that IS the chaos verdict.

    With `trace_dir`, the fault pass runs under a flight recorder
    (midgpt_tpu/obs/) and dumps it there as a Chrome trace
    (`flight_recorder.json` + `.prom` metrics) — the serving postmortem
    artifact, written even when an invariant assertion fails."""
    cfg, params = _tiny_model(seed)
    uses_server = "slow_client" in fault_plan
    uses_storm = "submit_storm" in fault_plan
    # The trie-flush fault needs a trie: both passes run with the prefix
    # cache ON over a template-shared trace, so the reference pass also
    # proves the cache itself is parity-clean before the flush is judged.
    uses_prefix = "evict_shared_prefix" in fault_plan
    trace = _trace(cfg, seed + 1, n_requests, shared=uses_prefix)

    # Fault-free reference pass (also warms every jit shape, so the fault
    # pass's timings/timeouts cannot hinge on compile stalls).
    faults.clear()
    ref = _engine(cfg, params, prefix=uses_prefix)
    ref_uids, _ = _run_plain(ref, trace, storm=False)
    ref_tokens = {
        idx: np.asarray(ref.finished[uid].tokens)
        for uid, idx in ref_uids.items()
    }

    faults.clear()
    armed = faults.activate_plan(fault_plan)
    # Only the FAULT pass is recorded: the reference pass must stay the
    # untouched parity baseline, and the postmortem reader wants the trace
    # of the run that went wrong, not the rehearsal.
    obs = None if trace_dir is None else Observability()
    eng = _engine(
        cfg, params,
        max_backlog_pages=STORM_BACKLOG_PAGES if uses_storm else None,
        prefix=uses_prefix,
        obs=obs,
    )
    delivered: tp.Optional[tp.Dict[int, tp.List[int]]] = None
    storm_shed = 0
    try:
        if uses_server:
            uid_to_idx, delivered = _run_server(eng, trace)
        else:
            uid_to_idx, storm_shed = _run_plain(eng, trace, storm=uses_storm)
    finally:
        trace_path = None if obs is None else obs.dump(trace_dir)
    fired = faults.fired_counts()
    faults.clear()

    # -- invariant 2: page conservation + engine still serviceable -------
    # With the prefix cache on, pages the trie retains for future matches
    # are accounted alongside the free list (every one of them must be
    # unreferenced once the engine drains — a dangling refcount would be a
    # leak in waiting).
    assert eng.idle, "engine left work behind"
    trie_pages = 0 if eng.prefix_cache is None else eng.prefix_cache.page_count()
    conserved = (
        eng.allocator.free_count + trie_pages == eng.allocator.num_pages - 1
    )
    assert conserved, (
        f"page leak: {eng.allocator.free_count} free + {trie_pages} trie of "
        f"{eng.allocator.num_pages - 1} allocatable"
    )
    if eng.prefix_cache is not None:
        dangling = eng.prefix_cache.referenced_page_count()
        assert dangling == 0, f"{dangling} trie refcount(s) outlived the drain"

    # -- invariant 3: unaffected greedy streams are bit-identical --------
    affected = set(eng.poisoned_uids)
    statuses: tp.Dict[str, int] = {}
    parity_checked = parity_ok = 0
    for uid, idx in uid_to_idx.items():
        fr = eng.finished.get(uid)
        assert fr is not None, f"request {uid} vanished"
        statuses[fr.status] = statuses.get(fr.status, 0) + 1
        if fr.status != "ok":
            affected.add(uid)  # shed/timeout/slow_client: partial by design
        if uid in affected:
            continue
        parity_checked += 1
        if np.array_equal(np.asarray(fr.tokens), ref_tokens[idx]):
            parity_ok += 1
        if delivered is not None:
            # What the client consumed must be a prefix of the reference
            # generation — streaming may trail the engine, never diverge.
            prompt_len = len(trace[idx][0])
            got = np.asarray(delivered[uid], np.int32)
            want = ref_tokens[idx][prompt_len:prompt_len + len(got)]
            assert np.array_equal(got, want), (
                f"delivered stream diverged for request {uid}"
            )
    assert parity_ok == parity_checked, (
        f"greedy parity broke on {parity_checked - parity_ok} unaffected "
        f"request(s)"
    )
    assert sum(fired.values()) >= min(1, len(armed)), "no armed fault fired"

    return {
        "mode": "serve",
        "fault_plan": fault_plan,
        "faults_fired": fired,
        "n_requests": n_requests,
        "statuses": statuses,
        "shed": eng.shed + storm_shed,
        "timeouts": eng.timeouts,
        "cancelled": eng.cancelled,
        "decode_kills": eng.decode_kills,
        "preemptions": eng.preemptions,
        "poisoned": len(eng.poisoned_uids),
        "parity_checked": parity_checked,
        "parity_ok": parity_ok,
        "pages_conserved": conserved,
        "prefix_cache": eng.prefix_cache is not None,
        "prefix_reclaimed": eng.prefix_evictions,
        "prefix_hit_rate": eng.prefix_stats()["hit_rate"],
        "trace": trace_path,
    }
