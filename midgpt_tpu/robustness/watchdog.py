"""Hung-step watchdog: a bounded deadline around device syncs.

A wedged dispatch — dead TPU tunnel, stuck collective, device restart —
blocks the *sync point* (`float(arr)` / `np.asarray(arr)`, the only forces
that work through the axon tunnel), and a Python thread cannot interrupt a
main thread parked inside that native wait. So the guard inverts control:
`StepWatchdog.sync(fn)` runs the sync in a fresh daemon worker thread and
bounds the main thread's wait on it. If the worker doesn't land inside
`deadline_s` (measured on the injected clock), the watchdog

  1. dumps the process-global flight recorder (`flight_recorder.json` +
     `.prom`) into `rundir` for the postmortem,
  2. calls the optional `on_expire(step, waited_s)` hook (the supervisor
     ledger's HUNG mark rides this),
  3. escalates: `escalate="raise"` raises StepHangError in the *caller* —
     the supervisor treats it like a divergence and restarts from the last
     verified checkpoint; `escalate="exit"` hard-exits with EXIT_CODE for
     a cluster layer that restarts whole processes (a wedged native wait
     cannot be unwound, so sys.exit would just hang in atexit).

The abandoned worker is a daemon thread: it either lands late (into a box
nothing reads anymore — each sync gets a fresh one) or stays parked until
process exit without blocking it.

Cost discipline: `deadline_s <= 0` disables the guard and `sync` degrades
to a plain call — no thread, no clock read, nothing. The watchdog is
host-side only and JAX-free: arming it compiles zero XLA programs and adds
zero jit statics (pinned with the obs-off pin in tests/test_robustness.py).
Clock-injected per the observability discipline (graftcheck GC012): the
defaults reference `time.monotonic` but the module never *calls* into the
`time` module, so deadline arithmetic is testable on a fake clock.
"""

from __future__ import annotations

import os
import threading
import time
import typing as tp

from midgpt_tpu.robustness.errors import StepHangError

# Distinct from ordinary failure exits so a supervisor/cluster layer can
# tell "hung device" from "crashed python" without parsing logs.
EXIT_CODE = 17


class StepWatchdog:
    """Deadline guard for device syncs (module docstring has the model).

    One instance guards one run; `sync` may be called from exactly one
    thread at a time (the train/engine loop — there is one sync point per
    step by design)."""

    def __init__(
        self,
        deadline_s: float,
        *,
        escalate: str = "raise",
        rundir: str = "",
        clock: tp.Callable[[], float] = time.monotonic,
        poll_s: float = 0.05,
        on_expire: tp.Optional[tp.Callable[[tp.Optional[int], float], None]] = None,
    ):
        if escalate not in ("raise", "exit"):
            raise ValueError(
                f"unknown escalate {escalate!r} ('raise' or 'exit')"
            )
        self.deadline_s = deadline_s
        self.escalate = escalate
        self.rundir = rundir
        self.poll_s = poll_s
        self.on_expire = on_expire
        self._clock = clock
        self.syncs = 0
        self.expiries = 0

    @property
    def enabled(self) -> bool:
        return self.deadline_s > 0

    def sync(
        self,
        fn: tp.Callable[[], tp.Any],
        *,
        step: tp.Optional[int] = None,
        label: str = "step",
    ) -> tp.Any:
        """Run `fn` (a device sync) under the deadline; return its result.

        Disabled watchdog: a plain call, zero machinery. An exception from
        `fn` itself (e.g. the divergence guard's float() of a NaN carrier
        raising downstream) propagates unchanged."""
        if not self.enabled:
            return fn()
        self.syncs += 1
        box: tp.Dict[str, tp.Any] = {}
        landed = threading.Event()

        def _worker() -> None:
            try:
                box["value"] = fn()
            except BaseException as e:  # propagate to the caller, not the log
                box["error"] = e
            finally:
                landed.set()

        t0 = self._clock()
        threading.Thread(
            target=_worker, daemon=True, name=f"midgpt-watchdog-{label}"
        ).start()
        while not landed.wait(self.poll_s):
            waited = self._clock() - t0
            if waited >= self.deadline_s:
                return self._expire(step, label, waited)
        if "error" in box:
            raise box["error"]
        return box.get("value")

    def _expire(self, step: tp.Optional[int], label: str, waited: float):
        self.expiries += 1
        # Postmortem artifacts FIRST — the raise/exit below may be the last
        # thing this process does. Deferred import keeps module import free.
        from midgpt_tpu.obs import dump_flight_recorder, flight_recorder

        flight_recorder().tracer.instant(
            "watchdog.expired", "watchdog", "train",
            args={
                "step": step, "label": label,
                "deadline_s": self.deadline_s,
                "waited_s": round(waited, 3),
            },
        )
        if self.rundir and not self.rundir.startswith("gs://"):
            dump_flight_recorder(self.rundir)
        if self.on_expire is not None:
            self.on_expire(step, waited)
        msg = (
            f"device sync '{label}' did not land within "
            f"{self.deadline_s:g}s (waited {waited:.3f}s"
            + (f" at step {step}" if step is not None else "")
            + ") — wedged dispatch or dead device tunnel. Flight recorder "
            + (f"dumped to {self.rundir}." if self.rundir else "not dumped "
               "(no rundir).")
        )
        if self.escalate == "exit":
            print(f"watchdog: {msg} hard-exiting {EXIT_CODE}.", flush=True)
            os._exit(EXIT_CODE)
        raise StepHangError(
            msg, step=step, waited_s=waited, rundir=self.rundir
        )
