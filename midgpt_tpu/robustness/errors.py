"""Exception types shared by the training loop, checkpointing, and the run
supervisor. Deliberately dependency-free (no jax import) so every layer can
import them without ordering constraints.
"""

from __future__ import annotations

import typing as tp


class DivergenceError(FloatingPointError):
    """Training produced a non-finite loss/grad (the sticky health carrier).

    Subclasses FloatingPointError so existing callers that catch/match the
    pre-supervisor divergence guard keep working; carries the structured
    fields the supervisor needs to roll back and skip the poisoned window.

    `step` is the loop iteration at which the poisoning was *noticed* (a log
    or save sync); the actual bad batch lies in (last_good_step, step] —
    stickiness guarantees it cannot be earlier than the last verified save.
    """

    def __init__(
        self,
        message: str,
        *,
        step: int,
        last_good_step: tp.Optional[int] = None,
        rundir: str = "",
    ):
        super().__init__(message)
        self.step = step
        self.last_good_step = last_good_step
        self.rundir = rundir


class StepHangError(RuntimeError):
    """A watchdog-guarded device sync did not land inside its deadline
    (robustness/watchdog.py) — the tunnel-down / wedged-dispatch failure
    mode that otherwise stalls a run forever (the r14/r18 bench hangs).

    `step` is the loop iteration whose sync was armed (None for
    non-training guards, e.g. the bench backend probe); `waited_s` is how
    long the watchdog's clock says it waited before giving up, which is
    >= the configured deadline by at most one poll interval.
    """

    def __init__(
        self,
        message: str,
        *,
        step: tp.Optional[int] = None,
        waited_s: float = 0.0,
        rundir: str = "",
    ):
        super().__init__(message)
        self.step = step
        self.waited_s = waited_s
        self.rundir = rundir


class CheckpointCorruptError(ValueError):
    """A checkpoint failed its manifest verification (missing/truncated/
    bit-flipped item). `problems` lists one human-readable line per
    mismatch."""

    def __init__(self, message: str, *, step: int, problems: tp.Sequence[str] = ()):
        super().__init__(message)
        self.step = step
        self.problems = list(problems)


class CheckpointWriteError(OSError):
    """A checkpoint save still failed after the configured retry budget.

    `step` is the step whose save was abandoned, `attempts` the retry
    budget that was exhausted (training/checkpoint.py `write_retries`),
    and `directory` the checkpoint root — the fields the supervisor's
    emergency-save path and the chaos gate (`ckpt_enospc*2`) report
    without re-parsing the message.
    """

    def __init__(
        self,
        message: str,
        *,
        step: int,
        attempts: int,
        directory: str = "",
    ):
        super().__init__(message)
        self.step = step
        self.attempts = attempts
        self.directory = directory


class SimulatedPreemption(BaseException):
    """Raised by the `kill_mid_save` fault to model the process dying between
    the TensorStore write and the manifest commit.

    Subclasses BaseException (like KeyboardInterrupt) on purpose: a real
    SIGKILL is not catchable, so no `except Exception` recovery path may
    swallow its simulation either — only the fault-injection tests catch it
    explicitly.
    """
