"""Bounded exponential retry-with-backoff, shared by every transient-
failure path in the repo.

PR 3 inlined the schedule in the checkpoint write retry
(`training/checkpoint.py`): `base * 2**attempt` between `retries` attempts.
The serving front door needs the identical discipline for BackpressureError
(`sampling/server.py` — but awaited, not slept), so the schedule and the
sync driver live here once. Keeping the schedule a plain iterator is what
lets the async caller reuse it: it awaits `asyncio.sleep(delay)` where the
sync caller calls `sleep(delay)`.

Deliberately dependency-free (no jax import), like robustness/errors.py.
"""

from __future__ import annotations

import time
import typing as tp

T = tp.TypeVar("T")


def backoff_delays(retries: int, base_s: float) -> tp.Iterator[float]:
    """The delays BETWEEN `retries` attempts: base, 2*base, 4*base, ...
    (`retries - 1` entries — no sleep after the last failure; the caller
    raises instead)."""
    for attempt in range(max(retries - 1, 0)):
        yield base_s * (2**attempt)


def retry_with_backoff(
    fn: tp.Callable[[], T],
    *,
    retries: int,
    base_s: float,
    retry_on: tp.Tuple[tp.Type[BaseException], ...],
    sleep: tp.Callable[[float], None] = time.sleep,
    should_retry: tp.Optional[tp.Callable[[BaseException], bool]] = None,
) -> T:
    """Call `fn` up to `retries` times, sleeping the exponential schedule
    between attempts. Only `retry_on` exceptions are absorbed — and only
    while `should_retry(exc)` (when given) agrees, so callers can stop
    early on errors that waiting cannot fix (e.g. a non-`retryable`
    BackpressureError). The final failure re-raises the last exception
    unchanged: the caller owns its error type (checkpoint.py wraps it in
    CheckpointWriteError)."""
    if retries < 1:
        raise ValueError(f"retries must be >= 1, got {retries}")
    delays = backoff_delays(retries, base_s)
    while True:
        try:
            return fn()
        except retry_on as e:
            if should_retry is not None and not should_retry(e):
                raise
            delay = next(delays, None)
            if delay is None:
                raise
            sleep(delay)
