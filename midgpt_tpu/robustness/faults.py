"""Fault-injection registry: named, bounded failures for recovery testing.

A fault is (kind, optional step, remaining firings). Production code calls
`should_fire(kind, step=...)` at the few places a real failure would strike;
with an empty registry (the default, always) that is a list scan over
nothing — no fault machinery is reachable unless a plan was activated.

Kinds (each exercised end to end by tests/test_robustness.py and drivable
via tools/chaos_run.py):

  nan_grad           poison the train step's sticky loss carrier at data
                     step k — models a bad batch NaN-ing the gradients. The
                     key is the DATA step index (itr + data_step_offset), so
                     a supervisor rollback that skips the window also skips
                     the fault, exactly like a real poisoned shard.
  ckpt_io_error      raise IOError from the next N checkpoint-save attempts
                     (a transient TensorStore/filesystem failure) — the
                     manager's retry/backoff must absorb it.
  kill_mid_save      after the TensorStore write lands, truncate one item
                     and raise SimulatedPreemption before the manifest is
                     written — models SIGKILL between write and commit.
  truncate_ckpt_item truncate one item file AFTER the manifest committed —
                     models later corruption (bit rot, partial copy);
                     verification must catch it at restore/resume time.
  preempt            set the preemption flag at data step k, as if SIGTERM
                     arrived mid-step — drives the emergency-save path
                     without depending on signal-delivery timing.
  hang_step          the step's device sync at data step k never lands (the
                     tunnel-down / wedged-dispatch failure): the guarded
                     float() blocks on a never-set event, so only the
                     hung-step watchdog (robustness/watchdog.py) can end
                     the wait — dump, ledger HUNG mark, escalation.
  ckpt_enospc        the next N checkpoint-save attempts fail with
                     OSError(ENOSPC) after partial bytes land in the step
                     directory — disk exhaustion mid-write. The atomic
                     manifest commit must leave no partial step visible to
                     latest_verified_step, the retry/backoff path must
                     recover when space frees, and verified-only GC must
                     never delete the last good checkpoint over it.
  resume_reshard     request a preemption exit at data step k so the driver
                     (tools/chaos_run.py) can restart the run on a DIFFERENT
                     device count — the cross-mesh resharding resume path
                     (train restores the checkpoint through the new mesh's
                     shardings; supervise checks on_resume_mesh).

Serving kinds (hooked in sampling/serve.py `ServeEngine.step`, the async
front door sampling/server.py, and the chaos scenario driver
robustness/chaos_serve.py; the step key is the engine's ROUND counter or —
submit_storm — the workload's arrival index, so a seeded trace makes every
firing deterministic):

  kill_mid_decode    the round's decode/spec dispatch dies before its
                     tokens land (device restart, tunnel drop); every
                     decode-ready slot is recompute-preempted and the
                     token streams must come out identical to an
                     unfaulted run.
  kill_overlapped_round  the IN-FLIGHT round N+1 dispatch dies while round
                     N's host work runs (overlap="double" engines keep two
                     rounds in the pipe — sampling/serve.py
                     `_step_overlapped`): the unsettled handle is dropped
                     without forcing, its slots recompute-preempt, the
                     watchdog still bounds a hung settle, and bystander
                     streams plus the page pool must come through
                     bit-identical / conserved (chaos_serve.py gate).
  poisoned_page      corrupt one live slot's first pool page in place
                     (HBM damage); page isolation must keep every OTHER
                     slot's stream bit-identical while the engine keeps
                     serving.
  slow_client        a streaming client stops draining its token queue;
                     the server's bounded per-client buffer must shed
                     exactly that client (status "slow_client") without
                     stalling the engine or its neighbors.
  submit_storm       a burst of simultaneous submissions beyond the
                     backpressure budget; admission must shed the excess
                     (BackpressureError) and serve the admitted rest to
                     completion.
  evict_shared_prefix  force-reclaim every unreferenced prefix-cache trie
                     page at once (a pressure spike flushing hot shared
                     nodes, LRU protection ignored); referenced entries
                     must survive — a shared node is never evicted out
                     from under a live reader — so live streams stay
                     bit-identical while later requests just re-prefill
                     and re-populate the trie, with pages + refcounts
                     conserved through the flush.
  hot_swap_mid_decode  stage a blue/green weight swap mid-trace (payload
                     from the engine's `swap_source` hook): admissions
                     pause, in-flight streams finish on the old weights
                     bit-exactly, queued arrivals take the new ones, zero
                     streams dropped, pool + trie conserved across the
                     flip (sampling/ops.py).
  pool_resize        live-resize the paged KV pool to the next target on
                     the engine's `resize_plan` (grow then shrink in the
                     chaos gate): resident pages migrate through the
                     adoption scatter with int8 scales, conservation
                     holds at every boundary, and live streams stay
                     greedy-bit-exact vs a no-resize run.

Fleet kinds (hooked in sampling/fleet.py `FleetRouter.step`, keyed on the
ROUTER round counter; scenarios in robustness/chaos_serve.py):

  engine_crash       kill the alive replica holding the most accepted
                     streams mid-trace: its finished results are
                     harvested, every accepted-but-unfinished stream
                     fails over to survivors through the bounded handoff
                     queue, and the replays must come out greedy
                     bit-identical to a fault-free pass — zero dropped
                     accepted streams, cross-tier conservation intact.
  handoff_stall      wedge the host page transport: the spill tier's next
                     consult that WOULD return pages refuses instead
                     (stays armed until one would), and the admission
                     falls back to plain re-prefill — slower, never
                     wrong, streams bit-identical.
  spill_corrupt      flip a byte in the most recently spilled host-RAM
                     page without updating its checksum (stays armed
                     until something is resident): the take-side crc32
                     verification must discard it and re-prefill — a
                     corrupt spill page never yields a token mismatch.

Cross-process fleet kinds (hooked in sampling/fleet.py
`FleetRouter._fire_proc_faults`, keyed on the ROUTER round counter;
targets the busiest alive ProcReplica — sampling/fleet_proc.py; scenario
in robustness/chaos_serve.py `_run_proc_fleet_chaos`):

  proc_kill9         SIGKILL the busiest worker PROCESS mid-decode — no
                     drain, no flush, no goodbye. The router must detect
                     the death purely through the wire (step RPCs fail
                     with ReplicaGoneError until the consecutive-failure
                     health check fires), then run the exact engine_crash
                     failover: zero dropped accepted streams, greedy
                     bit-parity on the survivor, router + spill ledgers
                     closing across the process boundary.
  conn_drop          abruptly close the live router->worker connection;
                     the transport must reconnect transparently on the
                     next RPC (counted `reconnects`) with zero stream
                     impact — the worker keeps its state, only the socket
                     died.
  wire_corrupt       flip a byte in the next received frame BEFORE
                     verification: the crc32 check must reject it
                     pre-decode (WireFrameError, counted
                     `corrupt_frames`), drop the desynced connection, and
                     recover by retrying the RPC on a fresh one — corrupt
                     bytes never reach a decode, mirroring spill_corrupt.
  wire_stall         the next RPC's response never lands inside its
                     deadline (wedged worker / dead tunnel): the deadline
                     must expire into a structured TransportError
                     (counted `deadline_expiries`) and the bounded
                     backoff retry must absorb it.

Activation: programmatic (`activate(...)`), or a plan string from config
(`ExperimentConfig.fault_plan`) / the MIDGPT_FAULTS env var, parsed by
`activate_plan`: comma-separated `kind[@step][*times]`, e.g.
`"nan_grad@12,ckpt_io_error*2"`. The supervisor activates the configured
plan exactly once per supervised run — NOT once per restart attempt — so a
consumed fault stays consumed across rollbacks.
"""

from __future__ import annotations

import dataclasses
import re
import typing as tp

KINDS = (
    "nan_grad",
    "ckpt_io_error",
    "kill_mid_save",
    "truncate_ckpt_item",
    "preempt",
    "hang_step",
    "ckpt_enospc",
    "resume_reshard",
    # serving (sampling/serve.py, sampling/server.py, chaos_serve.py)
    "kill_mid_decode",
    "kill_overlapped_round",
    "poisoned_page",
    "slow_client",
    "submit_storm",
    "evict_shared_prefix",
    "hot_swap_mid_decode",
    "pool_resize",
    # fleet (sampling/fleet.py FleetRouter.step, chaos_serve.py)
    "engine_crash",
    "handoff_stall",
    "spill_corrupt",
    # cross-process fleet (sampling/fleet.py _fire_proc_faults against
    # fleet_proc.py ProcReplica workers, chaos_serve.py)
    "proc_kill9",
    "conn_drop",
    "wire_corrupt",
    "wire_stall",
)

# One-line summaries for operator tooling (`tools/chaos_run.py --serve
# --list-faults` and unknown-fault diagnostics). The module docstring above
# stays the full contract; this is the discoverable index of it.
DESCRIPTIONS: tp.Dict[str, str] = {
    "nan_grad": "poison the train step's loss at data step k (bad batch)",
    "ckpt_io_error": "raise IOError from the next checkpoint-save attempts",
    "kill_mid_save": "truncate one ckpt item + die before the manifest lands",
    "truncate_ckpt_item": "corrupt one ckpt item AFTER its manifest committed",
    "preempt": "set the preemption flag at data step k (SIGTERM mid-step)",
    "hang_step": "the step's device sync never lands; the watchdog must end it",
    "ckpt_enospc": "ENOSPC mid checkpoint write, partial bytes left behind",
    "resume_reshard": "preempt at data step k; driver restarts on another mesh",
    "kill_mid_decode": "the round's decode dispatch dies; slots recompute-preempt",
    "kill_overlapped_round": "the in-flight overlapped dispatch dies mid host phase",
    "poisoned_page": "corrupt one live slot's pool page in place (HBM damage)",
    "slow_client": "a streaming client stops draining; bounded buffer sheds it",
    "submit_storm": "submission burst beyond the backpressure budget; excess sheds",
    "evict_shared_prefix": "force-flush every unreferenced prefix-trie page at once",
    "hot_swap_mid_decode": "blue/green weight swap mid-trace (engine swap_source)",
    "pool_resize": "live KV pool resize to the engine's next resize_plan target",
    "engine_crash": "kill the busiest fleet replica; streams fail over to survivors",
    "handoff_stall": "wedge the spill-tier transport; admissions re-prefill instead",
    "spill_corrupt": "bit-flip a spilled host-RAM KV page; checksum must catch it",
    "proc_kill9": "SIGKILL the busiest worker process; wire-detected failover",
    "conn_drop": "drop the live router->worker socket; next RPC reconnects",
    "wire_corrupt": "bit-flip the next wire frame; crc32 rejects pre-decode",
    "wire_stall": "next RPC response misses its deadline; backoff absorbs it",
}

# kind names may carry digits (proc_kill9); `@` still separates the step
_PLAN_RE = re.compile(
    r"^(?P<kind>[a-z_][a-z0-9_]*?)(?:@(?P<step>\d+))?(?:\*(?P<times>\d+))?$"
)


@dataclasses.dataclass
class Fault:
    kind: str
    step: tp.Optional[int] = None  # fire only when the hook's step matches
    times: int = 1  # remaining firings
    fired: int = 0  # total firings so far


_active: tp.List[Fault] = []

# Optional firing observer (tools/chaos_run.py timestamps detection latency
# with it — the wall clock stays in tools/, keeping this module free of
# clock reads per the GC012 discipline). Called once per consumed firing.
_on_fire: tp.Optional[tp.Callable[[Fault], None]] = None


def set_on_fire(cb: tp.Optional[tp.Callable[[Fault], None]]) -> None:
    global _on_fire
    _on_fire = cb


def activate(kind: str, *, step: tp.Optional[int] = None, times: int = 1) -> Fault:
    if kind not in KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; known: {KINDS}")
    f = Fault(kind, step=step, times=times)
    _active.append(f)
    return f


def activate_plan(plan: str) -> tp.List[Fault]:
    """Parse and activate `kind[@step][*times]` comma-separated specs."""
    out = []
    for spec in filter(None, (s.strip() for s in plan.split(","))):
        m = _PLAN_RE.match(spec)
        if not m:
            raise ValueError(
                f"bad fault spec {spec!r} (want kind[@step][*times], e.g. "
                "'nan_grad@12' or 'ckpt_io_error*2')"
            )
        out.append(
            activate(
                m.group("kind"),
                step=int(m.group("step")) if m.group("step") else None,
                times=int(m.group("times")) if m.group("times") else 1,
            )
        )
    return out


def clear() -> None:
    global _on_fire
    _active.clear()
    _on_fire = None


def active() -> tp.List[Fault]:
    return list(_active)


def fired_counts() -> tp.Dict[str, int]:
    out: tp.Dict[str, int] = {}
    for f in _active:
        out[f.kind] = out.get(f.kind, 0) + f.fired
    return out


def should_fire(kind: str, *, step: tp.Optional[int] = None) -> bool:
    """Consume one firing of the first matching armed fault.

    A step-scoped fault only fires when the hook reports that exact step; a
    stepless fault fires on any matching hook call."""
    for f in _active:
        if f.kind != kind or f.times <= 0:
            continue
        if f.step is not None and step != f.step:
            continue
        f.times -= 1
        f.fired += 1
        if _on_fire is not None:
            _on_fire(f)
        return True
    return False
