"""Preemption flag: SIGTERM/SIGINT -> emergency save at the next step boundary.

A signal handler may run at any host-code point, so it only sets a flag; the
training loop polls the flag at step boundaries (the only place a consistent
save is possible) and performs one synchronous emergency checkpoint before
exiting. On multihost meshes the poll goes through `any_host_requested`,
which all-gathers the flag across processes so EVERY host takes the same
save-and-exit branch — a host-local decision would deadlock the collective
inside the next compiled step (half the hosts enter it, half don't).

`install_handlers` chains: after the first signal fires, the previous
handler is restored, so a second SIGINT still hard-kills a wedged run.
"""

from __future__ import annotations

import signal
import time
import typing as tp

import numpy as np

_requested = False
_requested_at: tp.Optional[float] = None
_previous: tp.Dict[int, tp.Any] = {}


def request(
    signum: tp.Optional[int] = None,
    frame: tp.Any = None,
    _clock: tp.Callable[[], float] = time.monotonic,
) -> None:
    """Mark a preemption (the signal handler; also callable directly).

    Records the arrival time on the injected clock so the train loop can
    hold its `preempt_grace_s` budget: an emergency save that would START
    after the grace window is skipped loudly rather than being SIGKILLed
    mid-write (training/train.py)."""
    global _requested, _requested_at
    _requested = True
    if _requested_at is None:  # first signal wins; re-delivery keeps it
        _requested_at = _clock()
    if signum is not None and signum in _previous:
        # One-shot: a second signal reaches the previous (default) handler.
        signal.signal(signum, _previous.pop(signum))


def requested() -> bool:
    """Host-local flag (free; no collective)."""
    return _requested


def requested_at() -> tp.Optional[float]:
    """Monotonic timestamp of the first preemption request (None if none).
    Same clock family as `request`'s default, so `clock() - requested_at()`
    is the elapsed grace the train loop compares to `preempt_grace_s`."""
    return _requested_at


def reset() -> None:
    global _requested, _requested_at
    _requested = False
    _requested_at = None
    for signum, prev in list(_previous.items()):
        signal.signal(signum, prev)
    _previous.clear()


def install_handlers(
    signums: tp.Sequence[int] = (signal.SIGTERM, signal.SIGINT),
) -> None:
    """Route the preemption signals through `request` (launch.py calls this
    before train; tests drive `request()`/the `preempt` fault directly)."""
    for signum in signums:
        prev = signal.signal(signum, request)
        _previous.setdefault(signum, prev)


def any_host_requested() -> bool:
    """True when ANY host saw a preemption signal — replicated decision.

    Single-process: the local flag, no device work. Multihost: one tiny
    all-gather, which is why the train loop gates this behind
    `preempt_check_interval`."""
    import jax

    if jax.process_count() == 1:
        return _requested
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([_requested], dtype=np.int32)
    )
    return bool(np.asarray(flags).any())
