"""Run supervisor: restart-on-divergence with data-window skip.

`supervise(config)` wraps `train(config)` in a bounded restart policy:

  1. `train` raises DivergenceError when the sticky health carrier goes
     non-finite (training/train.py). The poisoned batch lies in
     `(last_good_step, step]` — stickiness guarantees nothing before the
     last verified checkpoint can be bad.
  2. The supervisor rolls back by simply re-entering `train`: resume picks
     `latest_verified_step()` automatically. It advances
     `config.data_step_offset` so the replayed iterations sample data PAST
     the detected window (train threads `itr + data_step_offset` into the
     positional sampler and the dropout key stream), exactly as if the
     poisoned shard had been cut out of the stream — deterministically,
     because the offset is plain config.
  3. Attempts share one TrainRuntime, so the rollback path reuses the
     already-compiled train step — zero recompiles per restart (pinned in
     tests/test_robustness.py).
  4. After `max_restarts` rollbacks (or a divergence with no verified
     checkpoint to return to) it fails loudly with a diagnosis of every
     skipped window, so an operator can tell data poisoning apart from an
     optimization-level divergence (bad lr/warmup shifts with the data and
     keeps recurring).

The rollback ledger (current offset + skipped windows) is persisted to
`rundir/supervisor_state.json`, so a supervisor relaunched after a
preemption resumes with the same skips and the trajectory stays exactly
reproducible.
"""

from __future__ import annotations

import json
import os
import time
import typing as tp

from midgpt_tpu.config import ExperimentConfig
from midgpt_tpu.obs import dump_flight_recorder, flight_recorder
from midgpt_tpu.robustness import faults
from midgpt_tpu.robustness.errors import DivergenceError
from midgpt_tpu.training.train import TrainRuntime, make_runtime, train

STATE_NAME = "supervisor_state.json"


def _state_path(rundir: str) -> tp.Optional[str]:
    if not rundir or rundir.startswith("gs://"):
        return None
    return os.path.join(rundir, STATE_NAME)


def _load_state(rundir: str) -> tp.Dict[str, tp.Any]:
    path = _state_path(rundir)
    if path is None or not os.path.exists(path):
        return {}
    with open(path) as fh:
        return json.load(fh)


def _save_state(rundir: str, state: tp.Dict[str, tp.Any]) -> None:
    path = _state_path(rundir)
    if path is None:
        return
    os.makedirs(rundir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh, indent=1)
    os.replace(tmp, path)


def supervise(
    config: ExperimentConfig,
    *,
    runtime: tp.Optional[TrainRuntime] = None,
    max_restarts: tp.Optional[int] = None,
    backoff_sec: tp.Optional[float] = None,
    sleep_fn: tp.Callable[[float], None] = time.sleep,
) -> dict:
    """Run `train(config)` under the restart policy (module docstring).

    Returns train's result dict with a `"supervisor"` summary added.
    `max_restarts`/`backoff_sec` default to the config knobs; `sleep_fn` is
    injectable so tests don't pay real backoff."""
    import jax  # deferred: keep module import JAX-free for tools

    if max_restarts is None:
        max_restarts = config.max_restarts
    if backoff_sec is None:
        backoff_sec = config.restart_backoff_sec
    # Activate the fault plan ONCE per supervised run (not per attempt): a
    # consumed fault must stay consumed across rollbacks, like the real
    # failure it models.
    plan = config.fault_plan or os.environ.get("MIDGPT_FAULTS", "")
    if plan:
        faults.activate_plan(plan)

    persisted = _load_state(config.rundir)
    offset = max(config.data_step_offset, int(persisted.get("data_step_offset", 0)))
    windows: tp.List[tp.List[int]] = [
        list(w) for w in persisted.get("windows_skipped", [])
    ]
    restarts = int(persisted.get("restarts", 0))
    rt = runtime if runtime is not None else make_runtime(config)

    while True:
        cfg = (
            config
            if offset == config.data_step_offset
            else config.replace(data_step_offset=offset)
        )
        try:
            result = train(cfg, runtime=rt)
            result["supervisor"] = {
                "restarts": restarts,
                "windows_skipped": windows,
                "data_step_offset": offset,
                "faults_fired": faults.fired_counts(),
            }
            return result
        except DivergenceError as e:
            # Postmortem artifact FIRST, before any re-raise path: the
            # flight recorder's tail (train.step spans, ckpt events, the
            # train.divergence instant) as a loadable Chrome trace
            # (docs/OBSERVABILITY.md "Crash dumps").
            if config.rundir and not config.rundir.startswith("gs://"):
                dump_flight_recorder(config.rundir)
            if e.last_good_step is None:
                raise RuntimeError(
                    f"training diverged at step {e.step} with NO verified "
                    "checkpoint to roll back to (divergence before the first "
                    "save). Nothing to resume; fix learning_rate/warmup_steps "
                    f"or the data and restart. Underlying: {e}"
                ) from e
            # Poisoned DATA window, in sampler (data-index) coordinates.
            lo = e.last_good_step + 1 + offset
            hi = e.step + offset
            if restarts >= max_restarts:
                raise RuntimeError(
                    f"training diverged {restarts + 1} time(s); restart "
                    f"budget ({max_restarts}) exhausted. Data windows "
                    f"skipped so far: {windows}; the final divergence was "
                    f"detected in data window [{lo}, {hi}]. Recurring "
                    "divergence across DIFFERENT data windows points at the "
                    "optimization (lower learning_rate / raise "
                    "warmup_steps), not at one bad shard."
                ) from e
            windows.append([lo, hi])
            restarts += 1
            offset += max(1, e.step - e.last_good_step)
            flight_recorder().tracer.instant(
                "supervisor.rollback", "supervisor", "train",
                args={
                    "step": e.step,
                    "last_good_step": e.last_good_step,
                    "window": [lo, hi],
                    "restart": restarts,
                },
            )
            _save_state(
                config.rundir,
                {
                    "data_step_offset": offset,
                    "windows_skipped": windows,
                    "restarts": restarts,
                },
            )
            if jax.process_index() == 0:
                print(
                    f"supervisor: divergence at step {e.step}; rolling back "
                    f"to verified step {e.last_good_step}, skipping data "
                    f"window [{lo}, {hi}] (restart {restarts}/{max_restarts})"
                )
            sleep_fn(backoff_sec * (2 ** (restarts - 1)))
