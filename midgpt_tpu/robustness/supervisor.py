"""Run supervisor: restart-on-divergence with data-window skip.

`supervise(config)` wraps `train(config)` in a bounded restart policy:

  1. `train` raises DivergenceError when the sticky health carrier goes
     non-finite (training/train.py). The poisoned batch lies in
     `(last_good_step, step]` — stickiness guarantees nothing before the
     last verified checkpoint can be bad.
  2. The supervisor rolls back by simply re-entering `train`: resume picks
     `latest_verified_step()` automatically. It advances
     `config.data_step_offset` so the replayed iterations sample data PAST
     the detected window (train threads `itr + data_step_offset` into the
     positional sampler and the dropout key stream), exactly as if the
     poisoned shard had been cut out of the stream — deterministically,
     because the offset is plain config.
  3. Attempts share one TrainRuntime, so the rollback path reuses the
     already-compiled train step — zero recompiles per restart (pinned in
     tests/test_robustness.py).
  4. After `max_restarts` rollbacks (or a divergence with no verified
     checkpoint to return to) it fails loudly with a diagnosis of every
     skipped window, so an operator can tell data poisoning apart from an
     optimization-level divergence (bad lr/warmup shifts with the data and
     keeps recurring).

The rollback ledger (current offset + skipped windows) is persisted to
`rundir/supervisor_state.json`, so a supervisor relaunched after a
preemption resumes with the same skips and the trajectory stays exactly
reproducible. A corrupt ledger (truncated write, disk damage) is
quarantined to `supervisor_state.json.corrupt` with a warning and the run
proceeds on a fresh ledger — a damaged sidecar must never brick a resume
whose checkpoints are intact.

Beyond divergence, the supervisor handles two more failure families:

* **Hung steps** (StepHangError from the watchdog, robustness/watchdog.py):
  restart WITHOUT advancing the data offset — a wedged device sync says
  nothing about the data, so the replay re-runs the same window from the
  last verified checkpoint. Each hang is marked in the ledger
  (`hung_steps`) and counts against the same `max_restarts` budget.
* **Topology changes** (elastic resume): each attempt's mesh geometry is
  recorded in the ledger (`mesh` / `mesh_history`). On resume with a
  DIFFERENT device count, `on_resume_mesh="same"` (default) refuses
  loudly; `"any"` rebuilds the runtime with the data axis re-derived for
  the new count (make_runtime's `devices=` path) and restores the
  checkpoint through the new mesh's shardings.
"""

from __future__ import annotations

import json
import os
import time
import typing as tp

from midgpt_tpu.config import ExperimentConfig
from midgpt_tpu.obs import dump_flight_recorder, flight_recorder
from midgpt_tpu.robustness import faults
from midgpt_tpu.robustness.errors import DivergenceError, StepHangError
from midgpt_tpu.training.train import TrainRuntime, make_runtime, train

STATE_NAME = "supervisor_state.json"


def _state_path(rundir: str) -> tp.Optional[str]:
    if not rundir or rundir.startswith("gs://"):
        return None
    return os.path.join(rundir, STATE_NAME)


def _load_state(rundir: str) -> tp.Dict[str, tp.Any]:
    path = _state_path(rundir)
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path) as fh:
            state = json.load(fh)
        if not isinstance(state, dict):
            raise ValueError(f"expected a JSON object, got {type(state).__name__}")
        return state
    except (json.JSONDecodeError, ValueError, OSError) as e:
        # A damaged ledger must never brick a resume whose CHECKPOINTS are
        # intact (the ledger is a sidecar, not the source of truth).
        # Quarantine the bytes for postmortems and start a fresh ledger —
        # losing the skip history is recoverable (the supervisor re-detects
        # a recurring divergence); refusing to start is not.
        quarantine = path + ".corrupt"
        try:
            os.replace(path, quarantine)
        except OSError:
            quarantine = "(could not quarantine)"
        print(
            f"WARNING: supervisor ledger {path} is corrupt ({e}); "
            f"quarantined to {quarantine} and starting a fresh ledger"
        )
        return {}


def append_note(rundir: str, note: tp.Dict[str, tp.Any]) -> None:
    """Append an operator-visible event to the ledger's `notes` list (e.g.
    train's preempt_grace_s save-skip) — load/modify/atomic-replace, so a
    note survives later supervisor state writes."""
    if _state_path(rundir) is None:
        return
    state = _load_state(rundir)
    state.setdefault("notes", []).append(dict(note))
    _save_state(rundir, state)


def _save_state(rundir: str, state: tp.Dict[str, tp.Any]) -> None:
    path = _state_path(rundir)
    if path is None:
        return
    os.makedirs(rundir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh, indent=1)
    os.replace(tmp, path)


def supervise(
    config: ExperimentConfig,
    *,
    runtime: tp.Optional[TrainRuntime] = None,
    max_restarts: tp.Optional[int] = None,
    backoff_sec: tp.Optional[float] = None,
    sleep_fn: tp.Callable[[float], None] = time.sleep,
) -> dict:
    """Run `train(config)` under the restart policy (module docstring).

    Returns train's result dict with a `"supervisor"` summary added.
    `max_restarts`/`backoff_sec` default to the config knobs; `sleep_fn` is
    injectable so tests don't pay real backoff."""
    import jax  # deferred: keep module import JAX-free for tools

    if max_restarts is None:
        max_restarts = config.max_restarts
    if backoff_sec is None:
        backoff_sec = config.restart_backoff_sec
    # Activate the fault plan ONCE per supervised run (not per attempt): a
    # consumed fault must stay consumed across rollbacks, like the real
    # failure it models.
    plan = config.fault_plan or os.environ.get("MIDGPT_FAULTS", "")
    if plan:
        faults.activate_plan(plan)

    persisted = _load_state(config.rundir)
    offset = max(config.data_step_offset, int(persisted.get("data_step_offset", 0)))
    windows: tp.List[tp.List[int]] = [
        list(w) for w in persisted.get("windows_skipped", [])
    ]
    restarts = int(persisted.get("restarts", 0))
    hung: tp.List[int] = [int(s) for s in persisted.get("hung_steps", [])]
    mesh_history: tp.List[tp.Dict[str, tp.Any]] = [
        dict(m) for m in persisted.get("mesh_history", [])
    ]

    # Topology policy (elastic resume): compare this attempt's device count
    # against the geometry the ledger recorded for the previous attempt.
    rt = runtime
    n_prev = (
        int(persisted["mesh"]["n_devices"]) if persisted.get("mesh") else None
    )
    n_now = (
        len(rt.mesh.devices.flatten()) if rt is not None else jax.device_count()
    )
    if n_prev is not None and n_prev != n_now:
        if config.on_resume_mesh == "same":
            raise RuntimeError(
                f"supervised run in {config.rundir} previously ran on "
                f"{n_prev} device(s) "
                f"(mesh {persisted['mesh'].get('axes')}), but this resume "
                f"sees {n_now}; on_resume_mesh='same' refuses the topology "
                "change. Set on_resume_mesh='any' to reshard-resume across "
                "meshes (the checkpoint restores through the new mesh's "
                "shardings; the positional sampler keeps the batch order)."
            )
        if rt is None:
            # "any": re-derive the data axis for the new count.
            rt = make_runtime(config, devices=list(jax.devices()))
    if rt is None:
        rt = make_runtime(config)
    geom = {
        "n_devices": n_now,
        "axes": {k: int(v) for k, v in rt.mesh.shape.items()},
    }
    if not mesh_history or mesh_history[-1] != geom:
        mesh_history.append(geom)

    def _persist() -> None:
        # Re-load first so notes appended by train (append_note) mid-attempt
        # survive this write.
        state = _load_state(config.rundir)
        state.update(
            {
                "data_step_offset": offset,
                "windows_skipped": windows,
                "restarts": restarts,
                "hung_steps": hung,
                "mesh": geom,
                "mesh_history": mesh_history,
            }
        )
        _save_state(config.rundir, state)

    _persist()  # record this attempt's geometry before training starts

    while True:
        cfg = (
            config
            if offset == config.data_step_offset
            else config.replace(data_step_offset=offset)
        )
        try:
            result = train(cfg, runtime=rt)
            result["supervisor"] = {
                "restarts": restarts,
                "windows_skipped": windows,
                "data_step_offset": offset,
                "hung_steps": hung,
                "mesh_history": mesh_history,
                "faults_fired": faults.fired_counts(),
            }
            return result
        except StepHangError as e:
            # A wedged device sync says NOTHING about the data: restart from
            # the last verified checkpoint WITHOUT advancing the offset (the
            # replay re-runs the same window), mark the step HUNG in the
            # ledger, and spend one restart from the shared budget. The
            # watchdog already dumped the flight recorder at expiry.
            hung.append(int(e.step) if e.step is not None else -1)
            if restarts >= max_restarts:
                _persist()
                raise RuntimeError(
                    f"step hung {len(hung)} time(s) (steps {hung}); restart "
                    f"budget ({max_restarts}) exhausted. A recurring hang "
                    "at the SAME step suggests a wedged compile or input "
                    "pipeline; across different steps, a flaky device or "
                    f"tunnel. Underlying: {e}"
                ) from e
            restarts += 1
            flight_recorder().tracer.instant(
                "supervisor.hung_restart", "supervisor", "train",
                args={"step": e.step, "waited_s": e.waited_s,
                      "restart": restarts},
            )
            _persist()
            if jax.process_index() == 0:
                print(
                    f"supervisor: step {e.step} HUNG after {e.waited_s:.1f}s; "
                    f"restarting from the last verified checkpoint "
                    f"(restart {restarts}/{max_restarts})"
                )
            sleep_fn(backoff_sec * (2 ** (restarts - 1)))
        except DivergenceError as e:
            # Postmortem artifact FIRST, before any re-raise path: the
            # flight recorder's tail (train.step spans, ckpt events, the
            # train.divergence instant) as a loadable Chrome trace
            # (docs/OBSERVABILITY.md "Crash dumps").
            if config.rundir and not config.rundir.startswith("gs://"):
                dump_flight_recorder(config.rundir)
            if e.last_good_step is None:
                raise RuntimeError(
                    f"training diverged at step {e.step} with NO verified "
                    "checkpoint to roll back to (divergence before the first "
                    "save). Nothing to resume; fix learning_rate/warmup_steps "
                    f"or the data and restart. Underlying: {e}"
                ) from e
            # Poisoned DATA window, in sampler (data-index) coordinates.
            lo = e.last_good_step + 1 + offset
            hi = e.step + offset
            if restarts >= max_restarts:
                raise RuntimeError(
                    f"training diverged {restarts + 1} time(s); restart "
                    f"budget ({max_restarts}) exhausted. Data windows "
                    f"skipped so far: {windows}; the final divergence was "
                    f"detected in data window [{lo}, {hi}]. Recurring "
                    "divergence across DIFFERENT data windows points at the "
                    "optimization (lower learning_rate / raise "
                    "warmup_steps), not at one bad shard."
                ) from e
            windows.append([lo, hi])
            restarts += 1
            offset += max(1, e.step - e.last_good_step)
            flight_recorder().tracer.instant(
                "supervisor.rollback", "supervisor", "train",
                args={
                    "step": e.step,
                    "last_good_step": e.last_good_step,
                    "window": [lo, hi],
                    "restart": restarts,
                },
            )
            _persist()
            if jax.process_index() == 0:
                print(
                    f"supervisor: divergence at step {e.step}; rolling back "
                    f"to verified step {e.last_good_step}, skipping data "
                    f"window [{lo}, {hi}] (restart {restarts}/{max_restarts})"
                )
            sleep_fn(backoff_sec * (2 ** (restarts - 1)))
