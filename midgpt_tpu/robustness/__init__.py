"""Fault tolerance: run supervision, preemption handling, fault injection.

The recovery model (docs/ROBUSTNESS.md) is built on two properties the rest
of the framework already guarantees:

  * the data sampler is positional (`data/dataset.py`: every batch is a pure
    function of (seed, split, step)) and the dropout key stream is
    step-folded (`training/train.py`), so resume-and-replay is exactly
    deterministic with zero sampler state to checkpoint;
  * training health is sticky (`training/train.py health_flag`): a NaN/Inf
    anywhere surfaces in the reported loss at the next log/save sync and no
    poisoned state can reach the rolling checkpoint.

This package adds the machinery on top: `supervisor.supervise` restarts a
diverged run from the last *verified* checkpoint with the poisoned data
window skipped; `preempt` turns SIGTERM/SIGINT into an emergency save at
the next step boundary; `faults` injects failures so all of it is testable
end to end on the CPU mesh (tools/chaos_run.py drives the same registry).
"""

from midgpt_tpu.robustness.errors import (
    CheckpointCorruptError,
    CheckpointWriteError,
    DivergenceError,
    SimulatedPreemption,
)

__all__ = [
    "CheckpointCorruptError",
    "CheckpointWriteError",
    "DivergenceError",
    "SimulatedPreemption",
]
