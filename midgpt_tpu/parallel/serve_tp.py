"""Mesh sharding rules for the SERVING engine: tensor-parallel paged decode
over a named (data, tp) mesh, plus the per-role submeshes the disaggregated
prefill/decode deployment (sampling/disagg.py) places its engines on.

Training already proves megatron-TP end to end (parallel/tp.py); serving
reuses exactly those parameter rules — the (3, D, D) wqkv layout was
designed so tp shards land on whole heads (models/gpt.py AttentionParams) —
and adds the one piece training does not have: the paged KV pool. The pool
is (n_layer, n_kv_heads, num_pages, page_size, head_dim) per tensor, so the
KV-head axis is the natural tp shard: every page of every request splits
into per-shard head slices, attention is pointwise in (KV) heads — under
GQA each shard's n_kv_heads/tp pool heads serve exactly its
n_head/tp = groups * n_kv_heads/tp query heads, so the boundary falls
between whole query groups (config.py validates both divisibilities) —
and the ONLY activation collectives in a tp decode step are the two
megatron all-reduces per layer that the row-parallel wo/w_down already pay
(the in-loop collective census in analysis/hlo_audit.py pins exactly
that: GQA shrinks the pool bytes per shard by the group factor, not the
all-reduce count). The int8 scale side buffers
(n_layer, num_pages, n_kv_heads, page_size) shard the same KV-head axis at
position 2.

Deliberately NOT sharded: the page table, lengths, and every other
scheduler input stay replicated host-side jit inputs — the prefix-cache
trie, the allocator, and the scheduler policies are untouched host logic,
which is what keeps "admitting/finishing requests never recompiles" true on
a mesh (docs/SERVING.md "Mesh-sharded serving").

Serving uses vocab_parallel=False: logits come out replicated, so the
engine's host-side first-token argmax and the in-graph greedy sample both
read full-vocab logits with no extra collective inside the decode loop.

`make_serve_mesh` builds the mesh directly over an explicit device count
(unlike parallel/mesh.make_mesh, which spans ALL devices — a serving
deployment routinely carves a submesh per engine role out of one slice).
All six named axes (parallel/mesh.AXES) are present so the training-side
spec rules apply verbatim; only 'data' and 'tp' exceed size 1 here.
"""

from __future__ import annotations

import typing as tp

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from midgpt_tpu.parallel.mesh import AXES
from midgpt_tpu.parallel.tp import tp_param_specs

# PagedKVCache pool layout (L, H_kv, P, ps, C): KV heads at axis 1.
POOL_SPEC = P(None, "tp", None, None, None)
# int8 scale side buffers (L, P, H_kv, ps): KV heads at axis 2.
SCALE_SPEC = P(None, None, "tp", None)


def make_serve_mesh(
    tp_size: int = 1,
    data: int = 1,
    devices: tp.Optional[tp.Sequence[jax.Device]] = None,
) -> Mesh:
    """A (data, tp) serving mesh over the first data*tp devices.

    'data' is the engine-ROLE axis (disaggregated prefill/decode instances,
    sampling/disagg.py — each role engine lives on one data row via
    `role_submeshes`), 'tp' the tensor-parallel axis within a role. The
    other four named axes are size 1 so parallel/tp.py's rules (which index
    mesh.shape['fsdp']/['ep']) work unchanged."""
    devices = list(devices if devices is not None else jax.devices())
    n = data * tp_size
    if n > len(devices):
        raise ValueError(
            f"serve mesh data={data} x tp={tp_size} needs {n} devices, "
            f"have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(data, 1, 1, tp_size, 1, 1)
    return Mesh(arr, axis_names=AXES)


def role_submeshes(mesh: Mesh) -> tp.List[Mesh]:
    """One (data=1, tp) submesh per 'data' row — the per-role engine meshes
    of a disaggregated deployment. Row 0 is the prefill role by convention
    (sampling/disagg.py)."""
    devs = mesh.devices  # (data, 1, 1, tp, 1, 1)
    return [Mesh(devs[r : r + 1], axis_names=AXES) for r in range(devs.shape[0])]


def serve_param_specs(params: tp.Any, mesh: Mesh) -> tp.Any:
    """Megatron tp specs for a serving engine's params: the training rule
    (parallel/tp.py) with vocab_parallel OFF (module docstring) and no size
    gate — serving replicates nothing shardable, however small the model
    (the CPU test mesh runs 32-dim toys)."""
    return tp_param_specs(
        params, mesh, shard_model=True, min_size=0, vocab_parallel=False
    )


def serve_cache_specs(cache: tp.Any) -> tp.Any:
    """PartitionSpec pytree matching a PagedKVCache: pools head-sharded over
    'tp', int8 scale side buffers likewise (layouts in the module
    docstring). Works on concrete caches and ShapeDtypeStruct trees alike —
    bf16 caches simply have no scale leaves."""
    from midgpt_tpu.models.gpt import PagedKVCache

    has_scales = cache.k_scale is not None
    return PagedKVCache(
        k=POOL_SPEC,
        v=POOL_SPEC,
        k_scale=SCALE_SPEC if has_scales else None,
        v_scale=SCALE_SPEC if has_scales else None,
    )


def put_sharded(tree: tp.Any, specs: tp.Any, mesh: Mesh) -> tp.Any:
    """device_put a pytree with NamedShardings (engine init: params and
    freshly-initialized pools land sharded once; every later update stays
    sharded through the jits' output constraints)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def constrain_cache(cache: tp.Any, mesh: Mesh) -> tp.Any:
    """with_sharding_constraint the pool layout onto a returned cache
    (inside jit). Pinning the OUT-sharding to the IN-sharding is what keeps
    the donated pool's buffers reusable across rounds — without it GSPMD is
    free to pick a different output layout and the donation degrades to a
    copy + reshard every serve round."""
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        cache,
        serve_cache_specs(cache),
    )


def mesh_shape(mesh: tp.Optional[Mesh]) -> tp.Optional[tp.Dict[str, int]]:
    """{'data': d, 'tp': t} for stats()/JSON reporting, None when unsharded."""
    if mesh is None:
        return None
    return {"data": int(mesh.shape["data"]), "tp": int(mesh.shape["tp"])}
