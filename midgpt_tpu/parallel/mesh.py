"""Device mesh construction: a named 5D ('data','fsdp','sp','tp','pp') mesh.

The reference hard-codes Mesh((n_devices // 8, 8), ('replica', 'data')) —
batch over both axes, params over the 8-wide axis (reference train.py:130),
which requires device counts divisible by 8. Here axis sizes come from config
with -1 inference, `mesh_utils.create_device_mesh` picks the physical layout
so 'fsdp' collectives (the per-layer all-gathers/reduce-scatters) ride
contiguous ICI links, 'sp' is the context-parallel axis (ring or Ulysses
attention), 'tp' is the tensor-parallel axis (Megatron column/row sharding
of the block projections, parallel/tp.py), and 'pp' is the pipeline axis
(GPipe stages shard the LAYER dimension, parallel/pipeline.py) — all three
size 1 unless enabled.
"""

from __future__ import annotations

import typing as tp

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.config import MeshConfig

AXES = ("data", "fsdp", "sp", "tp", "pp", "ep")
# The axes token batches shard over (batch_spec below; the shard_map loss
# bodies pmean/fold-in over these).
BATCH_AXES = ("data", "fsdp")


def make_mesh(
    cfg: tp.Optional[MeshConfig] = None,
    *,
    devices: tp.Optional[tp.Sequence[jax.Device]] = None,
) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fsdp = cfg.fsdp if cfg.fsdp != -1 else 1
    sp = cfg.sp if cfg.sp != -1 else 1
    tp_ = cfg.tp if cfg.tp != -1 else 1
    pp = cfg.pp if cfg.pp != -1 else 1
    ep = cfg.ep if cfg.ep != -1 else 1
    rest_axes = sp * tp_ * pp * ep
    if n % (fsdp * rest_axes) != 0:
        # Degrade gracefully on small device counts (e.g. 1-chip dev boxes):
        # clamp fsdp to the largest divisor of n // (sp * tp * pp * ep).
        if n % rest_axes != 0:
            raise ValueError(
                f"{n} devices not divisible by sp={sp} * tp={tp_} * pp={pp} * ep={ep}"
            )
        rest = n // rest_axes
        fsdp = max(d for d in range(1, rest + 1) if rest % d == 0 and d <= fsdp)
    data = cfg.data if cfg.data != -1 else n // (fsdp * rest_axes)
    if data * fsdp * rest_axes != n:
        raise ValueError(f"mesh {data}x{fsdp}x{sp}x{tp_}x{pp}x{ep} != {n} devices")
    mesh_devices = mesh_utils.create_device_mesh(
        (data, fsdp, sp, tp_, pp, ep), devices=np.asarray(devices)
    )
    return Mesh(mesh_devices, axis_names=AXES)


def batch_spec(with_accum: bool = True, shard_seq: bool = False) -> P:
    """PartitionSpec for token batches.

    (G, B, T) with grad accumulation, (B, T) without. The batch axis shards
    over both 'data' and 'fsdp' (matching the reference's
    P(None, ('replica','data'), None), reference train.py:105); the sequence
    axis shards over 'sp' when context parallelism is on.
    """
    seq = "sp" if shard_seq else None
    spec = (("data", "fsdp"), seq)
    return P(None, *spec) if with_accum else P(*spec)
