"""Ring attention: causal self-attention over a sequence sharded on a mesh axis.

Makes the mesh's `sp` (sequence-parallel) axis real: each device holds a
contiguous (B, H, T/n, C) shard of Q/K/V; K/V shards rotate around the ring
with `jax.lax.ppermute` while every device accumulates online-softmax
statistics of its local queries against each visiting K/V shard. After n
steps every query has seen every key once — attention over the full sequence
with O(T/n) activation memory per device and only neighbor-to-neighbor ICI
traffic (the ppermute rides the ring; there is no all-gather of the sequence).

This is the long-context scaling story the reference lacks entirely (its
attention materializes the full T x T scores on every device, reference
model.py:71-73, and its sequence axis is never sharded, reference
train.py:105). Design follows the blockwise/ring formulation of Liu et al.
(Ring Attention with Blockwise Transformers) re-expressed as a `lax.scan` of
shard-local blockwise attention + ppermute so it is reverse-differentiable
(jax transposes ppermute through AD; a fori_loop would not be).

Causal masking across shards is an index comparison on GLOBAL positions:
a visiting K/V shard j contributes fully when j < my shard index, the causal
triangle when j == mine, and nothing when j > mine (those steps still run —
shapes under scan are static — but their probabilities underflow to exactly 0
through the same finite-mask trick the flash kernel uses).

Use `ring_attention` inside `shard_map` (it needs a named axis); the
`ring_attention_sharded` wrapper applies the shard_map given a mesh and spec.
Numerics: scores/statistics in float32, matmuls in the input dtype — same
contract as ops/attention.py. Per visiting shard, scores are (B, H, T/n, T/n)
— blockwise memory, not O(T^2).
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array

# Finite stand-ins for -inf (same scheme as kernels/flash_attention.py:
# masked scores get MASK, running max starts at M_INIT > MASK, so
# exp(MASK - m) == 0 exactly, even for all-masked ring steps).
MASK = -1.0e30
M_INIT = -0.5e30


def ring_attention(
    q: Array,  # (B, H, Tl, C) local query shard
    k: Array,  # (B, H, Tl, C) local key shard
    v: Array,  # (B, H, Tl, C) local value shard
    axis_name: str,
    block_size: int = 1024,
) -> Array:
    """Causal attention across the `axis_name` ring. Call inside shard_map.

    Returns the local (B, H, Tl, C) output shard. Shards are assumed to be
    contiguous sequence chunks in axis order (chunk g holds global positions
    [g*Tl, (g+1)*Tl) — exactly what sharding the T axis of a (B, H, T, C)
    array over `axis_name` produces).

    Within each ring step, the visiting K/V shard is swept in `block_size`
    sub-blocks through the SAME online-softmax accumulators, so peak scores
    memory is (B, H, Tl, block_size) — not (Tl, Tl). At 32K context over
    sp=8 that is the difference between a 512 MB and a 2 GB f32 buffer per
    microbatch element."""
    n = jax.lax.axis_size(axis_name)
    g = jax.lax.axis_index(axis_name)  # my global chunk index
    B, H, Tl, C = q.shape
    scale = 1.0 / math.sqrt(C)
    blk = min(block_size, Tl)
    if Tl % blk:
        # keep memory bounded for every shape: the largest divisor of the
        # shard length that fits the budget (never the whole shard)
        blk = max(d for d in range(1, blk + 1) if Tl % d == 0)
    n_blk = Tl // blk

    rows = jnp.arange(Tl)[:, None]  # local row offsets
    cols = jnp.arange(blk)[None, :]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def kv_block_step(carry, kv_and_col0):
        """One (Tl, blk) tile of scores through the running statistics."""
        m, l, acc = carry
        k_blk, v_blk, col0 = kv_and_col0  # (B,H,blk,C) x2, () global col base
        scores = (
            jnp.einsum("bhqc,bhkc->bhqk", q, k_blk).astype(jnp.float32) * scale
        )
        valid = (g * Tl + rows) >= (col0 + cols)  # global causal comparison
        scores = jnp.where(valid, scores, MASK)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # masked entries underflow to 0
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkc->bhqc", p.astype(v_blk.dtype), v_blk
        ).astype(jnp.float32)
        return (m_new, l_new, acc_new), None

    # Recompute the (Tl, blk) probabilities in the backward pass instead of
    # stacking them as scan residuals: without this, reverse AD through the
    # double scan saves O(Tl * T) f32 of per-block softmax probabilities per
    # device per layer — exactly the O(T^2) memory ring attention exists to
    # avoid (the blockwise-backward formulation of Liu et al. recomputes p).
    # The recompute is one extra QK^T einsum per block — the same trade the
    # flash kernel's backward makes.
    kv_block_step_ckpt = jax.checkpoint(kv_block_step)

    def ring_step(carry, s):
        k_cur, v_cur, m, l, acc = carry
        j = (g - s) % n  # global chunk index of the visiting K/V shard
        kb = k_cur.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
        vb = v_cur.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
        col0 = j * Tl + blk * jnp.arange(n_blk)  # global col base per block
        (m, l, acc), _ = jax.lax.scan(kv_block_step_ckpt, (m, l, acc), (kb, vb, col0))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    init = (
        k,
        v,
        jnp.full((B, H, Tl), M_INIT, jnp.float32),
        jnp.zeros((B, H, Tl), jnp.float32),
        jnp.zeros((B, H, Tl, C), jnp.float32),
    )
    (k, v, m, l, acc), _ = jax.lax.scan(ring_step, init, jnp.arange(n))
    # every global row has >= 1 valid key under causal masking, so l > 0
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention_sharded(
    q: Array,  # (B, H, T, C) global arrays, T sharded (or shardable) over sp
    k: Array,
    v: Array,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axes: tp.Tuple[str, ...] = ("data", "fsdp"),
    block_size: int = 1024,
) -> Array:
    """shard_map wrapper: shards T over `axis_name`, batch over `batch_axes`,
    runs the ring, returns the (B, H, T, C) result with the same layout."""
    spec = P(batch_axes, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, block_size=block_size),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
