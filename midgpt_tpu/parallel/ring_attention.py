"""Ring attention: causal self-attention over a sequence sharded on a mesh axis.

Makes the mesh's `sp` (sequence-parallel) axis real: each device holds a
contiguous (B, H, T/n, C) shard of Q/K/V; K/V shards rotate around the ring
with `jax.lax.ppermute` while every device merges online-softmax statistics
of its local queries against each visiting K/V shard. After n steps every
query has seen every key once — attention over the full sequence with O(T/n)
activation memory per device and only neighbor-to-neighbor ICI traffic.

This is the long-context scaling story the reference lacks entirely (its
attention materializes the full T x T scores on every device, reference
model.py:71-73, and its sequence axis is never sharded, reference
train.py:105). Design follows the blockwise/ring formulation of Liu et al.
(Ring Attention with Blockwise Transformers), structured TPU-first:

  * The causal structure is decided PER PAIR of shards, not per element:
    with contiguous sequence chunks in ring order, the local (diagonal)
    pair is ordinary causal attention, a visiting shard j < mine is fully
    valid (NO mask — full-attention kernel), and j > mine contributes
    nothing (its statistics are multiplied out at merge time; the compute
    still runs because shapes under `lax.scan` are static). So the per-pair
    compute is served by the SAME Pallas flash kernels as the dense path
    (kernels/flash_attention.py with causal=True/False) — on a real sp>1
    slice the per-pair attention runs at kernel speed, not jnp speed.
  * The whole ring is one `jax.custom_vjp`: forward saves only
    (q, k, v, out, lse) — O(T/n · C) per device. The backward pass is a
    second authored ring pass: dK/dV accumulators rotate WITH the visiting
    K/V shards (n rotations total brings them home), per-pair grads come
    from the flash backward kernels reconstructing p = exp(s − lse_global),
    and dQ accumulates locally. No AD through the scan, so nothing is
    stacked — this is the blockwise-backward of the paper, written down.
  * Per-pair partials merge through log-sum-exp statistics in f32:
    out = Σ_j out_j · exp(lse_j − lse_total), with lse_j = MASK for invalid
    pairs (the same finite-mask trick as the kernels: exp underflows to
    exactly 0, no NaN-scrubbing selects).

Off-TPU the per-pair compute falls back to the equivalent blockwise jnp
online-softmax (`use_kernel=False`, auto-selected; tests force the kernel
path in interpret mode for parity coverage).

Use `ring_attention` inside `shard_map` (it needs a named axis); the
`ring_attention_sharded` wrapper applies the shard_map given a mesh and spec.
Numerics: scores/statistics in float32, matmuls in the input dtype — same
contract as ops/attention.py.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import importlib

# the real module (the kernels package re-exports a same-named function)
fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")
from midgpt_tpu.ops.attention import flash_block_sizes
from midgpt_tpu.ops.online_softmax import (
    MASK,
    M_INIT,
    finalize,
    merge_normalized,
    online_block,
)
from midgpt_tpu.utils.compat import axis_size, shard_map

Array = jax.Array


def _auto_use_kernel() -> bool:
    """Kernel per-pair compute on TPU (or when tests force interpret mode)."""
    return jax.default_backend() == "tpu" or fa.RUN_INTERPRET_OFF_TPU


def _kernel_serves(Tl: int, block_size: int) -> bool:
    """True when the flash kernels tile this shard length cleanly. Shard
    lengths the dispatcher's blocks don't divide (e.g. Tl=2560 at the
    default 1024 KV block) fall back to the jnp pair path instead of
    tripping _block_sizes' VMEM bound; the same predicate gates forward and
    backward, so the custom VJP stays consistent."""
    bq, bk = flash_block_sizes(Tl, block_size)
    return Tl % bq == 0 and Tl % bk == 0


def _divisor_block(Tl: int, block_size: int) -> int:
    blk = min(block_size, Tl)
    if Tl % blk:
        blk = max(d for d in range(1, blk + 1) if Tl % d == 0)
    return blk


# (Tl, block_size) pairs already warned about — the fallback is a large,
# silent-by-default perf cliff, so it gets exactly one loud line per shape.
_WARNED: tp.Set[tp.Tuple[int, int]] = set()


def _resolve_pair_plan(
    Tl: int, block_size: int, use_kernel: tp.Optional[bool]
) -> tp.Tuple[bool, int]:
    """Decide (use_kernel, block_size) for this shard length, at trace time.

    When the configured block does not tile Tl, prefer AUTO-ADJUSTING to the
    largest divisor of Tl in [128, block_size] (8-aligned for the kernel's
    sublane tiling) so the per-pair compute stays on the Pallas kernels —
    e.g. Tl=1280 at block 1024 runs at block 640 instead of dropping to jnp.
    Only when no such divisor exists fall back to the jnp pair path, and say
    so ONCE per shape: the fallback preserves correctness but costs kernel
    speed (the whole point of ring v2), which silently looks like 'ring
    attention is slow'. Pure function of its arguments, so the forward and
    backward rings always agree on the plan."""
    if use_kernel is None:
        use_kernel = _auto_use_kernel()
    if not use_kernel:
        return False, block_size
    if _kernel_serves(Tl, block_size):
        return True, block_size
    for d in range(min(block_size, Tl), 127, -1):
        if Tl % d == 0 and d % 8 == 0 and _kernel_serves(Tl, d):
            return True, d
    if (Tl, block_size) not in _WARNED:
        _WARNED.add((Tl, block_size))
        import warnings

        divisors = [d for d in range(8, Tl + 1) if Tl % d == 0 and d % 8 == 0]
        hint = (
            f"e.g. attn_block_size={max(divisors)}"
            if divisors
            else "no 8-aligned divisor exists; change the sequence shard length"
        )
        warnings.warn(
            f"ring attention: shard length {Tl} is not tileable by "
            f"attn_block_size={block_size} and has no kernel-servable "
            f"divisor >= 128 — per-pair compute falls back to the jnp path "
            f"(correct but far slower than the Pallas kernels). Pick a "
            f"block that divides the shard ({hint}).",
            RuntimeWarning,
            stacklevel=3,
        )
    return False, block_size


# ----------------------------------------------------------------------
# per-pair attention: local q against one visiting K/V shard
# ----------------------------------------------------------------------


def _pair_fwd_jnp(
    q: Array, k: Array, v: Array, causal: bool, block_size: int
) -> tp.Tuple[Array, Array]:
    """Blockwise online-softmax pair attention -> (out, lse (B,H,Tl) f32)."""
    B, H, Tl, C = q.shape
    scale = 1.0 / math.sqrt(C)
    blk = _divisor_block(Tl, block_size)
    n_blk = Tl // blk
    rows = jnp.arange(Tl)[:, None]
    cols = jnp.arange(blk)[None, :]

    def kv_block_step(carry, kv_and_col0):
        m, l, acc = carry
        k_blk, v_blk, col0 = kv_and_col0
        s = (
            jnp.einsum("bhqc,bhkc->bhqk", q, k_blk).astype(jnp.float32) * scale
        )
        if causal:
            s = jnp.where(rows >= (col0 + cols), s, MASK)
        m_new, alpha, p, l_new = online_block(m, l, s)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkc->bhqc", p.astype(v_blk.dtype), v_blk
        ).astype(jnp.float32)
        return (m_new, l_new, acc_new), None

    kb = k.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
    col0 = blk * jnp.arange(n_blk)
    # init derived from q (not fresh constants) so the carry's device-varying
    # axes match the body output under shard_map's vma tracking
    zero_q = q.astype(jnp.float32) * 0
    init = (zero_q[..., 0] + M_INIT, zero_q[..., 0], zero_q)
    (m, l, acc), _ = jax.lax.scan(kv_block_step, init, (kb, vb, col0))
    # every row has >= 1 valid key in both pair cases (diagonal: itself)
    out, lse = finalize(m, l, acc, dtype=q.dtype)
    return out, lse


def _pair_bwd_jnp(
    q, k, v, out, do, lse, delta, causal: bool, block_size: int
) -> tp.Tuple[Array, Array, Array]:
    """Pair backward from global statistics: p = exp(s - lse), delta global.

    Blockwise over the visiting shard's KV blocks (bounds scores memory to
    (Tl, blk), matching the forward)."""
    B, H, Tl, C = q.shape
    scale = 1.0 / math.sqrt(C)
    blk = _divisor_block(Tl, block_size)
    n_blk = Tl // blk
    rows = jnp.arange(Tl)[:, None]
    cols = jnp.arange(blk)[None, :]

    def kv_block_step(dq_acc, kv_and_col0):
        k_blk, v_blk, col0 = kv_and_col0
        s = (
            jnp.einsum("bhqc,bhkc->bhqk", q, k_blk).astype(jnp.float32) * scale
        )
        if causal:
            s = jnp.where(rows >= (col0 + cols), s, MASK)
        p = jnp.exp(s - lse[..., None])  # masked entries underflow to 0
        dv_blk = jnp.einsum("bhqk,bhqc->bhkc", p.astype(do.dtype), do)
        dp = jnp.einsum("bhqc,bhkc->bhqk", do, v_blk).astype(jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkc->bhqc", ds, k_blk).astype(
            jnp.float32
        )
        dk_blk = jnp.einsum("bhqk,bhqc->bhkc", ds, q)
        return dq_acc, (dk_blk, dv_blk)

    kb = k.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, n_blk, blk, C).transpose(2, 0, 1, 3, 4)
    col0 = blk * jnp.arange(n_blk)
    dq, (dkb, dvb) = jax.lax.scan(
        kv_block_step, q.astype(jnp.float32) * 0, (kb, vb, col0)
    )
    dk = dkb.transpose(1, 2, 0, 3, 4).reshape(B, H, Tl, C)
    dv = dvb.transpose(1, 2, 0, 3, 4).reshape(B, H, Tl, C)
    return dq, dk.astype(jnp.float32), dv.astype(jnp.float32)


def _pair_fwd(q, k, v, causal: bool, block_size: int, use_kernel: bool):
    Tl = q.shape[2]
    if use_kernel and _kernel_serves(Tl, block_size):
        bq, bk = flash_block_sizes(Tl, block_size)
        out, lse8 = fa._flash_forward(q, k, v, bq, bk, causal=causal)
        return out, lse8[..., 0]
    return _pair_fwd_jnp(q, k, v, causal, block_size)


def _pair_bwd(q, k, v, out, do, lse, delta, causal: bool, block_size: int, use_kernel: bool):
    Tl = q.shape[2]
    if use_kernel and _kernel_serves(Tl, block_size):
        bq, bk = flash_block_sizes(Tl, block_size)
        lse8 = jnp.broadcast_to(lse[..., None], (*lse.shape, fa._STATS_LANES))
        dq, dk, dv = fa._flash_backward(
            bq, bk, (q, k, v, out, lse8), do, causal=causal
        )
        return dq.astype(jnp.float32), dk.astype(jnp.float32), dv.astype(jnp.float32)
    return _pair_bwd_jnp(q, k, v, out, do, lse, delta, causal, block_size)


# ----------------------------------------------------------------------
# the ring
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(
    q: Array,  # (B, H, Tl, C) local query shard
    k: Array,  # (B, H, Tl, C) local key shard
    v: Array,  # (B, H, Tl, C) local value shard
    axis_name: str,
    block_size: int = 1024,
    use_kernel: tp.Optional[bool] = None,
) -> Array:
    """Causal attention across the `axis_name` ring. Call inside shard_map.

    Returns the local (B, H, Tl, C) output shard. Shards are assumed to be
    contiguous sequence chunks in axis order (chunk g holds global positions
    [g*Tl, (g+1)*Tl) — exactly what sharding the T axis of a (B, H, T, C)
    array over `axis_name` produces)."""
    out, _ = _ring_fwd(q, k, v, axis_name, block_size, use_kernel)
    return out


def _ring_fwd(q, k, v, axis_name, block_size, use_kernel):
    use_kernel, block_size = _resolve_pair_plan(q.shape[2], block_size, use_kernel)
    n = axis_size(axis_name)
    g = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Diagonal pair: ordinary causal attention on the local shard (static
    # case — ring step s=0 always visits the local shard).
    out_d, lse_d = _pair_fwd(q, k, v, True, block_size, use_kernel)
    if n == 1:
        return out_d, (q, k, v, out_d, lse_d)

    def ring_step(carry, s):
        k_c, v_c, m, l, acc = carry
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        j = (g - s) % n  # global chunk index of the visiting K/V shard
        # Off-diagonal pairs are never diagonal-straddling: j < g is fully
        # valid (full attention, no mask), j > g contributes nothing — its
        # lse is forced to MASK so its weight underflows to exactly 0 at
        # merge (compute still runs: static shapes under scan).
        o_s, lse_s = _pair_fwd(q, k_c, v_c, False, block_size, use_kernel)
        lse_s = jnp.where(j < g, lse_s, MASK)
        m_new, l, acc = merge_normalized(m, l, acc, o_s, lse_s)
        return (k_c, v_c, m_new, l, acc), None

    init = (k, v, lse_d, lse_d * 0 + 1.0, out_d.astype(jnp.float32))
    (_, _, m, l, acc), _ = jax.lax.scan(ring_step, init, jnp.arange(1, n))
    # l >= exp(lse_d - m) > 0 always (the local diagonal softmax seeds the
    # running sum), so the shared finalize is a bitwise no-op guard here.
    out, lse = finalize(m, l, acc, dtype=q.dtype)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, block_size, use_kernel, residuals, do):
    q, k, v, out, lse = residuals
    use_kernel, block_size = _resolve_pair_plan(q.shape[2], block_size, use_kernel)
    n = axis_size(axis_name)
    g = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Global softmax-jacobian correction, one pass (the kernels recompute it
    # in-VMEM from the same o/do tiles; the jnp path takes it as input).
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)

    dq, dk_c, dv_c = _pair_bwd(
        q, k, v, out, do, lse, delta, True, block_size, use_kernel
    )
    if n == 1:
        return dq.astype(q.dtype), dk_c.astype(k.dtype), dv_c.astype(v.dtype)

    def ring_step(carry, s):
        k_c, v_c, dk_c, dv_c, dq_acc = carry
        # dK/dV accumulators ride the ring WITH their K/V shard: after the
        # final rotation below they have made n hops and are home, carrying
        # every device's contribution.
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        dk_c = jax.lax.ppermute(dk_c, axis_name, perm)
        dv_c = jax.lax.ppermute(dv_c, axis_name, perm)
        j = (g - s) % n
        dq_s, dk_s, dv_s = _pair_bwd(
            q, k_c, v_c, out, do, lse, delta, False, block_size, use_kernel
        )
        valid = j < g
        dq_acc = dq_acc + jnp.where(valid, dq_s, 0.0)
        dk_c = dk_c + jnp.where(valid, dk_s, 0.0)
        dv_c = dv_c + jnp.where(valid, dv_s, 0.0)
        return (k_c, v_c, dk_c, dv_c, dq_acc), None

    (k_c, v_c, dk_c, dv_c, dq), _ = jax.lax.scan(
        ring_step, (k, v, dk_c, dv_c, dq), jnp.arange(1, n)
    )
    dk = jax.lax.ppermute(dk_c, axis_name, perm)  # n-th hop: home
    dv = jax.lax.ppermute(dv_c, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ring_fwd_rule(q, k, v, axis_name, block_size, use_kernel):
    return _ring_fwd(q, k, v, axis_name, block_size, use_kernel)


ring_attention.defvjp(_ring_fwd_rule, _ring_bwd)


def ring_attention_sharded(
    q: Array,  # (B, H, T, C) global arrays, T sharded (or shardable) over sp
    k: Array,
    v: Array,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axes: tp.Tuple[str, ...] = ("data", "fsdp"),
    block_size: int = 1024,
    use_kernel: tp.Optional[bool] = None,
    head_axis: tp.Optional[str] = None,
) -> Array:
    """shard_map wrapper: shards T over `axis_name`, batch over `batch_axes`,
    runs the ring, returns the (B, H, T, C) result with the same layout.

    `head_axis` (e.g. 'tp') additionally shards the head axis — the ring is
    head-independent, so Megatron tensor parallelism and sequence parallelism
    compose here with no extra collectives: each (tp, sp) device runs the
    ring over its own H/tp heads' T/sp shard."""
    spec = P(batch_axes, head_axis, axis_name, None)
    # nondiff_argnums of a custom_vjp function must be passed positionally
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name, block_size, use_kernel),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
