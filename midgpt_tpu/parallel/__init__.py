from midgpt_tpu.parallel.mesh import make_mesh, batch_spec
from midgpt_tpu.parallel.fsdp import fsdp_param_specs, constrain, named_shardings
from midgpt_tpu.parallel.data import make_global_batch

__all__ = [
    "make_mesh",
    "batch_spec",
    "fsdp_param_specs",
    "constrain",
    "named_shardings",
    "make_global_batch",
]
