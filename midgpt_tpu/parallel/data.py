"""Host -> device data plumbing for multihost SPMD.

Each host samples its own contiguous shard of the token stream (reference
train.py:122-136) and produces a *process-local* batch; the global jax.Array
is assembled with `jax.make_array_from_process_local_data` — the modern,
TPU-native replacement for the reference's hand-rolled per-device
device_put + make_array_from_single_device_arrays (reference sharding.py:33-42).
"""

from __future__ import annotations

import typing as tp

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_global_batch(arr: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Assemble a global array from this process's local slice of the batch.

    `arr` is the process-local chunk: its batch axis is 1/n_proc of the
    global batch. make_array_from_process_local_data infers the global shape
    from the sharding.
    """
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, arr)


def replicate(x: tp.Any, mesh: Mesh) -> tp.Any:
    """Fully-replicate host values across the mesh (multihost-safe)."""
    sharding = NamedSharding(mesh, P())

    def put(leaf):
        leaf = np.asarray(leaf)
        return jax.make_array_from_process_local_data(sharding, leaf)

    return jax.tree.map(put, x)
