"""GPipe pipeline parallelism over the mesh 'pp' axis.

Beyond the reference's capability set (its only model sharding is FSDP,
reference model.py:167-178). The design falls out of this framework's
model representation: block parameters are already STACKED along a leading
layer axis (models/gpt.py), so a pipeline stage is nothing more than that
axis sharded over 'pp' — stage s holds the (L/pp, ...) slice of every block
leaf, and shard_map hands it each stage's slice with zero data movement.

Schedule (classic GPipe, SPMD-expressed — every stage runs the SAME
program every tick; there is no per-stage control flow to trace):

  * the step's local batch is split into M microbatches; the embedded
    activations (M, Bm, T, D) are visible to every stage (the 'pp' axis is
    replicated for activations — only stage 0's use of them is real);
  * one `lax.scan` runs M + pp - 1 ticks. Each tick, every stage runs its
    layer slice on one activation: stage 0 reads microbatch t from the
    input stream, stage s>0 reads what stage s-1 ppermuted to it last tick.
    Tick outputs ride a single neighbor `ppermute`; the last stage collects
    its finished microbatches into an output buffer by a masked
    dynamic-index update (bubble ticks compute on garbage that is never
    collected — static shapes, no `lax.cond`);
  * loss (v2): the collected outputs are `psum_scatter`ed over 'pp' — only
    the last stage's buffer is nonzero, so the scatter-sum is a
    broadcast-slice handing stage s tokens [s·B/pp, (s+1)·B/pp) — and EVERY
    stage runs final-norm + fused CE on its 1/pp slice; a `pmean` over 'pp'
    recombines the mean. Total lm_head/CE matmul volume is 1×, not the v1
    pp× (where each stage ran the full-batch CE on mostly-zero outputs).
    Reverse-mode AD through the tick scan + ppermute IS the GPipe backward
    schedule (ppermute transposes to the reverse permutation; the scan's
    saved residuals are the activation stash; psum_scatter transposes to
    all_gather), and shard_map's transpose of the pp-replicated wte/lm_head
    inputs inserts the psum that combines stage 0's embedding grad and the
    per-stage head grads.
  * fsdp composition (v2): with a real 'fsdp' axis the batch additionally
    shards over it (BATCH_AXES) and each stage's block leaves shard a
    non-layer axis over 'fsdp' (pipeline_param_specs); the body all-gathers
    each layer's weights inside the stage scan (ZeRO-3 streaming, same
    authored collective as parallel/shard_map_fsdp.py — AD emits the
    per-layer grad reduce-scatter as the gather's transpose).

The pipeline bubble is the standard (pp-1)/(M+pp-1) fraction of ticks;
`pipeline_microbatches` trades bubble against per-tick matmul size.

**1F1B** (`pipeline_schedule='1f1b'`, r5 — make_pipeline_loss_and_grad):
GPipe's activation stash grows with M (reverse AD of the tick scan saves
every tick's stage input). The 1F1B schedule bounds it at 2·pp slots,
M-INDEPENDENT, by running forward and backward in ONE loop — which reverse
AD cannot express, so the backward is written out: each tick every stage
does one forward (GPipe timing: F of microbatch m at stage s on tick m+s)
AND one backward (B of m at stage s on tick m+2·pp-1-s: recompute the
stage from its stashed INPUT via jax.vjp and pull the incoming cotangent
through), with bubble ticks masked. The loss stage runs the same
pp-scattered CE as GPipe per fresh microbatch and seeds the cotangent
stream; grads accumulate in-loop (blocks per-stage, wte by scatter-add,
lm_head from the CE pull), so nothing M-sized is ever stored. Memory bound
and loss/grad parity with GPipe are test-pinned (tests/test_pipeline.py).

Composes with 'data' and 'fsdp' (same per-layer gather streaming; the
gather's vjp IS the grad reduce-scatter). tp under 1F1B and sp under any
pipeline schedule are future work (config validation enforces this).
"""

from __future__ import annotations

import functools
import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams, _remat_policy
from midgpt_tpu.ops.norms import rms_norm
from midgpt_tpu.ops.rope import rope_table
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.mesh import BATCH_AXES
from midgpt_tpu.utils.compat import shard_map

Array = jax.Array


def pipeline_param_specs(
    params: tp.Any,
    mesh: tp.Optional[Mesh] = None,
    shard_model: bool = True,
    min_size: int = 2**18,
) -> tp.Any:
    """Specs for the GPipe schedule: block leaves shard their leading LAYER
    axis over 'pp'; with a real 'fsdp' mesh axis (and shard_model), large
    leaves additionally shard a non-layer axis over 'fsdp' (the same
    axis-choice rule as parallel/fsdp.py — exact divisibility required,
    since shard_map hands the body literal shards). With a real 'tp' axis
    the four block projections additionally shard their Megatron axis over
    'tp' (same name->axis table as parallel/tp.py, which the stacked leaves
    share since both carry the leading L) and fsdp moves to the OTHER
    feature axis; the embedding/lm_head stay tp-replicated (no
    vocab-parallel under pp — the pipeline CE runs on gathered heads).
    Works for params AND optimizer-state trees (path-keyed on 'blocks')."""
    from midgpt_tpu.parallel.fsdp import fsdp_leaf_spec
    from midgpt_tpu.parallel.tp import _leaf_name, megatron_leaf_axes

    n_fsdp = mesh.shape["fsdp"] if mesh is not None else 1
    n_tp = mesh.shape["tp"] if mesh is not None else 1

    def rule(path, x) -> P:
        names = [getattr(e, "name", None) or getattr(e, "key", None) for e in path]
        if "blocks" in names:
            if n_tp > 1:
                axes = megatron_leaf_axes(_leaf_name(path), x.shape, n_tp)
                # Stacked block leaves carry the leading layer axis, so the
                # Megatron axes (trailing) can never collide with slot 0 —
                # guarded anyway: fall through to the plain pp+fsdp rule.
                if axes is not None and 0 not in axes:
                    tp_ax, fsdp_ax = axes
                    spec: tp.List[tp.Any] = [None] * x.ndim
                    spec[0] = "pp"
                    spec[tp_ax] = "tp"
                    if (
                        shard_model
                        and n_fsdp > 1
                        and x.size > min_size
                        and x.shape[fsdp_ax] % n_fsdp == 0
                    ):
                        spec[fsdp_ax] = "fsdp"
                    return P(*spec)
            # layer axis reserved for 'pp'; fsdp picks among the rest
            spec = fsdp_leaf_spec(x, n_fsdp, shard_model, min_size, reserved_leading=1)
            spec[0] = "pp"
            return P(*spec)
        spec = fsdp_leaf_spec(x, n_fsdp, shard_model, min_size)
        return P(*spec) if any(e is not None for e in spec) else P()

    return jax.tree_util.tree_map_with_path(rule, params)


def _strip_tp(spec: P) -> P:
    """in_specs for the pipeline shard_map mention MANUAL axes only: 'tp'
    stays a GSPMD ('auto') axis inside the body, its sharding carried by the
    arrays themselves (make_pipeline_loss)."""
    def strip(entry):
        if entry == "tp":
            return None
        if isinstance(entry, tuple):
            kept = tuple(e for e in entry if e != "tp")
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return entry

    return P(*(strip(e) for e in spec))


def auto_tp_shard_map_kwargs(mesh: Mesh, param_specs):
    """(param_in_specs, extra_shard_map_kwargs) for the tp-as-auto-axis
    composition — ONE definition of the rule, used by the pipeline losses
    here and the explicit ZeRO-3 body (parallel/shard_map_fsdp.py): with a
    real 'tp' axis, strip it from in_specs (auto axes may not appear there)
    and exclude it from the manual axis_names so GSPMD authors the Megatron
    collectives inside the body; at tp=1 return the specs untouched and no
    extra kwargs, keeping that path byte-identical to the full-manual form
    (which also sidesteps an XLA CPU AllReducePromotion CHECK-crash on the
    partial-manual + bf16 combination)."""
    if mesh.shape["tp"] > 1:
        return (
            jax.tree.map(_strip_tp, param_specs),
            dict(
                axis_names=frozenset(mesh.axis_names) - {"tp"},
                check_vma=False,
            ),
        )
    return param_specs, {}


def gpipe_stage_apply(
    config: GPTConfig, stage_blocks, x: Array, rope, layer_transform=None
) -> Array:
    """Run this stage's (L/pp)-layer slice on one microbatch (Bm, T, D).

    `layer_transform` (optional) maps a layer's sharded block leaves to full
    ones — the fsdp all-gather hook; under remat the gather replays in the
    backward instead of keeping gathered weights alive (ZeRO-3)."""

    def block_fn(h, block):
        if layer_transform is not None:
            block = layer_transform(block)
        return (
            GPT.block_apply(config, block, h, key=None, inference=True, rope=rope),
            None,
        )

    if config.remat:
        block_fn = jax.checkpoint(block_fn, policy=_remat_policy(config.remat_policy))
    h, _ = jax.lax.scan(block_fn, x, stage_blocks, unroll=config.scan_unroll)
    return h


def make_pipeline_loss(
    model_cfg: GPTConfig,
    mesh: Mesh,
    param_specs,
    loss_chunk_tokens: int,
    loss_remat_chunks: tp.Optional[bool] = None,
    microbatches: int = 0,
) -> tp.Callable:
    """Build loss_fn(params, x, y, key) -> scalar running the GPipe schedule.

    Drop-in replacement for the GSPMD loss in make_train_step (same contract
    as make_shard_map_loss): GLOBAL (B, T) arrays in, global-mean scalar
    out, differentiable. `key` is accepted for interface compatibility but
    unused (pp requires dropout 0, enforced at config construction)."""
    pp = mesh.shape["pp"]
    M = microbatches or pp

    # fsdp gather plumbing (shared helpers with the explicit ZeRO-3 module):
    # per-layer block specs are the stacked specs minus the leading 'pp' axis.
    from midgpt_tpu.parallel.shard_map_fsdp import _drop_leading, _gather_leaf

    block_layer_specs = jax.tree.map(_drop_leading, param_specs.blocks)

    def gather_block(block):
        return jax.tree.map(_gather_leaf, block, block_layer_specs)

    def local_loss(params: GPTParams, x: Array, y: Array, key) -> Array:
        del key  # dropout 0 under pp (config validation)
        B, T = x.shape
        if B % M != 0 or B % pp != 0:
            raise ValueError(
                f"per-data-shard batch {B} must be divisible by both "
                f"pipeline_microbatches={M} and pp={pp} — lower them or "
                "raise batch_size (config-time validation can only check the "
                "global batch; this is the per-shard constraint)"
            )
        Bm = B // M
        s = jax.lax.axis_index("pp")
        rope = rope_table(model_cfg.head_dim, T)

        # Embedding on every stage (replicated compute — a cheap gather);
        # only stage 0's result enters the pipeline, so only stage 0
        # contributes wte grad (shard_map's pp-replicated-input transpose
        # psums over 'pp'; the fsdp gather transposes to reduce-scatter).
        full_wte = _gather_leaf(params.wte, param_specs.wte)
        full_head = _gather_leaf(params.lm_head, param_specs.lm_head)
        h = jnp.take(full_wte, x, axis=0)  # (B, T, D)
        x_mb = h.reshape(M, Bm, T, model_cfg.n_embd)

        n_ticks = M + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        stage_fn = functools.partial(
            gpipe_stage_apply, model_cfg, params.blocks, rope=rope,
            layer_transform=gather_block,
        )

        def tick(carry, t):
            recv, outs = carry
            mb = t - s  # microbatch index this stage serves at tick t
            inp = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(
                    x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
                ),
                recv,
            )
            out = stage_fn(inp)
            collect = (s == pp - 1) & (mb >= 0) & (mb < M)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, out.astype(outs.dtype), jnp.clip(mb, 0, M - 1), 0
            )
            outs = jnp.where(collect, upd, outs)
            send = jax.lax.ppermute(out, "pp", perm)
            return (send, outs), None

        init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
        (_, outs), _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))

        # v2 loss: scatter the collected outputs over 'pp' so the final-norm
        # + fused-CE matmul volume is 1× the batch, not pp×. Only the last
        # stage's buffer is nonzero, so the scatter-SUM is a broadcast-slice:
        # stage s receives rows [s·B/pp, (s+1)·B/pp). Each stage's CE is the
        # mean over its equal-size token slice; pmean over 'pp' recombines
        # the global mean. (Transpose: psum_scatter -> all_gather, so the
        # backward hands the full outs-cotangent to the last stage's stash.)
        shard = jax.lax.psum_scatter(
            outs.reshape(B, T, model_cfg.n_embd), "pp",
            scatter_dimension=0, tiled=True,
        )  # (B/pp, T, D)
        Bp = B // pp
        y_s = jax.lax.dynamic_slice_in_dim(y, s * Bp, Bp, axis=0)
        hidden = rms_norm(shard, eps=1e-5)
        loss = fused_linear_cross_entropy(
            hidden, full_head, y_s, loss_chunk_tokens, loss_remat_chunks
        )
        loss = jax.lax.pmean(loss, "pp")
        # global mean over the batch axes
        return jax.lax.pmean(loss, BATCH_AXES)

    batch_spec = P(BATCH_AXES, None)
    # tp composition (r5): 'tp' is deliberately NOT a manual axis — the
    # tick body stays written in pp/fsdp collectives only, while the
    # Megatron tp schedule rides GSPMD inside it (auto axis) — see
    # auto_tp_shard_map_kwargs.
    in_param_specs, extra = auto_tp_shard_map_kwargs(mesh, param_specs)
    return shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(in_param_specs, batch_spec, batch_spec, P()),
        out_specs=P(),
        **dict({"check_vma": False}, **extra),
    )


def make_pipeline_loss_and_grad(
    model_cfg: GPTConfig,
    mesh: Mesh,
    param_specs,
    loss_chunk_tokens: int,
    loss_remat_chunks: tp.Optional[bool] = None,
    microbatches: int = 0,
) -> tp.Callable:
    """1F1B schedule: loss_and_grad(params, x, y, key) -> (loss, grads).

    Reverse AD of the GPipe tick scan stashes EVERY tick's stage input —
    O(M) activations per stage. 1F1B interleaves forward and backward in
    one loop, which AD cannot express, so this function computes loss AND
    grads directly (the train step calls it instead of value_and_grad;
    module docstring has the schedule). Tick timing:

      F of microbatch m at stage s:  tick  m + s            (GPipe timing)
      CE + cotangent seed for m:     tick  m + pp - 1       (its last-stage F)
      B of microbatch m at stage s:  tick  m + 2*pp - 1 - s

    F at stage s lands on ticks == s (mod 1... both streams run every tick,
    masked); the stash slot for m is m % (2*pp): F_m is written at tick m+s
    and read back at tick m+2*pp-1-s, before F_{m+2*pp} rewrites the slot at
    tick m+2*pp+s — a 2*pp ring buffer regardless of M. B recomputes the
    stage from the stashed INPUT (jax.vjp), so activation memory is the
    stash + one in-flight vjp, and the per-layer fsdp gather's vjp emits the
    grad reduce-scatter exactly as in the GPipe path.

    Gradient bookkeeping (all in-loop, nothing M-sized): block grads
    accumulate per stage in f32; wte grads scatter-add token rows at stage
    0's B; lm_head grads accumulate from the CE pull. Final reductions match
    what shard_map AD inserts for the GPipe path: psum over 'data' (+ the
    fsdp batch contribution via reduce-scatter), psum over 'pp' for the
    replicated wte/lm_head, and a 1/(M * n_data * n_fsdp) scale pairing the
    per-tick cotangent seed (1/pp for the pp-scattered CE slices) with the
    loss's batch pmean."""
    pp = mesh.shape["pp"]
    M = microbatches or pp
    S = 2 * pp  # stash slots
    n_batch = mesh.shape["data"] * mesh.shape["fsdp"]

    from midgpt_tpu.parallel.shard_map_fsdp import (
        _drop_leading,
        _gather_leaf,
        _sharded_axis,
    )

    block_layer_specs = jax.tree.map(_drop_leading, param_specs.blocks)

    def gather_block(block):
        return jax.tree.map(_gather_leaf, block, block_layer_specs)

    def _reduce_to_spec(g: Array, spec: P) -> Array:
        """Full (gathered-layout) grad -> sharded layout: sum the fsdp batch
        shards' contributions and scatter per the param's fsdp axis."""
        ax = _sharded_axis(spec)
        if ax is None:
            return jax.lax.psum(g, "fsdp") if mesh.shape["fsdp"] > 1 else g
        return jax.lax.psum_scatter(g, "fsdp", scatter_dimension=ax, tiled=True)

    def local_loss_and_grad(params: GPTParams, x: Array, y: Array, key):
        del key  # dropout 0 under pp (config validation)
        B, T = x.shape
        if B % M != 0 or B % pp != 0 or (B // M) % pp != 0:
            raise ValueError(
                f"per-data-shard batch {B} must be divisible by "
                f"pipeline_microbatches={M} (and each microbatch by pp={pp} "
                "for the scattered CE) — lower them or raise batch_size"
            )
        Bm = B // M
        Bmp = Bm // pp
        s = jax.lax.axis_index("pp")
        rope = rope_table(model_cfg.head_dim, T)
        f32 = jnp.float32

        full_wte = _gather_leaf(params.wte, param_specs.wte)
        full_head = _gather_leaf(params.lm_head, param_specs.lm_head)
        x_tok = x.reshape(M, Bm, T)
        y_mb = y.reshape(M, Bm, T)
        # NO up-front (M, Bm, T, D) embedding buffer (GPipe embeds the whole
        # batch before its scan): stage 0 embeds ONE microbatch per tick
        # inside the loop, keeping the schedule's memory M-independent —
        # only the int32 token ids are M-sized.

        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        stage_fn = functools.partial(
            gpipe_stage_apply, model_cfg, rope=rope, layer_transform=gather_block
        )

        def ce_fn(shard, head, y_slice):
            hidden = rms_norm(shard, eps=1e-5)
            return fused_linear_cross_entropy(
                hidden, head, y_slice, loss_chunk_tokens, loss_remat_chunks
            )

        act_shape = (Bm, T, model_cfg.n_embd)
        act_dtype = full_wte.dtype
        gblocks0 = jax.tree.map(lambda p: jnp.zeros(p.shape, f32), params.blocks)
        carry0 = dict(
            stash=jnp.zeros((S,) + act_shape, act_dtype),
            fwd_recv=jnp.zeros(act_shape, act_dtype),
            bwd_recv=jnp.zeros(act_shape, f32),
            dh_pend=jnp.zeros(act_shape, f32),
            gblocks=gblocks0,
            dwte=jnp.zeros(full_wte.shape, f32),
            dhead=jnp.zeros(full_head.shape, f32),
            loss=jnp.zeros((), f32),
        )
        n_ticks = M + 2 * pp - 1

        def tick(c, t):
            # ---- forward stream: F of mf = t - s at this stage
            mf = t - s
            f_valid = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            tok_f = jax.lax.dynamic_index_in_dim(x_tok, mf_c, 0, keepdims=False)
            inp = jnp.where(
                s == 0,
                jnp.take(full_wte, tok_f, axis=0).astype(act_dtype),
                c["fwd_recv"],
            )
            out = stage_fn(params.blocks, inp)
            slot_f = mf_c % S
            stash = jax.lax.dynamic_update_index_in_dim(
                c["stash"],
                jnp.where(f_valid, inp, c["stash"][slot_f]),
                slot_f,
                0,
            )

            # ---- CE + cotangent seed for the microbatch finishing this tick
            mf_last = t - (pp - 1)  # uniform scalar across stages
            ce_valid = (mf_last >= 0) & (mf_last < M)
            mf_last_c = jnp.clip(mf_last, 0, M - 1)
            o_ce = jnp.where(s == pp - 1, out, jnp.zeros_like(out))
            shard = jax.lax.psum_scatter(
                o_ce, "pp", scatter_dimension=0, tiled=True
            )  # (Bm/pp, T, D)
            y_m = jax.lax.dynamic_index_in_dim(y_mb, mf_last_c, 0, keepdims=False)
            y_slice = jax.lax.dynamic_slice_in_dim(y_m, s * Bmp, Bmp, axis=0)
            lm, pull_ce = jax.vjp(lambda sh, hd: ce_fn(sh, hd, y_slice), shard, full_head)
            lm = jax.lax.pmean(lm, "pp")
            dshard, dhead_m = pull_ce(jnp.asarray(1.0 / pp, lm.dtype))
            dh_full = jax.lax.all_gather(
                dshard.astype(f32), "pp", axis=0, tiled=True
            )  # (Bm, T, D)
            loss = c["loss"] + jnp.where(ce_valid, lm.astype(f32), 0.0)
            dhead = c["dhead"] + jnp.where(ce_valid, dhead_m.astype(f32), 0.0)

            # ---- backward stream: B of mb = t - 2*pp + 1 + s at this stage
            mb = t - 2 * pp + 1 + s
            b_valid = (mb >= 0) & (mb < M)
            mb_c = jnp.clip(mb, 0, M - 1)
            inp_b = c["stash"][mb_c % S]
            cot = jnp.where(s == pp - 1, c["dh_pend"], c["bwd_recv"])
            _, pull_stage = jax.vjp(
                lambda bl, ii: stage_fn(bl, ii), params.blocks, inp_b
            )
            dbl, dinp = pull_stage(cot.astype(out.dtype))
            bm = b_valid.astype(f32)
            gblocks = jax.tree.map(
                lambda g, d: g + d.astype(f32) * bm, c["gblocks"], dbl
            )
            tok_b = jax.lax.dynamic_index_in_dim(x_tok, mb_c, 0, keepdims=False)
            dinp32 = dinp.astype(f32) * (bm * (s == 0).astype(f32))
            dwte = c["dwte"].at[tok_b.reshape(-1)].add(
                dinp32.reshape(-1, dinp32.shape[-1])
            )

            # ---- sends
            new_c = dict(
                stash=stash,
                fwd_recv=jax.lax.ppermute(out, "pp", perm_fwd),
                bwd_recv=jax.lax.ppermute(dinp.astype(f32), "pp", perm_bwd),
                dh_pend=jnp.where(ce_valid, dh_full, jnp.zeros_like(dh_full)),
                gblocks=gblocks,
                dwte=dwte,
                dhead=dhead,
                loss=loss,
            )
            return new_c, None

        c, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))

        scale = 1.0 / (M * n_batch)
        loss = jax.lax.pmean(c["loss"] / M, BATCH_AXES)

        # blocks: the batch shards over BOTH 'data' and 'fsdp'. For
        # fsdp-SHARDED leaves the gather's vjp already reduce-scattered the
        # fsdp contributions; fsdp-REPLICATED leaves (below fsdp_min_size,
        # shard_model=False, or no divisible axis — e.g. q/k scales) still
        # hold only this rank's batch contribution and need the psum that
        # shard_map AD inserts for the GPipe path. Then sum the data shards
        # and apply the loss-mean scale.
        def block_reduce(g, spec):
            if mesh.shape["fsdp"] > 1 and _sharded_axis(spec) is None:
                g = jax.lax.psum(g, "fsdp")
            if mesh.shape["data"] > 1:
                g = jax.lax.psum(g, "data")
            return g * scale

        gblocks = jax.tree.map(block_reduce, c["gblocks"], param_specs.blocks)
        # wte / lm_head: only stage 0 / the CE contribute (masked), so the
        # pp-psum collects them; data-psum + fsdp reduce-scatter as above.
        def emb_reduce(g, spec):
            g = jax.lax.psum(g, "pp")
            if mesh.shape["data"] > 1:
                g = jax.lax.psum(g, "data")
            return _reduce_to_spec(g, spec) * scale

        grads = GPTParams(
            wte=emb_reduce(c["dwte"], param_specs.wte),
            blocks=gblocks,
            lm_head=emb_reduce(c["dhead"], param_specs.lm_head),
        )
        return loss, grads

    batch_spec = P(BATCH_AXES, None)
    return shard_map(
        local_loss_and_grad,
        mesh=mesh,
        in_specs=(param_specs, batch_spec, batch_spec, P()),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
