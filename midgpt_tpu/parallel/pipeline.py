"""GPipe pipeline parallelism over the mesh 'pp' axis.

Beyond the reference's capability set (its only model sharding is FSDP,
reference model.py:167-178). The design falls out of this framework's
model representation: block parameters are already STACKED along a leading
layer axis (models/gpt.py), so a pipeline stage is nothing more than that
axis sharded over 'pp' — stage s holds the (L/pp, ...) slice of every block
leaf, and shard_map hands it each stage's slice with zero data movement.

Schedule (classic GPipe, SPMD-expressed — every stage runs the SAME
program every tick; there is no per-stage control flow to trace):

  * the step's local batch is split into M microbatches; the embedded
    activations (M, Bm, T, D) are visible to every stage (the 'pp' axis is
    replicated for activations — only stage 0's use of them is real);
  * one `lax.scan` runs M + pp - 1 ticks. Each tick, every stage runs its
    layer slice on one activation: stage 0 reads microbatch t from the
    input stream, stage s>0 reads what stage s-1 ppermuted to it last tick.
    Tick outputs ride a single neighbor `ppermute`; the last stage collects
    its finished microbatches into an output buffer by a masked
    dynamic-index update (bubble ticks compute on garbage that is never
    collected — static shapes, no `lax.cond`);
  * loss: the last stage runs final-norm + fused CE on its collected
    outputs; a `psum` over 'pp' of the masked per-stage value broadcasts
    the scalar. Reverse-mode AD through the tick scan + ppermute IS the
    GPipe backward schedule (ppermute transposes to the reverse
    permutation; the scan's saved residuals are the activation stash), and
    shard_map's transpose of the replicated wte/lm_head inputs inserts the
    psum that combines stage 0's embedding grad and the last stage's head
    grad.

The pipeline bubble is the standard (pp-1)/(M+pp-1) fraction of ticks;
`pipeline_microbatches` trades bubble against per-tick matmul size.

v1 composes with the 'data' axis (batch sharding); fsdp/sp/tp sharding of
the per-stage weights is future work (config validation enforces this).
"""

from __future__ import annotations

import functools
import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams, _remat_policy
from midgpt_tpu.ops.norms import rms_norm
from midgpt_tpu.ops.rope import rope_table
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.mesh import BATCH_AXES

Array = jax.Array


def pipeline_param_specs(params: tp.Any) -> tp.Any:
    """Specs for the GPipe schedule: block leaves shard their leading LAYER
    axis over 'pp'; everything else replicated (v1 — see module docstring).
    Works for params AND optimizer-state trees (path-keyed on 'blocks')."""

    def rule_blocks(x) -> P:
        spec: tp.List[tp.Any] = [None] * x.ndim
        spec[0] = "pp"
        return P(*spec)

    def rule(path, x) -> P:
        names = [getattr(e, "name", None) or getattr(e, "key", None) for e in path]
        if "blocks" in names:
            return rule_blocks(x)
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)


def gpipe_stage_apply(
    config: GPTConfig, stage_blocks, x: Array, rope
) -> Array:
    """Run this stage's (L/pp)-layer slice on one microbatch (Bm, T, D)."""

    def block_fn(h, block):
        return (
            GPT.block_apply(config, block, h, key=None, inference=True, rope=rope),
            None,
        )

    if config.remat:
        block_fn = jax.checkpoint(block_fn, policy=_remat_policy(config.remat_policy))
    h, _ = jax.lax.scan(block_fn, x, stage_blocks, unroll=config.scan_unroll)
    return h


def make_pipeline_loss(
    model_cfg: GPTConfig,
    mesh: Mesh,
    param_specs,
    loss_chunk_tokens: int,
    loss_remat_chunks: tp.Optional[bool] = None,
    microbatches: int = 0,
) -> tp.Callable:
    """Build loss_fn(params, x, y, key) -> scalar running the GPipe schedule.

    Drop-in replacement for the GSPMD loss in make_train_step (same contract
    as make_shard_map_loss): GLOBAL (B, T) arrays in, global-mean scalar
    out, differentiable. `key` is accepted for interface parity but unused
    (pp requires dropout 0, enforced at config construction)."""
    pp = mesh.shape["pp"]
    M = microbatches or pp

    def local_loss(params: GPTParams, x: Array, y: Array, key) -> Array:
        del key  # dropout 0 under pp (config validation)
        B, T = x.shape
        if B % M != 0:
            raise ValueError(
                f"per-data-shard batch {B} not divisible by "
                f"pipeline_microbatches={M} — lower pipeline_microbatches or "
                "raise batch_size (config-time validation can only check the "
                "global batch; this is the per-shard constraint)"
            )
        Bm = B // M
        s = jax.lax.axis_index("pp")
        rope = rope_table(model_cfg.head_dim, T)

        # Embedding on every stage (replicated compute); only stage 0's
        # result enters the pipeline, so only stage 0 contributes wte grad
        # (shard_map's replicated-input transpose psums over 'pp').
        h = jnp.take(params.wte, x, axis=0)  # (B, T, D)
        x_mb = h.reshape(M, Bm, T, model_cfg.n_embd)

        n_ticks = M + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        stage_fn = functools.partial(
            gpipe_stage_apply, model_cfg, params.blocks, rope=rope
        )

        def tick(carry, t):
            recv, outs = carry
            mb = t - s  # microbatch index this stage serves at tick t
            inp = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(
                    x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
                ),
                recv,
            )
            out = stage_fn(inp)
            collect = (s == pp - 1) & (mb >= 0) & (mb < M)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, out.astype(outs.dtype), jnp.clip(mb, 0, M - 1), 0
            )
            outs = jnp.where(collect, upd, outs)
            send = jax.lax.ppermute(out, "pp", perm)
            return (send, outs), None

        init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
        (_, outs), _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))

        # Final norm + fused CE on the last stage's collected outputs; the
        # masked psum broadcasts the scalar to all stages. Other stages'
        # outs are zeros — their loss value is discarded by the mask, and
        # its cotangent is zero, so no garbage gradients flow.
        hidden = rms_norm(outs.reshape(B, T, model_cfg.n_embd), eps=1e-5)
        loss = fused_linear_cross_entropy(
            hidden, params.lm_head, y, loss_chunk_tokens, loss_remat_chunks
        )
        loss = jnp.where(s == pp - 1, loss, 0.0)
        loss = jax.lax.psum(loss, "pp")
        # global mean over the batch axes
        return jax.lax.pmean(loss, BATCH_AXES)

    batch_spec = P(BATCH_AXES, None)
    return jax.shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(param_specs, batch_spec, batch_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
