"""FSDP parameter-sharding rules over the ('data', 'fsdp', 'sp', 'tp') mesh.

Rule (generalizing reference model.py:167-178): every array leaf with
size > min_size is sharded along one axis over mesh axis 'fsdp'; everything
else (QK-norm scales, scalars) is replicated. Applied as
`with_sharding_constraint` inside jit — at sharded init, to grads each
microstep, and to the updated params — so XLA GSPMD materializes the FSDP
schedule: all-gather params for fwd/bwd, reduce-scatter grads, all without
ever materializing a full replica of the big leaves.

Axis choice is smarter than the reference's hard-coded last axis: we pick the
largest axis divisible by the mesh size, preferring the trailing (lane) axis.
For stacked block leaves (leading n_layer axis) this naturally lands on the
embed/hidden axes. A leaf with no divisible axis falls back to replicated
rather than crashing (the reference would fail in GSPMD).
"""

from __future__ import annotations

import typing as tp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array

STACKED_AXIS_HINT = 0  # leading axis of stacked block params is the layer axis


def _choose_axis(shape: tp.Tuple[int, ...], n_shards: int, skip_leading: bool) -> tp.Optional[int]:
    """Pick the axis to shard: prefer the last, then the largest divisible."""
    ndim = len(shape)
    candidates = [ax for ax in range(ndim - 1, -1, -1) if shape[ax] % n_shards == 0]
    if skip_leading and ndim > 1:
        candidates = [ax for ax in candidates if ax != 0] or candidates
    if not candidates:
        return None
    # Last axis if it qualifies (best for TPU lane layout), else the largest.
    if candidates[0] == ndim - 1:
        return ndim - 1
    return max(candidates, key=lambda ax: shape[ax])


def fsdp_leaf_spec(
    x,
    n_shards: int,
    shard_model: bool = True,
    min_size: int = 2**18,
    reserved_leading: int = 0,
) -> tp.List[tp.Any]:
    """THE per-leaf FSDP rule (single source — the pp spec rule reuses it):
    size gate, then axis choice over the non-reserved axes. Returns a
    mutable spec list so callers (pipeline_param_specs) can fill the
    reserved leading slots before building the PartitionSpec."""
    spec: tp.List[tp.Any] = [None] * x.ndim
    if shard_model and n_shards > 1 and x.size > min_size:
        ax = _choose_axis(
            tuple(x.shape[reserved_leading:]),
            n_shards,
            skip_leading=reserved_leading == 0,
        )
        if ax is not None:
            spec[ax + reserved_leading] = "fsdp"
    return spec


def fsdp_param_specs(
    params: tp.Any,
    mesh: Mesh,
    shard_model: bool = True,
    min_size: int = 2**18,
) -> tp.Any:
    """Pytree of PartitionSpecs matching `params`."""
    n_shards = mesh.shape["fsdp"]

    def rule(x) -> P:
        spec = fsdp_leaf_spec(x, n_shards, shard_model, min_size)
        return P(*spec) if any(e is not None for e in spec) else P()

    return jax.tree.map(rule, params)


def named_shardings(specs: tp.Any, mesh: Mesh) -> tp.Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


def constrain(tree: tp.Any, specs: tp.Any, mesh: Mesh) -> tp.Any:
    """with_sharding_constraint over a pytree (inside jit)."""
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree,
        specs,
    )
