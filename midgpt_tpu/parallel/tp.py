"""Tensor parallelism: Megatron column/row sharding of the block projections.

Beyond the reference's capability set (its only model sharding is FSDP,
reference model.py:167-178); added for model families too big for FSDP-only.
The GPT block has exactly four projections, and the classic Megatron-LM
schedule falls out of sharding them over the mesh 'tp' axis:

  column-parallel (shard the OUTPUT features):
    wqkv  (L, 3, D, D) -> P(None, None, 'tp', 'fsdp')   whole heads per
        shard: the explicit leading q/k/v axis (models/gpt.py
        AttentionParams) means each of q, k, v is column-sharded
        independently on its own D = H*C head-major feature axis — shard
        boundaries never straddle q/k/v or split a head. (Requires the
        'split3' QKV lowering, auto-selected by the runtime under tp > 1.)
    wkv   (L, 2, H_kv*C, D) -> P(None, None, 'tp', 'fsdp')   GQA K/V
        projection (models/gpt.py): same rule on the KV-head-major output
        axis; each shard keeps H_kv/tp whole KV heads, matching the
        H_q/tp = groups * H_kv/tp query heads of its wqkv shard.
    w_up  (L, 4D, D)   -> P(None, 'tp', 'fsdp')   whole MLP columns per shard
  row-parallel (shard the INPUT / contraction features):
    wo     (L, D, D)  -> P(None, 'fsdp', 'tp')
    w_down (L, D, 4D) -> P(None, 'fsdp', 'tp')

Everything between a column-parallel and its matching row-parallel matmul
(QK-norm, RoPE, attention itself, the GELU) is pointwise in the sharded
feature/head axis, so GSPMD propagates the shard through with zero
collectives; the row-parallel contraction produces partial sums and the
residual-add's replicated requirement makes XLA place exactly the one
all-reduce per half-block that Megatron prescribes.

With `vocab_parallel` (the default when tp > 1, config field `tp_vocab`)
the embedding and lm_head also shard their VOCAB axis over 'tp' — the
Megatron vocab-parallel schedule: the token-embedding lookup becomes a
masked local gather + all-reduce, and the fused CE loss's per-chunk
reductions (max / sum-exp / label-logit gather, ops/loss.py) reduce over
the sharded vocab axis with (chunk,)-sized psums. Each tp shard then holds
only V/tp x D of the two largest leaves in the model. Everything is
expressed through these specs; GSPMD authors the collectives.

FSDP composes on the leaf's OTHER feature axis: each tp shard's weights are
further sharded/gathered over 'fsdp', i.e. standard 2D (tp × zero-3) layout.

Specs are path-keyed on the leaf field names (wqkv/wo/w_up/w_down), so the
same rule covers params AND optimizer state (mu/nu mirror the param tree).
"""

from __future__ import annotations

import typing as tp

import jax
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.parallel.fsdp import fsdp_param_specs

# leaf field name -> axis (from the end) that shards over 'tp'
# wkv is the GQA K/V projection (L, 2, H_kv*C, D): same column rule on its
# own (smaller) head-major output axis — requires n_kv_heads % tp == 0
# (config.py validates; megatron_leaf_axes returns None otherwise), so each
# shard holds whole KV-head groups and attention stays collective-free.
_COLUMN_PARALLEL = {"wqkv": 2, "wkv": 2, "w_up": 2}  # output features = axis -2
_ROW_PARALLEL = {"wo": 1, "w_down": 1}  # input features = axis -1
_VOCAB_PARALLEL = {"wte": 2, "lm_head": 2}  # vocab axis = axis -2 of (V, D)
# MoE expert leaves (models/gpt.py MoEParams): the E axis sits after the
# stacked layer axis — axis 1 of (L, E, ...). Sharded over 'ep'.
_EXPERT_PARALLEL = ("experts_up", "experts_down")


def megatron_leaf_axes(
    name: str, shape: tp.Tuple[int, ...], n_tp: int
) -> tp.Optional[tp.Tuple[int, int]]:
    """(tp_ax, fsdp_ax) for a Megatron-shardable leaf, or None.

    THE axis-selection rule, shared by tp_param_specs and the pipeline's
    pp×tp spec rule (parallel/pipeline.py) so the two layouts cannot
    silently diverge: tp on the column/row-parallel axis per the tables
    above, fsdp composing on the leaf's OTHER trailing feature axis."""
    off = _COLUMN_PARALLEL.get(name) or _ROW_PARALLEL.get(name)
    ndim = len(shape)
    if off is None or ndim < 2:
        return None
    tp_ax = ndim - off
    if shape[tp_ax] % n_tp != 0:
        return None
    fsdp_ax = ndim - 1 if tp_ax == ndim - 2 else ndim - 2
    return tp_ax, fsdp_ax


def _leaf_name(path: tp.Tuple[tp.Any, ...]) -> str:
    """Last attribute-ish component of a pytree path."""
    for entry in reversed(path):
        name = getattr(entry, "name", None) or getattr(entry, "key", None)
        if isinstance(name, str):
            return name
    return ""


def tp_param_specs(
    params: tp.Any,
    mesh: Mesh,
    shard_model: bool = True,
    min_size: int = 2**18,
    vocab_parallel: bool = True,
) -> tp.Any:
    """Pytree of PartitionSpecs: Megatron 'tp' on the four block projections
    (composed with 'fsdp' on their other feature axis) and — with
    `vocab_parallel` — on the vocab axis of wte/lm_head; the plain FSDP rule
    (parallel/fsdp.py) everywhere else. With mesh tp=1 this IS the FSDP rule."""
    n_tp = mesh.shape["tp"]
    n_fsdp = mesh.shape["fsdp"]
    n_ep = mesh.shape["ep"]
    base = fsdp_param_specs(params, mesh, shard_model, min_size)
    if n_tp == 1 and n_ep == 1:
        return base

    def rule(path, x, base_spec):
        name = _leaf_name(path)
        if n_ep > 1 and name in _EXPERT_PARALLEL:
            # stacked (L, E, feat, feat): 'ep' on the expert axis, fsdp
            # composing on the trailing feature axis when it divides.
            if x.ndim >= 3 and x.shape[1] % n_ep == 0:
                spec: tp.List[tp.Any] = [None] * x.ndim
                spec[1] = "ep"
                if (
                    shard_model
                    and n_fsdp > 1
                    and x.size > min_size
                    and x.shape[-1] % n_fsdp == 0
                ):
                    spec[-1] = "fsdp"
                return P(*spec)
            return base_spec
        if n_tp == 1:
            return base_spec
        axes = megatron_leaf_axes(name, x.shape, n_tp)
        if axes is None:
            if not (vocab_parallel and name in _VOCAB_PARALLEL):
                return base_spec
            tp_ax = x.ndim - _VOCAB_PARALLEL[name]
            if x.ndim < 2 or x.shape[tp_ax] % n_tp != 0:
                return base_spec
            fsdp_ax = x.ndim - 1 if tp_ax == x.ndim - 2 else x.ndim - 2
        else:
            tp_ax, fsdp_ax = axes
        spec: tp.List[tp.Any] = [None] * x.ndim
        spec[tp_ax] = "tp"
        if (
            shard_model
            and n_fsdp > 1
            and x.size > min_size
            and x.shape[fsdp_ax] % n_fsdp == 0
        ):
            spec[fsdp_ax] = "fsdp"
        return P(*spec)

    return jax.tree_util.tree_map_with_path(rule, params, base)
