"""Explicit shard_map FSDP: authored per-layer all-gather / grad reduce-scatter.

The GSPMD path (parallel/fsdp.py) matches the reference's approach — sharding
constraints in, compiler-chosen collectives out (reference model.py:167-178,
train.py:87). This module is the TPU-first redesign: the FSDP schedule is
*written down* instead of inferred.

  * Params enter `jax.shard_map` still sharded (in_specs = their FSDP specs).
  * The embedding and lm_head are all-gathered once per step.
  * Each block's weights are all-gathered INSIDE the layer scan
    (`layer_transform` hook in GPT.hidden) — classic ZeRO-3 streaming: at any
    moment only one layer's full weights exist per device. Under the
    per-block `jax.checkpoint` the gather replays in the backward pass
    (re-gather instead of keeping gathered weights alive).
  * Gradients need no hand-written collective at all: the transpose rule of
    `all_gather(axis='fsdp', tiled=True)` IS `psum_scatter` over 'fsdp', so
    AD emits exactly the per-layer grad reduce-scatter ZeRO-3 prescribes,
    and shard_map's replication tracking inserts the `psum` over 'data' for
    the data-parallel grad reduction.
  * The loss is a `pmean` over ('data', 'fsdp') — the only explicit
    collective in the module besides the gathers.

Gather/compute overlap is pinned, not assumed (r5):
  * tests/test_shard_map_fsdp.py::test_zero3_gathers_schedulable_ahead_of_compute
    asserts the dataflow precondition on the compiled step — at
    scan_unroll=2 no weight gather in the scan body depends on the body's
    compute, so the scheduler is free to issue layer l+1's gathers during
    layer l.
  * tools/check_overlap_tpu.py AOT-compiles this step for a v5e:2x4
    topology and asserts the TPU compiler actually exploits that freedom:
    the body's weight gathers become async (annotated
    async_collective_name="all-gather-start") or are continuation-FUSED
    into the block matmul kernels (gather windows streamed inside the dots,
    forward and backward). Measured result in RESULTS.md §3a. NOTE: that
    requires xla_tpu_enable_latency_hiding_scheduler=true — NOT default-on
    in this toolchain; real-pod launches should set it (docs/PARALLELISM.md).

Numerical parity with the GSPMD path is asserted in
tests/test_shard_map_fsdp.py (same loss and same grads to fp32 tolerance on
the 8-device CPU mesh).
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.models.gpt import GPT, GPTParams
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.mesh import BATCH_AXES
from midgpt_tpu.utils.compat import axis_size, shard_map

Array = jax.Array


def _sharded_axis(spec: P) -> tp.Optional[int]:
    """Index of the axis a spec shards over 'fsdp', or None if replicated."""
    for ax, names in enumerate(spec):
        if names == "fsdp" or (isinstance(names, tuple) and "fsdp" in names):
            return ax
    return None


def _gather_leaf(x: Array, spec: P) -> Array:
    ax = _sharded_axis(spec)
    if ax is None:
        return x
    return jax.lax.all_gather(x, "fsdp", axis=ax, tiled=True)


def _drop_leading(spec: P) -> P:
    """Spec for one layer's slice of a stacked (n_layer, ...) leaf."""
    return P(*spec[1:]) if len(spec) else spec


def make_shard_map_loss(
    model_cfg,
    mesh: Mesh,
    param_specs,
    loss_chunk_tokens: int,
    loss_remat_chunks: tp.Optional[bool] = None,
    sequence_parallel: tp.Optional[str] = None,
) -> tp.Callable:
    """Build loss_fn(params, x, y, key) -> scalar with authored collectives.

    Drop-in replacement for the GSPMD loss in make_train_step: takes GLOBAL
    arrays, returns the global-mean loss; differentiable (grads come back in
    the params' sharded layout).

    `sequence_parallel` ('ring' | 'ulysses' | None) additionally shards the
    batch's T axis over the mesh's 'sp' axis and runs the named
    context-parallel attention schedule — ZeRO-3 and SP compose inside ONE
    shard_map body: per-layer weight all-gathers ride the 'fsdp' axis while
    the attention collectives ride 'sp' (K/V ppermute rotation for the ring,
    head<->sequence all_to_all for Ulysses), with no nesting. Everything
    else in the backbone is token-pointwise, needing only shard-aware RoPE
    positions (GPT.hidden positions/rope_len)."""
    if sequence_parallel not in (None, "ring", "ulysses"):
        raise ValueError(f"unknown sequence_parallel {sequence_parallel!r}")
    block_specs = jax.tree.map(_drop_leading, param_specs.blocks)

    def gather_block(block):
        return jax.tree.map(_gather_leaf, block, block_specs)

    loss_axes = BATCH_AXES + ("sp",) if sequence_parallel else BATCH_AXES

    def local_loss(params: GPTParams, x: Array, y: Array, key) -> Array:
        if key is not None:
            # decorrelate dropout masks across batch (and sequence) shards
            key = jax.random.fold_in(key, jax.lax.axis_index(loss_axes))
        full_wte = _gather_leaf(params.wte, param_specs.wte)
        full_head = _gather_leaf(params.lm_head, param_specs.lm_head)
        gathered = GPTParams(
            wte=full_wte, blocks=params.blocks, lm_head=full_head
        )
        positions = rope_len = attn_fn = None
        if sequence_parallel:
            Tl = x.shape[1]
            rope_len = Tl * axis_size("sp")
            positions = jax.lax.axis_index("sp") * Tl + jnp.arange(Tl)
            if sequence_parallel == "ring":
                from midgpt_tpu.parallel.ring_attention import ring_attention

                attn_fn = lambda q, k, v: ring_attention(q, k, v, "sp")
            else:
                from midgpt_tpu.parallel.ulysses import ulysses_attention

                attn_fn = lambda q, k, v: ulysses_attention(
                    q, k, v, "sp",
                    block_size=model_cfg.attn_block_size,
                    impl="flash",
                )
        h = GPT.hidden(
            model_cfg,
            gathered,
            x,
            key=key,
            inference=key is None,
            layer_transform=gather_block,
            attn_fn=attn_fn,
            positions=positions,
            rope_len=rope_len,
        )
        # local mean over an equal-size token shard -> pmean is the global
        # mean (batch shards over data/fsdp, sequence shards over sp)
        loss = fused_linear_cross_entropy(h, full_head, y, loss_chunk_tokens, loss_remat_chunks)
        return jax.lax.pmean(loss, loss_axes)

    batch_spec = P(BATCH_AXES, "sp" if sequence_parallel else None)
    # tp composition (r5): same split as the pipeline's pp×tp — 'tp' stays
    # a GSPMD auto axis, so the authored ZeRO-3 gathers/reduce-scatters
    # keep riding 'fsdp' while the Megatron column/row schedule (specs from
    # parallel/tp.py, split3 QKV lowering auto-selected by the runtime) is
    # inserted by GSPMD inside the body. The kwargs builder
    # (parallel/pipeline.py auto_tp_shard_map_kwargs, shared) strips 'tp'
    # from in_specs and the manual axis set only when tp>1 — the tp=1 path
    # stays byte-identical (the partial-manual form also trips an XLA CPU
    # AllReducePromotion crash on bf16; config validation keeps
    # ring/ulysses out of the tp combination for now).
    from midgpt_tpu.parallel.pipeline import auto_tp_shard_map_kwargs

    in_specs, extra = auto_tp_shard_map_kwargs(mesh, param_specs)
    return shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(in_specs, batch_spec, batch_spec, P()),
        out_specs=P(),
        **extra,
    )
