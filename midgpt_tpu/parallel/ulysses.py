"""All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

The second context-parallel schedule next to the ring
(parallel/ring_attention.py), selectable per config (attn_impl='ulysses').
Where the ring keeps queries local and rotates K/V shards n-1 hops around
the 'sp' axis, Ulysses re-shards ONCE: an all-to-all trades the sequence
sharding for a head sharding, every device then runs ordinary dense causal
attention over the FULL sequence for its H/n heads (the same Pallas flash
kernel as the single-device path — no per-pair decomposition at all), and a
second all-to-all restores the sequence sharding.

Trade-offs vs the ring (why both exist):
  * collectives: 2 all-to-alls of the local shard vs 2(n-1) neighbor
    ppermutes — Ulysses wins on latency for moderate n on all-to-all-capable
    interconnects (TPU ICI is), the ring wins on very large n where its
    traffic stays neighbor-only and overlaps with per-pair compute.
  * memory: Ulysses materializes full-T attention inputs for H/n heads
    (activation O(T·H/n·C) = same total as the ring's O(T/n·H·C)); but its
    attention is one dense kernel call, so the kernel's own O(T) statistics
    apply, not O(T/n).
  * constraint: needs n_head divisible by the sp size (whole heads per
    device); the ring has no head constraint.

Differentiation needs no custom VJP: `all_to_all` is its own transpose, and
the inner attention is the already-differentiable dispatcher (custom-VJP
flash kernel on TPU, blockwise jnp elsewhere).

Use `ulysses_attention` inside shard_map; `ulysses_attention_sharded`
applies the shard_map given a mesh (same contract as the ring wrapper,
including `head_axis='tp'` composition — heads then shard over tp x sp).
"""

from __future__ import annotations

import typing as tp

import jax
from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.ops.attention import multihead_attention
from midgpt_tpu.utils.compat import axis_size, shard_map

Array = jax.Array


def ulysses_attention(
    q: Array,  # (B, H, Tl, C) local sequence shard
    k: Array,
    v: Array,
    axis_name: str,
    block_size: int = 512,
    impl: str = "flash",
) -> Array:
    """Causal attention across the `axis_name` group. Call inside shard_map.

    Shards are contiguous sequence chunks in axis order (what sharding the
    T axis over `axis_name` produces); heads must divide the axis size."""
    n = axis_size(axis_name)
    if n > 1:
        if q.shape[1] % n != 0:
            # ValueError (not assert): direct callers bypass the
            # ExperimentConfig validation, and `python -O` strips asserts —
            # the failure would otherwise surface as an opaque all_to_all
            # shape error.
            raise ValueError(
                f"n_head={q.shape[1]} not divisible by {axis_name} size {n}"
            )
        # trade sequence sharding for head sharding: (B, H/n, T, C)
        q, k, v = (
            jax.lax.all_to_all(a, axis_name, split_axis=1, concat_axis=2, tiled=True)
            for a in (q, k, v)
        )
    # inference=True here only disables dropout inside the dispatcher — and
    # no dropout can ever reach this path: the fused impls define none
    # (ops/attention.py raises NotImplementedError), GPT._attention refuses
    # to inject an attn_fn when training with dropout>0, and config
    # validation rejects attn_impl='ulysses' + dropout up front. Three
    # guards, so this flag is not load-bearing for train/eval semantics.
    out = multihead_attention(
        q, k, v, impl=impl, inference=True, block_size=block_size, layout="bhtc"
    )
    if n > 1:
        # restore the sequence sharding: (B, H, Tl, C)
        out = jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)
    return out


def ulysses_attention_sharded(
    q: Array,  # (B, H, T, C) global arrays, T sharded (or shardable) over sp
    k: Array,
    v: Array,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axes: tp.Tuple[str, ...] = ("data", "fsdp"),
    block_size: int = 512,
    head_axis: tp.Optional[str] = None,
    impl: str = "flash",
) -> Array:
    """shard_map wrapper, same contract as ring_attention_sharded: shards T
    over `axis_name` (and heads over `head_axis`, e.g. 'tp'), returns the
    (B, H, T, C) result with the same layout. `impl` selects the inner dense
    attention ('flash' kernel-dispatched; 'blockwise'/'naive' for debug)."""
    spec = P(batch_axes, head_axis, axis_name, None)
    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name, block_size, impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
