"""610M wide-head (C=128) slice — the repo's best-MFU shape, as a config.

GPT-2-XL width (n_embd=2048, n_head=16 → head dim C=128) at 8 layers, so
fp32 master params + Adam state + remat-free activations fit one v5e chip
(15.75 GB). C=128 fills the MXU's 128-wide systolic array on QK^T/PV where
the GPT-2-small C=64 runs it half-utilized; measured 63.8% MFU sustained at
per-chip batch 12 — the repo's ≥55% target with 8 points to spare, 1.34×
the reference's published 47.8% (reference README.md:55; RESULTS.md §1).

This file is the single source of truth for the shape: `bench.py --shape
wide` loads it, so the number is reproducible both ways —

    python bench.py --shape wide              # driver-style one-liner
    python launch.py --config=wide610m --rundir=outputs/wide  # real training

Optimizer/schedule constants follow the openwebtext_xl recipe (reference
configs/openwebtext_xl.py:4-22) with the horizon scaled to a single chip.
"""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="data/local_text",
    learning_rate=1e-3,
    batch_size=12,  # measured optimum: 12 → 63.8% MFU; 16 hits HBM pressure
    warmup_steps=300,
    min_lr=1e-5,
    lr_decay_steps=3000,
    max_steps=3000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=250,
    eval_steps=50,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=1,
    shard_model=False,
    mesh=MeshConfig(data=-1, fsdp=1, sp=1),
    model_config=GPTConfig(
        block_size=1024,
        vocab_size=50304,
        n_layer=8,
        n_head=16,
        n_embd=2048,
        dropout=0.0,
        attn_impl="flash",
        # Remat OFF is what fits-and-flies at batch 12 (63.8%); +remat OOMs
        # at batch 16 and loses ~10 points at 12 (RESULTS.md §1 wide table).
        remat=False,
        remat_policy="flash",
        # Like the 124M recipe: remat-off only FITS with the layer scan
        # fully unrolled (the bench's measured setting) — the rolled scan's
        # per-iteration temps exceed HBM (OOMs at unroll=1).
        scan_unroll=8,
        rope_style="split",
        # At C=128 the head-major end-to-end layout wins (+1.2 MFU, 63.9%
        # measured r5); at C=64 it loses — keep 'seq' there (RESULTS §4a).
        attn_layout="head",
    ),
)
