"""Flagship long run: the 124M openwebtext recipe, 10k steps on one chip.

The r4 golden-loss artifact (docs/runs/local_text_124m_r4_10k/): the full
openwebtext recipe shape and optimizer (reference configs/openwebtext.py:4-21)
with the warmup/decay horizon scaled to 10,000 steps — ~2.62B training tokens
(effective batch 256 × T=1024), ~11.5 epochs over the 228M-token offline-BPE
local_text corpus — with a deliberate kill + `--rundir` resume mid-run as the
recovery proof (reference README.md:29-33's resume flow, under test instead
of in prose). Inherits the 3k config's fast path: flash attention, remat off,
fused CE, G=16.
"""

from midgpt_tpu.configs.local_text_124m import config as _base

config = _base.replace(
    warmup_steps=300,
    lr_decay_steps=10_000,
    max_steps=10_000,
    eval_interval=500,
    eval_steps=50,
)
