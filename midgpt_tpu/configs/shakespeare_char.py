"""Char-level tiny GPT (reference configs/shakespeare_char.py:4-21)."""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="data/shakespeare_char",
    learning_rate=1e-3,
    batch_size=64,
    warmup_steps=100,
    min_lr=1e-4,
    lr_decay_steps=5000,
    max_steps=5000,
    beta2=0.99,
    weight_decay=1e-4,
    eval_interval=2000,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=1,
    shard_model=False,
    mesh=MeshConfig(data=-1, fsdp=1, sp=1),
    model_config=GPTConfig(
        block_size=256, vocab_size=65, n_layer=6, n_head=6, n_embd=384, dropout=0.2
    ),
)
