"""1.5B GPT-2-XL-ish, multihost FSDP (reference configs/openwebtext_xl.py:4-22).

The headline benchmark config: reference hits ~2.42 val loss / ~444K tok/s /
47.8% MFU on a v3-128 (BASELINE.md).
"""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="/mnt/disks/persist/openwebtext",
    learning_rate=1e-3,
    batch_size=1024,
    warmup_steps=2500,
    min_lr=1e-5,
    lr_decay_steps=25_000,
    max_steps=25_000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=1000,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=1,
    shard_model=True,
    mesh=MeshConfig(data=-1, fsdp=8, sp=1),
    model_config=GPTConfig(
        block_size=1024,
        vocab_size=50304,
        n_layer=24,
        n_head=16,
        n_embd=2048,
        dropout=0.0,
        attn_impl="flash",
    ),
)
