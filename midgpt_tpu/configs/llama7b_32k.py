"""7B Llama-shape at 32K context: ring attention over an 8-wide sp axis.

Sequence parallelism (parallel/ring_attention.py) holds T/8 = 4096 tokens of
K/V per device and rotates shards over ICI — no device ever materializes the
32K x 32K scores. This shape exists in no form in the reference (its context
is capped at 1024 by the materialized T x T buffer, reference model.py:71-73).
"""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="/mnt/disks/persist/openwebtext",
    learning_rate=3e-4,
    batch_size=32,
    warmup_steps=2000,
    min_lr=3e-5,
    lr_decay_steps=50_000,
    max_steps=50_000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=1000,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=8,
    shard_model=True,
    mesh=MeshConfig(data=-1, fsdp=8, sp=8),
    # Serving: self-draft speculative decoding with the first 8 of 32
    # layers (1/4 depth); decode is weight-bandwidth-bound at 7B, so one
    # verify sweep amortized over k accepted drafts is the dominant
    # serving lever (docs/SERVING.md). k adapts in [1, 8] per slot.
    spec_layers=8,
    spec_k_max=8,
    model_config=GPTConfig(
        block_size=32768,
        vocab_size=50304,
        n_layer=32,
        n_head=32,
        n_embd=4096,
        dropout=0.0,
        attn_impl="ring",
        rope_style="split",  # same-function fast RoPE (see openwebtext.py)
        # 32 layers: the unrolled decode DUS chain costs O(n_layer)
        # trace+compile per decode chunk length; take the rolled scan's
        # 2 cache copies/step instead (GPTConfig.decode_layer_scan).
        decode_layer_scan=True,
    ),
)
