"""124M flagship shape on the offline-BPE local_text corpus, single chip.

The full openwebtext recipe (configs/openwebtext.py; reference
configs/openwebtext.py:4-21) scaled to a single v5e chip and a ~2h horizon:
identical model shape (GPT-2-small, vocab padded to 50304), identical
optimizer constants (lr 1e-3 cosine to 1e-5, beta2 0.95, wd 1e-4 with
wd/lr decoupling), the full fast path (flash attention, remat off —
it fits at this scale, RESULTS.md §1 — fused CE) and the G=16
accumulation schedule — with effective batch 256
(16 x 16) instead of 2048 and the warmup/decay horizon scaled to 3000
steps. Data comes from data/local_text/prepare.py (offline-trained
byte-level BPE over local text trees).
"""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="data/local_text",
    learning_rate=1e-3,
    batch_size=16,
    warmup_steps=300,
    min_lr=1e-5,
    lr_decay_steps=3000,
    max_steps=3000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=250,
    eval_steps=50,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=16,  # effective batch 256
    shard_model=False,
    mesh=MeshConfig(data=-1, fsdp=1, sp=1),
    # Serving: 4-of-12-layer self-draft speculation for sample.py
    # --engine=continuous (override with --spec_layers; docs/SERVING.md).
    spec_layers=4,
    model_config=GPTConfig(
        block_size=1024,
        vocab_size=50304,
        n_layer=12,
        n_head=12,
        n_embd=768,
        dropout=0.0,
        attn_impl="flash",
        # 124M at microbatch 16 fits the 15.75 GB chip WITHOUT per-block
        # remat (measured: 51.4% MFU remat-off vs 47.5% with the 'flash'
        # policy at G=16 — RESULTS.md §1); keep the policy name so
        # `--set model_config.remat=True` restores it for tighter chips.
        remat=False,
        remat_policy="flash",
        # Remat-off only FITS with the layer scan fully unrolled (the bench's
        # measured setting): the rolled scan's per-iteration temps push the
        # no-remat activation set past 15.75 GB (OOMs at unroll=1).
        scan_unroll=12,
        rope_style="split",  # same-function fast RoPE (see openwebtext.py)
    ),
)
