"""124M GPT-2-small shape, single host (reference configs/openwebtext.py:4-21)."""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="data/openwebtext",
    learning_rate=1e-3,
    batch_size=128,
    warmup_steps=5_000,
    min_lr=1e-5,
    lr_decay_steps=60_000,
    max_steps=60_000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=1000,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=16,  # effective batch 2048
    shard_model=False,
    mesh=MeshConfig(data=-1, fsdp=1, sp=1),
    model_config=GPTConfig(
        block_size=1024, vocab_size=50304, n_layer=12, n_head=12, n_embd=768,
        dropout=0.0,
        # Same function as the reference rotation via the in-graph q/k row
        # permutation (models/gpt.py _qkv_weights, exactness test-pinned):
        # +2.1 MFU measured on the v5e 124M bench (RESULTS §4a r5).
        rope_style="split",
    ),
)
