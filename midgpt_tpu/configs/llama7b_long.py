"""7B Llama-shape, seq 4096, 2D data x fsdp mesh + grad accum (BASELINE.json
configs list). Long context rides the Pallas flash-attention kernel; for
contexts past what one chip's flash can hold, see llama7b_32k (ring
attention over the sp axis)."""

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig

config = ExperimentConfig(
    rundir="",
    data_dir="/mnt/disks/persist/openwebtext",
    learning_rate=3e-4,
    batch_size=256,
    warmup_steps=2000,
    min_lr=3e-5,
    lr_decay_steps=100_000,
    max_steps=100_000,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=1000,
    compute_dtype="bfloat16",
    param_dtype="float32",
    g_accum_iters=4,
    shard_model=True,
    mesh=MeshConfig(data=-1, fsdp=16, sp=1),
    model_config=GPTConfig(
        block_size=4096,
        vocab_size=50304,
        n_layer=32,
        n_head=32,
        n_embd=4096,
        dropout=0.0,
        attn_impl="flash",
        rope_style="split",  # same-function fast RoPE (see openwebtext.py)
        # 32 layers: the unrolled decode DUS chain costs O(n_layer)
        # trace+compile per decode chunk length; take the rolled scan's
        # 2 cache copies/step instead (GPTConfig.decode_layer_scan).
        decode_layer_scan=True,
    ),
)
