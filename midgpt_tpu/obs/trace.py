"""Host-side span tracer with a bounded ring-buffer flight recorder.

JAX-free and clock-injected by design: the tracer never imports jax, never
touches device state, and reads time only through the callable handed to it
at construction — the same injectable-clock discipline the serving engine
uses (sampling/serve.py `clock=`), so tests drive it with a fake clock and
graftcheck GC012 has nothing to flag. Events are recorded as cheap tuples
into a `collections.deque(maxlen=...)`: when the ring fills, the OLDEST
events fall off and `dropped` counts them — a flight recorder keeps the
crash-adjacent tail, not the takeoff.

Export is Chrome trace-event JSON (the `{"traceEvents": [...]}` container),
loadable in Perfetto / chrome://tracing. Span begin/end pairs are emitted
as complete events (ph "X", ts/dur in microseconds), point events as
instants (ph "i"), and long-lived request lifecycles as async begin/end
pairs (ph "b"/"e") keyed by id so overlapping requests render as separate
tracks. Thread names ("engine", "server", "train", ...) become tid lanes
via metadata events (ph "M", name "thread_name").

The off switch is `NULL_TRACER`: a shared singleton whose `span()` returns
one reusable no-op context manager and whose record methods are `pass`.
Instrumented code calls the tracer unconditionally and stays branch-free;
with NULL_TRACER in place the per-call cost is one attribute lookup and an
empty function body — sub-microsecond, zero clock reads, zero allocation.
"""

from __future__ import annotations

import json
import time
import typing as tp
from collections import deque

# Event kinds stored in the ring (first tuple field). Kept as one-char
# tags: the ring holds tens of thousands of tuples and these are compared
# on every export.
_COMPLETE = "X"
_INSTANT = "i"
_ASYNC_BEGIN = "b"
_ASYNC_END = "e"


class _SpanHandle:
    """Context manager for one open span; re-armed per `span()` call.

    Not reentrant and not thread-safe per instance — each `span()` call
    returns a fresh handle, so nesting and cross-thread use are safe at
    the Tracer level (the ring append is the only shared mutation, and
    deque.append is atomic under the GIL).
    """

    __slots__ = ("_tracer", "_name", "_cat", "_tid", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, tid: str):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._tid = tid
        self._t0 = 0.0

    def __enter__(self) -> "_SpanHandle":
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, *exc) -> None:
        t1 = self._tracer._clock()
        self._tracer._push(
            (_COMPLETE, self._name, self._cat, self._tid, self._t0,
             t1 - self._t0, None, None)
        )


class _NullSpan:
    """The no-op context manager NULL_TRACER hands out — one shared
    instance, no state, so `with tracer.span(...)` costs two empty calls
    when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded-ring span recorder. All timestamps come from the injected
    `clock` (seconds, monotonic-ish); export rebases them to the tracer's
    construction instant so Perfetto timelines start near zero."""

    def __init__(
        self,
        capacity: int = 16384,
        clock: tp.Callable[[], float] = time.perf_counter,
    ):
        self._clock = clock
        self._ring: tp.Deque[tuple] = deque(maxlen=capacity)
        self._capacity = capacity
        self._t_base = clock()
        self.dropped = 0

    # -- recording -------------------------------------------------------

    def _push(self, ev: tuple) -> None:
        if len(self._ring) == self._capacity:
            self.dropped += 1
        self._ring.append(ev)

    def span(self, name: str, cat: str = "", tid: str = "main") -> _SpanHandle:
        """Context manager measuring one host-side phase."""
        return _SpanHandle(self, name, cat, tid)

    def complete(
        self, name: str, cat: str, tid: str, start: float, dur: float,
        args: tp.Optional[dict] = None,
    ) -> None:
        """Record a span from explicit clock readings — for phases whose
        boundaries were already captured (the round decomposition reads
        the clock once per boundary and derives several spans)."""
        self._push((_COMPLETE, name, cat, tid, start, dur, None, args))

    def instant(
        self, name: str, cat: str = "", tid: str = "main",
        args: tp.Optional[dict] = None,
    ) -> None:
        """Point event (admission, eviction, shed, rollback, ...)."""
        self._push((_INSTANT, name, cat, tid, self._clock(), 0.0, None, args))

    def async_begin(
        self, name: str, ident: str, cat: str = "", tid: str = "main",
        args: tp.Optional[dict] = None,
    ) -> None:
        """Open one track of a long-lived overlapping lifecycle (a request
        from submit to finish). `ident` pairs it with async_end."""
        self._push(
            (_ASYNC_BEGIN, name, cat, tid, self._clock(), 0.0, ident, args)
        )

    def async_end(
        self, name: str, ident: str, cat: str = "", tid: str = "main",
        args: tp.Optional[dict] = None,
    ) -> None:
        self._push(
            (_ASYNC_END, name, cat, tid, self._clock(), 0.0, ident, args)
        )

    # -- introspection / export -----------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self.dropped = 0

    def events(self) -> tp.List[tuple]:
        """Raw ring contents, oldest first (tests introspect these)."""
        return list(self._ring)

    def export(self) -> tp.List[dict]:
        """Chrome trace events (ts/dur in microseconds, rebased to the
        tracer's birth). tid strings map to stable integer lanes with
        `thread_name` metadata events so Perfetto labels them."""
        tids: tp.Dict[str, int] = {}
        out: tp.List[dict] = []
        for kind, name, cat, tid, t, dur, ident, args in self._ring:
            lane = tids.setdefault(tid, len(tids) + 1)
            ev: tp.Dict[str, tp.Any] = {
                "name": name,
                "cat": cat or "obs",
                "ph": kind,
                "pid": 1,
                "tid": lane,
                "ts": round((t - self._t_base) * 1e6, 3),
            }
            if kind == _COMPLETE:
                ev["dur"] = round(dur * 1e6, 3)
            if kind == _INSTANT:
                ev["s"] = "t"  # thread-scoped instant
            if ident is not None:
                ev["id"] = ident
            if args:
                ev["args"] = args
            out.append(ev)
        for tid, lane in tids.items():
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": lane,
                    "args": {"name": tid},
                }
            )
        return out

    def dump(self, path: str) -> str:
        """Write `{"traceEvents": [...]}` to `path`; returns the path."""
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": self.export()}, fh)
        return path


class _NullTracer:
    """Off switch. Shares the Tracer surface; every method is free."""

    __slots__ = ()

    dropped = 0

    def span(self, name: str, cat: str = "", tid: str = "main") -> _NullSpan:
        return _NULL_SPAN

    def complete(self, *a, **k) -> None:
        pass

    def instant(self, *a, **k) -> None:
        pass

    def async_begin(self, *a, **k) -> None:
        pass

    def async_end(self, *a, **k) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def clear(self) -> None:
        pass

    def events(self) -> tp.List[tuple]:
        return []

    def export(self) -> tp.List[dict]:
        return []

    def dump(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": []}, fh)
        return path


NULL_TRACER = _NullTracer()
