"""Unified observability: span tracing, flight recorder, metrics export.

One `Observability` object bundles the two primitives (obs/trace.py span
tracer with its bounded flight-recorder ring, obs/metrics.py registry) plus
the serving round-timing decomposition. It is JAX-free and clock-injected:
constructing one compiles nothing, touches no device, and — wired through
`ServeEngine(obs=...)` — adds zero XLA programs and zero jit statics (the
recompile pin in tests/test_recompile_pins.py holds that line).

Round decomposition semantics (docs/OBSERVABILITY.md has the full story):
the engine loop reads its injected clock at four boundaries per round —

    t0      batch assembly starts
    t1      jit call returned (dispatch enqueued; NOT compute done)
    t_land  np.asarray(...) force returned — the only sync that works
            through the axon tunnel (CLAUDE.md gotchas)
    t_post  token commit / trie bookkeeping done

— and derives `t_dispatch` = t1-t0 (host assembly + enqueue),
`t_device_wait` = t_land-t1 (device compute + tunnel round-trip),
`t_host_post` = t_post-t_land. These aggregate to p50/p95 in histograms
and surface on `stats()["obs"]["round_decomp"]`, loadgen's serve_slo
points, and the bench_serve profiles — the baseline artifact ROADMAP
item 3's round-overlap dispatch A/Bs against. Under overlap="double"
(sampling/serve.py `_step_overlapped`) round N settles one step late, so
its t1 -> t_land window CONTAINS host work for other rounds; the engine
reports that overlapped span via `hidden_s` and it surfaces as the
`overlap_hidden` decomposition entry (`overlap_hidden_ms` on the bench
lines) — the host time the overlap actually hid, the A/B headline of
docs/SERVING.md "Round-overlap dispatch".

The module-level `flight_recorder()` singleton is the always-on crash
recorder for the training path: train/checkpoint/supervisor record into
it without plumbing, and crash paths (`DivergenceError`, SIGTERM drain,
serving chaos) call `dump_flight_recorder(rundir)` for postmortems.
"""

from __future__ import annotations

import os
import time
import typing as tp

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .trace import NULL_TRACER, Tracer

__all__ = [
    "Observability",
    "Tracer",
    "NULL_TRACER",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "flight_recorder",
    "dump_flight_recorder",
]


class Observability:
    """Tracer + metrics + round decomposition, one handle.

    `enabled=False` (or just not passing an Observability at all —
    engine code holds NULL_TRACER in that case) keeps every
    instrumentation site free: no clock reads, no ring appends, and the
    scheduling/token path bit-identical to obs-off.
    """

    def __init__(
        self,
        capacity: int = 16384,
        clock: tp.Callable[[], float] = time.perf_counter,
    ):
        self.clock = clock
        self.tracer = Tracer(capacity=capacity, clock=clock)
        self.metrics = MetricsRegistry()
        # round decomposition histograms, seconds; surfaced in ms
        self._h_dispatch = self.metrics.histogram(
            "round_dispatch_s", "batch assembly + jit enqueue per round"
        )
        self._h_device = self.metrics.histogram(
            "round_device_wait_s", "dispatch return to host landing (device "
            "compute + tunnel round-trip)"
        )
        self._h_post = self.metrics.histogram(
            "round_host_post_s", "token commit + trie bookkeeping per round"
        )
        self._h_hidden = self.metrics.histogram(
            "round_overlap_hidden_s", "host work overlapped under an "
            "in-flight dispatch (round-overlap dispatch; 0 when off)"
        )
        self._rounds = self.metrics.counter(
            "rounds_decomposed", "rounds with timing decomposition recorded"
        )

    # -- round timing ---------------------------------------------------

    def record_round(
        self, kind: str, tid: str,
        t0: float, t1: float, t_land: float, t_post: float,
        hidden_s: float = 0.0,
    ) -> None:
        """Record one engine round's boundary clock readings (see module
        docstring for the four-boundary semantics). Also emits the three
        phase spans into the flight recorder with explicit timestamps —
        no extra clock reads beyond the four the engine already took.
        `hidden_s` is the slice of t1 -> t_land spent doing OTHER rounds'
        host work under round-overlap dispatch (the engine reads the clock
        once more as the settle force starts); it defaults to 0.0 so
        classic rounds record an honest zero."""
        self._h_dispatch.observe(t1 - t0)
        self._h_device.observe(t_land - t1)
        self._h_post.observe(t_post - t_land)
        self._h_hidden.observe(hidden_s)
        self._rounds.inc()
        self.tracer.complete(f"{kind}.dispatch", "round", tid, t0, t1 - t0)
        self.tracer.complete(
            f"{kind}.device_wait", "round", tid, t1, t_land - t1
        )
        self.tracer.complete(
            f"{kind}.host_post", "round", tid, t_land, t_post - t_land
        )

    def round_decomp(self) -> tp.Dict[str, tp.Any]:
        """p50/p95/mean per phase, milliseconds (stats() schema)."""
        def _ms(h: Histogram) -> tp.Dict[str, float]:
            s = h.summary()
            return {
                "n": s["n"],
                "mean_ms": round(s["mean"] * 1e3, 3),
                "p50_ms": round(s["p50"] * 1e3, 3),
                "p95_ms": round(s["p95"] * 1e3, 3),
                "max_ms": round(s["max"] * 1e3, 3),
            }

        return {
            "rounds": int(self._rounds.value),
            "dispatch": _ms(self._h_dispatch),
            "device_wait": _ms(self._h_device),
            "host_post": _ms(self._h_post),
            "overlap_hidden": _ms(self._h_hidden),
        }

    # -- unified stats schema -------------------------------------------

    def snapshot(self) -> tp.Dict[str, tp.Any]:
        """The `stats()["obs"]` payload shared by engine/server/
        supervisor: enabled flag, round decomposition, full metrics
        snapshot, and flight-recorder health."""
        snap = self.metrics.snapshot()
        snap.update(
            enabled=True,
            round_decomp=self.round_decomp(),
            spans=len(self.tracer),
            spans_dropped=self.tracer.dropped,
        )
        return snap

    def dump(self, rundir: str, filename: str = "flight_recorder.json") -> str:
        """Write the Chrome trace + a .prom metrics dump into `rundir`."""
        os.makedirs(rundir, exist_ok=True)
        path = self.tracer.dump(os.path.join(rundir, filename))
        prom = os.path.join(rundir, filename.rsplit(".", 1)[0] + ".prom")
        with open(prom, "w", encoding="utf-8") as fh:
            fh.write(self.metrics.to_prometheus())
        return path


DISABLED_SNAPSHOT: tp.Dict[str, tp.Any] = {"enabled": False}

_FLIGHT: tp.Optional[Observability] = None


def flight_recorder() -> Observability:
    """Process-global always-on recorder for the training/supervisor path
    (serving constructs per-engine Observability explicitly). Lazy so
    importing midgpt_tpu never pays for it."""
    global _FLIGHT
    if _FLIGHT is None:
        _FLIGHT = Observability()
    return _FLIGHT


def dump_flight_recorder(
    rundir: str, filename: str = "flight_recorder.json"
) -> tp.Optional[str]:
    """Dump the global recorder if it was ever touched; None otherwise.
    Crash paths call this unconditionally — a run that never recorded
    anything leaves no file rather than an empty lie."""
    if _FLIGHT is None:
        return None
    return _FLIGHT.dump(rundir, filename)
