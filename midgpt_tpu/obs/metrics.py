"""Counters / gauges / histograms with a Prometheus-text-format dump.

JAX-free, allocation-light, and schema-first: every instrument lives in a
`MetricsRegistry` whose `snapshot()` is the unified stats() payload shared
by engine/server/supervisor, and whose `to_prometheus()` emits the text
exposition format a scrape endpoint would serve. Histograms keep a bounded
reservoir (`deque(maxlen=...)`) plus exact count/sum, so percentiles stay
cheap and memory stays flat no matter how many rounds a long-lived server
sees — the same bounded-tail philosophy as the flight recorder.
"""

from __future__ import annotations

import math
import re
import typing as tp
from collections import deque

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset."""
    return _NAME_RE.sub("_", name)


class Counter:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Bounded-reservoir histogram: exact n/sum/max, percentile estimates
    from the most recent `maxlen` observations (recency bias is the POINT
    for serving latencies — a p95 from an hour ago is not operable)."""

    __slots__ = ("name", "help", "n", "total", "max", "_tail")

    def __init__(self, name: str, help: str = "", maxlen: int = 4096):
        self.name = name
        self.help = help
        self.n = 0
        self.total = 0.0
        self.max = 0.0
        self._tail: tp.Deque[float] = deque(maxlen=maxlen)

    def observe(self, value: float) -> None:
        v = float(value)
        self.n += 1
        self.total += v
        if v > self.max:
            self.max = v
        self._tail.append(v)

    def _quantile(self, sorted_tail: tp.List[float], q: float) -> float:
        # nearest-rank on the sorted reservoir; exact for n <= maxlen
        if not sorted_tail:
            return 0.0
        idx = min(len(sorted_tail) - 1, max(0, math.ceil(q * len(sorted_tail)) - 1))
        return sorted_tail[idx]

    def summary(self) -> tp.Dict[str, float]:
        tail = sorted(self._tail)
        return {
            "n": self.n,
            "mean": round(self.total / self.n, 6) if self.n else 0.0,
            "p50": round(self._quantile(tail, 0.50), 6),
            "p95": round(self._quantile(tail, 0.95), 6),
            "max": round(self.max, 6),
        }


class MetricsRegistry:
    """Create-or-get instruments by name; snapshot/export the lot."""

    def __init__(self):
        self._counters: tp.Dict[str, Counter] = {}
        self._gauges: tp.Dict[str, Gauge] = {}
        self._histograms: tp.Dict[str, Histogram] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter(name, help)
        return c

    def gauge(self, name: str, help: str = "") -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, help)
        return g

    def histogram(self, name: str, help: str = "", maxlen: int = 4096) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram(name, help, maxlen)
        return h

    def snapshot(self) -> tp.Dict[str, tp.Any]:
        """The unified stats() payload: plain dicts, JSON-serializable."""
        return {
            "counters": {n: c.value for n, c in sorted(self._counters.items())},
            "gauges": {n: g.value for n, g in sorted(self._gauges.items())},
            "histograms": {
                n: h.summary() for n, h in sorted(self._histograms.items())
            },
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition format. Histograms export as summary
        quantiles (not cumulative buckets): the reservoir gives percentile
        estimates directly and bucket bounds would be a lie."""
        lines: tp.List[str] = []
        for n, c in sorted(self._counters.items()):
            pn = _prom_name(n)
            if c.help:
                lines.append(f"# HELP {pn} {c.help}")
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {c.value:g}")
        for n, g in sorted(self._gauges.items()):
            pn = _prom_name(n)
            if g.help:
                lines.append(f"# HELP {pn} {g.help}")
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {g.value:g}")
        for n, h in sorted(self._histograms.items()):
            pn = _prom_name(n)
            if h.help:
                lines.append(f"# HELP {pn} {h.help}")
            lines.append(f"# TYPE {pn} summary")
            s = h.summary()
            lines.append(f'{pn}{{quantile="0.5"}} {s["p50"]:g}')
            lines.append(f'{pn}{{quantile="0.95"}} {s["p95"]:g}')
            lines.append(f"{pn}_sum {h.total:g}")
            lines.append(f"{pn}_count {h.n:g}")
        return "\n".join(lines) + "\n"
