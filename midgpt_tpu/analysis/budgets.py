"""Declarative budget manifest for the compiled-artifact audits.

Every numeric pin the serving stack promises about its lowered programs —
how many in-loop collectives a tensor-parallel body may carry, how many
pool-sized copies a decode loop may make (zero), what geometry the audit
suite lowers against — lives HERE, once. Both consumers read this module:

  * `analysis/hlo_audit.py run_audit()` lowers the serving programs at
    `AUDIT` geometry and asserts each census against these budgets;
  * `tests/test_recompile_pins.py::test_audit_suite_passes_on_cpu_mesh`
    re-asserts the report keys against the SAME numbers.

A new serving mode declares its budget by adding one entry to
`TP_LOOP_LAYERS` (or one constant below); drift between the audit and the
pin tests is then structurally impossible — there is no second literal to
forget. No JAX import: the manifest must be loadable by the lint pass and
by the tests' collection phase without touching a backend.
"""

from __future__ import annotations

import dataclasses
import typing as tp


@dataclasses.dataclass(frozen=True)
class AuditGeometry:
    """The tiny abstract-lowering geometry the audit suite runs at.

    Small enough to lower in seconds on the 1-core CI host, large enough
    that every structural feature exists: >1 layer (so step-scan bodies
    carry a per-layer collective multiple), >1 head (so tp=2 sharding is
    head-aligned), a paged pool with more pages than any one request.
    """

    n_layer: int = 2
    n_head: int = 2
    n_embd: int = 32
    head_dim: int = 16  # n_embd // n_head
    block_size: int = 64
    vocab_size: int = 128
    num_pages: int = 9
    page_size: int = 8
    batch: int = 2
    max_pages: int = 8
    decode_chunk: int = 4
    spec_k: int = 2
    split_k: int = 4
    tp: int = 2
    draft_n_layer: int = 1
    # Attention-variant knobs (docs/SERVING.md "Attention variants"):
    # n_kv_heads = 0 means MHA (KV heads == query heads); a smaller value
    # shrinks the paged pool's head axis to the KV-head count, which is
    # exactly what the copy census must grep. Window/sinks change masking
    # only — pool geometry is untouched.
    n_kv_heads: int = 0
    sliding_window: int = 0
    attn_sinks: int = 0

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_head


AUDIT = AuditGeometry()

# Variant lowerings the audit suite must also hold the zero-copy /
# collective-free pins on: MQA (2 query heads sharing 1 KV head — the
# extreme grouping, so any head-fold bug in the lowering surfaces), the
# same MQA geometry with a sliding window + sinks (masking must not add
# pool traffic), and a GQA tensor-parallel geometry (4 query heads, 2 KV
# heads, tp=2: one KV head — one whole query GROUP — per shard).
AUDIT_GQA = AuditGeometry(n_kv_heads=1)
AUDIT_GQA_WINDOW = AuditGeometry(n_kv_heads=1, sliding_window=24, attn_sinks=8)
AUDIT_GQA_TP = AuditGeometry(n_head=4, head_dim=8, n_kv_heads=2)

# The megatron sharding contract (docs/SERVING.md "Mesh-sharded serving"):
# one activation all-reduce after the attention output projection and one
# after the MLP down projection — per layer, per decode step, and nothing
# else (zero all-gather / all-to-all / reduce-scatter / collective-permute
# in any serving loop body).
MEGATRON_ALL_REDUCES_PER_LAYER = 2

# How many transformer layers execute inside ONE while-body iteration of
# each tp-audited program. The step-scan programs (decode, int8 decode,
# split-K decode, int8 draft) unroll all their layers inside the body; the
# verify program is lowered with decode_layer_scan=True so its body IS a
# single layer. Values are AuditGeometry field names (resolved at query
# time) or plain ints.
TP_LOOP_LAYERS: tp.Dict[str, tp.Union[str, int]] = {
    "tp_decode": "n_layer",
    "tp_decode_int8": "n_layer",
    "tp_decode_split": "n_layer",  # split-K must not move the budget
    "tp_verify": 1,  # layer-scan body = one layer = one megatron pair
    "tp_draft_int8": "draft_n_layer",
    # GQA must not move the budget either: grouping shrinks pool BYTES per
    # shard, never the megatron activation all-reduce count (lowered at
    # AUDIT_GQA_TP geometry, hence outside TP_PROGRAMS' shared-shape loop)
    "tp_decode_gqa": "n_layer",
}

TP_PROGRAMS: tp.Tuple[str, ...] = tuple(
    k for k in TP_LOOP_LAYERS if k != "tp_decode_gqa"
)

# Pool/scale copy budget inside ANY serving loop body, split or not,
# sharded or not: the KV pool aliases through the loop carry (the r5/r6
# perf pin), so the census must find exactly zero pool-sized copies.
LOOP_POOL_COPY_BUDGET = 0

# Report keys that pin an all-zero copy census for the split-K lowerings
# (dict-per-while-body form: every value must be 0).
SPLIT_ZERO_COPY_KEYS: tp.Tuple[str, ...] = (
    "split_decode_loop_pool_copies",
    "split_verify_loop_pool_copies",
    "split_decode_int8_loop_pool_copies",
    "split_decode_int8_loop_scale_copies",
)

# The split-K decode body census is also collective-free; the report key
# holds {body: n_collectives} and every value must be 0.
SPLIT_ZERO_COLLECTIVE_KEYS: tp.Tuple[str, ...] = ("split_decode_while_bodies",)

# Round-overlap dispatch (docs/SERVING.md "Round-overlap dispatch"): the
# fused multi-round group program (`_serve_decode_group`) wraps k decode
# rounds in one lax.scan, so its while body carries the ENTIRE pool through
# the scan carry. The aliasing pin must hold at every audited round_group —
# a single in-loop pool copy would multiply by k rounds per dispatch and
# erase the overlap win. `run_audit` lowers the group program at these
# round_group values (f32 at both, int8 at the first).
ROUND_GROUPS_AUDITED: tp.Tuple[int, ...] = (2, 4)

# All-zero copy census keys for the group lowerings (same dict-per-body
# form as the split-K keys above: every value must be 0).
GROUP_ZERO_COPY_KEYS: tp.Tuple[str, ...] = (
    "group2_decode_loop_pool_copies",
    "group4_decode_loop_pool_copies",
    "group2_decode_int8_loop_pool_copies",
    "group2_decode_int8_loop_scale_copies",
)

# The group scan body is single-engine work — zero collectives of any kind
# may appear in it ({body: n_collectives}, every value 0).
GROUP_ZERO_COLLECTIVE_KEYS: tp.Tuple[str, ...] = (
    "group2_decode_while_bodies",
    "group4_decode_while_bodies",
)

# Attention-variant lowerings (docs/SERVING.md "Attention variants"): the
# KV-head-shrunk pool must STILL alias through every decode loop carry —
# grouping changes pool geometry, which is precisely the kind of change
# that silently breaks XLA's donation/aliasing match — and window masking
# must add zero pool traffic (it is select math on scores, not data
# movement). Same dict-per-body report form as the split/group keys.
VARIANT_ZERO_COPY_KEYS: tp.Tuple[str, ...] = (
    "gqa_decode_loop_pool_copies",
    "gqa_window_decode_loop_pool_copies",
    "gqa_decode_int8_loop_pool_copies",
    "gqa_decode_int8_loop_scale_copies",
)

VARIANT_ZERO_COLLECTIVE_KEYS: tp.Tuple[str, ...] = (
    "gqa_decode_while_bodies",
    "gqa_window_decode_while_bodies",
)


def tp_loop_all_reduce_budget(
    program: str, geom: AuditGeometry = AUDIT
) -> int:
    """In-loop all-reduce budget for one tp-audited serving program."""
    layers = TP_LOOP_LAYERS[program]
    if isinstance(layers, str):
        layers = getattr(geom, layers)
    return MEGATRON_ALL_REDUCES_PER_LAYER * layers


def tp_mesh_shape(geom: AuditGeometry = AUDIT) -> tp.Dict[str, int]:
    """The serving mesh the tp audits lower against (pure tp, no data)."""
    return {"tp": geom.tp, "data": 1}


def pool_shape(
    geom: AuditGeometry = AUDIT, dtype: str = "f32", tp_shards: int = 1
) -> str:
    """HLO shape string of one KV pool buffer (the copy-census grep key).

    Layout [L, H_kv, P, ps, D] per models/gpt.py PagedKVCache — the head
    axis is the KV-head count (== n_head only for MHA; GQA geometries
    shrink it by the group factor). Under tensor parallelism that same
    axis shards, so the per-shard census greps kv_heads // tp_shards.
    """
    return (
        f"{dtype}[{geom.n_layer},{geom.kv_heads // tp_shards},"
        f"{geom.num_pages},{geom.page_size},{geom.head_dim}]"
    )


def scale_shape(
    geom: AuditGeometry = AUDIT, tp_shards: int = 1
) -> str:
    """HLO shape string of an int8 pool's f32 scale side buffer.

    Layout [L, P, H_kv, ps] (page-major so the per-page quantization
    scales gather alongside the page table; KV-head axis like the pools).
    """
    return (
        f"f32[{geom.n_layer},{geom.num_pages},"
        f"{geom.kv_heads // tp_shards},{geom.page_size}]"
    )


def shard_pool_shapes(
    geom: AuditGeometry = AUDIT,
) -> tp.Tuple[str, ...]:
    """All per-shard pool/scale shapes the tp copy census must grep."""
    return (
        pool_shape(geom, "f32", geom.tp),
        pool_shape(geom, "s8", geom.tp),
        scale_shape(geom, geom.tp),
    )
