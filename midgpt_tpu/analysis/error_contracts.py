"""Declarative field contracts for the structured error types (GC016).

The robustness and serving layers communicate failure through structured
exceptions — the supervisor reads ``DivergenceError.last_good_step`` to pick
a rollback target, the serving front door reads ``BackpressureError``'s page
accounting to compute a retry delay, chaos gates match on
``PoolResizeError.retryable``. A raise that forgets a field does not fail at
the raise site; it fails much later, in whatever handler reaches for the
missing attribute — usually inside a chaos run where the traceback points at
the *recovery* path, not the bug.

GC016 (analysis/concurrency.py) makes the contract lexical: every ``raise``
of a registered error must pass each field marked required below, and may
pass only fields the class declares. This module is the single place that
registers contracts — like ``budgets.py``, it is a reviewed manifest, not
configuration, and it must stay importable without jax (the analysis pass
runs in a JAX-free interpreter).

Keep entries in sync with the constructor signatures in
``robustness/errors.py``, ``sampling/ops.py``, ``sampling/serve.py``, and
``sampling/disagg.py`` — ``tests/test_graftcheck.py`` pins the registry
against the live classes via ``inspect.signature``.
"""

from __future__ import annotations

import typing as tp


class ErrorContract(tp.NamedTuple):
    """Field contract for one structured error class.

    ``required``: keyword fields every raise must pass explicitly (no
    defaults worth relying on — an absent value means the handler gets a
    lie, not a placeholder). ``optional``: declared fields a raise may
    pass. Anything else is a typo'd/undeclared keyword and is flagged.
    The positional message argument is outside the contract.
    """

    required: tp.Tuple[str, ...]
    optional: tp.Tuple[str, ...] = ()


# Keyed by bare class name: graftcheck resolves `raise X(...)` by the dotted
# leaf, the same bare-name discipline as pass 1 (imports are flattened by
# the AST walk; none of these names collide across modules).
ERROR_CONTRACTS: tp.Dict[str, ErrorContract] = {
    # robustness/errors.py
    "DivergenceError": ErrorContract(
        required=("step",), optional=("last_good_step", "rundir")
    ),
    "StepHangError": ErrorContract(
        required=("waited_s", "rundir"), optional=("step",)
    ),
    "CheckpointCorruptError": ErrorContract(
        required=("step",), optional=("problems",)
    ),
    "CheckpointWriteError": ErrorContract(
        required=("step", "attempts"), optional=("directory",)
    ),
    # sampling/ops.py
    "HotSwapError": ErrorContract(
        required=("reason",), optional=("path", "expected", "got")
    ),
    "PoolResizeError": ErrorContract(
        required=("requested_pages", "resident_pages", "num_pages"),
        optional=("requested_slots", "live_slots", "retryable"),
    ),
    # sampling/serve.py — `retry_after_pages` is a derived property, NOT a
    # constructor field; listing it here would bless a TypeError.
    "BackpressureError": ErrorContract(
        required=("needed_pages", "backlog_pages", "budget_pages", "retryable")
    ),
    # sampling/disagg.py
    "HandoffRetryExhausted": ErrorContract(required=("uid", "attempts")),
    # sampling/fleet_proc.py — the cross-process transport triad: a failed
    # attempt (retryable), a rejected frame (pre-decode), a dead replica
    # (retry budget spent). Handlers key on host/port/rpc to name the
    # replica and verb in failover logs and chaos summaries.
    "TransportError": ErrorContract(
        required=("host", "port", "rpc"), optional=("deadline_s",)
    ),
    "WireFrameError": ErrorContract(
        required=("reason",), optional=("nbytes",)
    ),
    "ReplicaGoneError": ErrorContract(
        required=("host", "port", "rpc", "attempts")
    ),
}
