"""Shared checker for the one-JSON-line driver contract.

bench.py and tools/bench_serve.py each print exactly ONE line of JSON to
stdout and the driver consumes it blind — a stray print, a NaN (json.dumps
emits bare `NaN`, which is not JSON), or a silently renamed field breaks
the pipeline with no test noticing. This module is the single place the
contract is written down; tests/test_bench_contract.py runs the real bench
entry points and validates their stdout through it, and the graftcheck CLI
validates its own --json output the same way.

Checkers return a list of problem strings (empty = conformant) rather than
raising, so callers can aggregate.
"""

from __future__ import annotations

import json
import typing as tp

Number = (int, float)


def _reject_nonfinite(value: str) -> tp.NoReturn:
    raise ValueError(f"non-finite JSON constant {value!r} (NaN/Infinity is not JSON)")


def parse_single_json_line(stdout: str) -> tp.Tuple[tp.Optional[dict], tp.List[str]]:
    """Enforce 'stdout is exactly one JSON object line'. Returns (record,
    problems); record is None when parsing failed."""
    problems: tp.List[str] = []
    lines = [l for l in stdout.splitlines() if l.strip()]
    if len(lines) != 1:
        problems.append(f"expected exactly 1 non-empty stdout line, got {len(lines)}")
        if not lines:
            return None, problems
    try:
        rec = json.loads(lines[-1], parse_constant=_reject_nonfinite)
    except ValueError as e:
        problems.append(f"last line is not valid JSON: {e}")
        return None, problems
    if not isinstance(rec, dict):
        problems.append(f"JSON line is a {type(rec).__name__}, not an object")
        return None, problems
    return rec, problems


def _require(
    rec: dict, spec: tp.Dict[str, tp.Tuple[type, ...]], problems: tp.List[str]
) -> None:
    for key, types in spec.items():
        if key not in rec:
            problems.append(f"missing required field {key!r}")
        elif not isinstance(rec[key], types) or isinstance(rec[key], bool):
            problems.append(
                f"field {key!r} has type {type(rec[key]).__name__}, expected "
                + "/".join(t.__name__ for t in types)
            )


def check_train_bench(rec: dict) -> tp.List[str]:
    """bench.py profile: {metric, value, unit, vs_baseline, detail}."""
    problems: tp.List[str] = []
    _require(
        rec,
        {"metric": (str,), "value": Number, "unit": (str,), "detail": (dict,)},
        problems,
    )
    if "vs_baseline" not in rec:
        problems.append("missing required field 'vs_baseline'")
    elif rec["vs_baseline"] is not None and not isinstance(rec["vs_baseline"], Number):
        problems.append("field 'vs_baseline' must be a number or null")
    if isinstance(rec.get("detail"), dict):
        _require(
            rec["detail"],
            {"tokens_per_sec": Number, "step_ms": Number, "n_devices": (int,)},
            problems,
        )
    return problems


def _require_round_decomp(rec: dict, problems: tp.List[str]) -> None:
    """round_host_ms / round_device_ms / overlap_hidden_ms: the decode-round
    split the flight recorder measures (docs/OBSERVABILITY.md). Each is
    {p50, p95} in ms, finite (NaN already rejected at parse) and
    non-negative. Round-overlap dispatch (docs/SERVING.md) rides the same
    records: `overlap_mode` names the dispatch mode, `round_group` the
    fused rounds per dispatch (1 unless mode is 'group'), and
    `overlap_hidden_ms` the host time hidden under in-flight dispatches —
    an honest zero when overlap is off, which is why the fields are
    required rather than optional: their absence is a silent A/B lie."""
    for key in ("round_host_ms", "round_device_ms", "overlap_hidden_ms"):
        d = rec.get(key)
        if not isinstance(d, dict):
            problems.append(f"field {key!r} must be an object with p50/p95")
            continue
        for q in ("p50", "p95"):
            v = d.get(q)
            if not isinstance(v, Number) or isinstance(v, bool):
                problems.append(f"field {key!r}.{q} must be a number")
            elif v < 0:
                problems.append(f"{key}.{q} {v} < 0")
    mode = rec.get("overlap_mode")
    if mode not in ("off", "double", "group"):
        problems.append(
            f"field 'overlap_mode' is {mode!r}, expected off/double/group"
        )
    rg = rec.get("round_group")
    if not isinstance(rg, int) or isinstance(rg, bool) or rg < 1:
        problems.append(f"field 'round_group' must be an int >= 1, got {rg!r}")
    elif mode != "group" and rg != 1:
        problems.append(f"round_group {rg} with overlap_mode {mode!r} — "
                        "groups only exist in 'group' mode")


def check_serve_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py profile (field table: docs/SERVING.md)."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "continuous_tok_s": Number,
            "sequential_tok_s": Number,
            "speedup": Number,
            "p50_token_ms": Number,
            "p99_token_ms": Number,
            "ttft_ms_mean": Number,
            "ttft_ms_p50": Number,
            "ttft_ms_p95": Number,
            "req_tok_s_p50": Number,
            "req_tok_s_p95": Number,
            "decode_rounds": (int,),
            "kv_dtype": (str,),
            "num_pages": (int,),
            "preemptions": (int,),
            "cache_hbm_bytes": (int,),
            "hbm_paged_cache_bytes": (int,),
            "hbm_sequential_cache_bytes": (int,),
            "model": (dict,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve":
        problems.append(f"field 'bench' is {rec.get('bench')!r}, expected 'serve'")
    _require_round_decomp(rec, problems)
    if rec.get("kv_dtype") not in (None, "bf16", "int8"):
        problems.append(f"field 'kv_dtype' is {rec.get('kv_dtype')!r}")
    if "device_peak_bytes_in_use" not in rec:
        problems.append("missing required field 'device_peak_bytes_in_use'")
    elif rec["device_peak_bytes_in_use"] is not None and not isinstance(
        rec["device_peak_bytes_in_use"], int
    ):
        problems.append("field 'device_peak_bytes_in_use' must be int or null")
    # int8 runs carry the bf16-comparison block; when present it must be
    # coherent (the driver keys the capacity claim off these numbers)
    gmf = rec.get("greedy_match_frac")
    if gmf is not None and (not isinstance(gmf, Number) or not 0.0 <= gmf <= 1.0):
        problems.append(f"greedy_match_frac {gmf!r} outside [0, 1]")
    if rec.get("kv_dtype") == "int8" and "greedy_match_frac" not in rec:
        problems.append("int8 serve record missing 'greedy_match_frac'")
    return problems


def check_serve_spec_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --spec profile: speculative vs plain continuous
    engine on the same trace (field table: docs/SERVING.md)."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "model": (dict,),
            "draft_layers": (int,),
            "spec_k_max": (int,),
            "train_steps": (int,),
            "baseline_tok_s": Number,
            "spec_tok_s": Number,
            "speedup_spec": Number,
            "accept_rate": Number,
            "tokens_per_verify": Number,
            "kv_dtype": (str,),
            "cache_hbm_bytes": (int,),
            "hbm_target_cache_bytes": (int,),
            "hbm_draft_cache_bytes": (int,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_spec":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_spec'"
        )
    ar = rec.get("accept_rate")
    if isinstance(ar, Number) and not 0.0 <= ar <= 1.0:
        problems.append(f"accept_rate {ar} outside [0, 1]")
    tpv = rec.get("tokens_per_verify")
    if isinstance(tpv, Number) and tpv < 1.0 and rec.get("n_requests", 0) > 0:
        # every verify yields at least its correction/bonus token
        problems.append(f"tokens_per_verify {tpv} < 1 — counter drift?")
    return problems


def check_serve_prefix_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --shared-prefix-frac profile: the template
    workload run cache-off then cache-on at the same page budget (field
    table: docs/SERVING.md 'Prefix cache'). The load-bearing invariant is
    greedy_match_frac == 1.0 EXACTLY — prefix sharing is page-table
    indirection over bit-identical K/V, so any mismatch at all means a
    torn page, not noise — which makes it a schema check, not a quality
    threshold."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "shared_prefix_frac": Number,
            "n_templates": (int,),
            "template_tokens": (int,),
            "kv_dtype": (str,),
            "num_pages": (int,),
            "model": (dict,),
            "baseline_tok_s": Number,
            "prefix_tok_s": Number,
            "speedup_prefix": Number,
            "baseline_ttft_ms_p50": Number,
            "baseline_ttft_ms_p95": Number,
            "prefix_ttft_ms_p50": Number,
            "prefix_ttft_ms_p95": Number,
            "prefix_hit_rate": Number,
            "cow_pages": (int,),
            "baseline_prefill_tokens": (int,),
            "prefix_prefill_tokens": (int,),
            "baseline_preemptions": (int,),
            "prefix_preemptions": (int,),
            "trie_pages": (int,),
            "reclaimed_pages": (int,),
            "greedy_match_frac": Number,
            "cache_hbm_bytes": (int,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_prefix":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_prefix'"
        )
    hr = rec.get("prefix_hit_rate")
    if isinstance(hr, Number) and not 0.0 <= hr <= 1.0:
        problems.append(f"prefix_hit_rate {hr} outside [0, 1]")
    gmf = rec.get("greedy_match_frac")
    if isinstance(gmf, Number) and gmf != 1.0:
        problems.append(
            f"greedy_match_frac {gmf} != 1.0 — prefix sharing must be "
            "bit-invisible to greedy streams"
        )
    pf = rec.get("prefix_prefill_tokens")
    bf = rec.get("baseline_prefill_tokens")
    if isinstance(pf, int) and isinstance(bf, int) and pf > bf:
        problems.append(
            f"prefix run prefilled MORE tokens than baseline ({pf} > {bf})"
        )
    return problems


def check_serve_tp_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --tp profile: the same greedy trace through a
    single-chip engine and a tensor-parallel mesh-sharded engine, per cache
    mode (base dtype / int8 / self-draft speculation). The load-bearing
    invariant is match_* == 1.0 EXACTLY for every mode — tp sharding splits
    head-aligned einsums whose all-reduce restores the same f32 partials a
    single chip computes, so any token divergence means a wrong sharding
    spec or a torn collective, not noise (tests/test_tp_serving.py pins the
    same matrix). Per-shard HBM arithmetic is checked exactly: the pool is
    sharded on the head axis, so each shard holds total/tp bytes."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "max_slots": (int,),
            "page_size": (int,),
            "tp": (int,),
            "n_devices": (int,),
            "mesh": (dict,),
            "base_dtype": (str,),
            "model": (dict,),
            "train_steps": (int,),
            "train_loss": Number,
            "draft_layers": (int,),
            "spec_k_max": (int,),
            "match_f32": Number,
            "match_int8": Number,
            "match_spec": Number,
            "single_tok_s_f32": Number,
            "single_tok_s_int8": Number,
            "single_tok_s_spec": Number,
            "tp_tok_s_f32": Number,
            "tp_tok_s_int8": Number,
            "tp_tok_s_spec": Number,
            "num_pages": (int,),
            "int8_num_pages": (int,),
            "cache_hbm_bytes": (int,),
            "cache_hbm_bytes_per_shard": (int,),
            "hbm_per_slot_per_shard_bytes": (int,),
            "int8_cache_hbm_bytes_per_shard": (int,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_tp":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_tp'"
        )
    ntp = rec.get("tp")
    if isinstance(ntp, int) and ntp < 2:
        problems.append(f"tp {ntp} < 2 — the tp profile requires a sharded mesh")
    mesh = rec.get("mesh")
    if isinstance(mesh, dict) and isinstance(ntp, int) and mesh.get("tp") != ntp:
        problems.append(f"mesh {mesh} does not carry tp={ntp}")
    for mode in ("f32", "int8", "spec"):
        m = rec.get(f"match_{mode}")
        if isinstance(m, Number) and m != 1.0:
            problems.append(
                f"match_{mode} {m} != 1.0 — tp sharding must be bit-invisible "
                "to greedy streams"
            )
    total = rec.get("cache_hbm_bytes")
    shard = rec.get("cache_hbm_bytes_per_shard")
    slot = rec.get("hbm_per_slot_per_shard_bytes")
    slots = rec.get("max_slots")
    if isinstance(total, int) and isinstance(shard, int) and isinstance(ntp, int):
        if shard * ntp != total:
            problems.append(
                f"per-shard bytes {shard} * tp {ntp} != pool bytes {total}"
            )
    if isinstance(shard, int) and isinstance(slot, int) and isinstance(slots, int):
        if slots > 0 and slot != shard // slots:
            problems.append(
                f"hbm_per_slot_per_shard_bytes {slot} != "
                f"{shard} // max_slots {slots}"
            )
    return problems


def check_serve_longctx_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --long-ctx profile: split-K decode A/B (field
    table: docs/SERVING.md 'Split-K decode'). Two load-bearing invariants:

      * greedy_match_frac == 1.0 EXACTLY — split-K reorders f32 softmax
        reductions, so the bench pins that on a fitted model the argmax
        margins absorb the reorder (tests/test_split_k.py pins the same
        matrix per cache mode); any mismatch is a kernel bug or a model
        with no margins, either of which invalidates the record.
      * split_k_short == 1 — the no-regression-at-short-T guarantee is
        structural: the auto bucket rule must keep short traffic on the
        byte-identical unsplit program. The forced-split short latency
        (short_ratio) is recorded as diagnostic context, not gated — on
        tiny CPU-mesh rounds it is dominated by per-dispatch overhead.

    split_k_long >= 2 and t_long >= 1024 keep the record an actual A/B:
    an unsplit-vs-unsplit run would vacuously 'match'."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "t_long": (int,),
            "t_short": (int,),
            "page_size": (int,),
            "decode_chunk": (int,),
            "rounds": (int,),
            "kv_dtype": (str,),
            "model": (dict,),
            "split_k_long": (int,),
            "split_k_short": (int,),
            "ms_round_long_unsplit": Number,
            "ms_round_long_split": Number,
            "long_speedup": Number,
            "ms_round_short_unsplit": Number,
            "ms_round_short_forced_split": Number,
            "short_ratio": Number,
            "match_block_size": (int,),
            "greedy_match_frac": Number,
            "train_steps": (int,),
            "train_loss": Number,
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_longctx":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_longctx'"
        )
    tl = rec.get("t_long")
    if isinstance(tl, int) and tl < 1024:
        problems.append(f"t_long {tl} < 1024 — below the auto-split regime")
    sl = rec.get("split_k_long")
    if isinstance(sl, int) and sl < 2:
        problems.append(
            f"split_k_long {sl} < 2 — the long point never engaged split-K, "
            "so the A/B is vacuous"
        )
    ss = rec.get("split_k_short")
    if isinstance(ss, int) and ss != 1:
        problems.append(
            f"split_k_short {ss} != 1 — short traffic must stay on the "
            "unsplit program (the structural no-regression guarantee)"
        )
    gmf = rec.get("greedy_match_frac")
    if isinstance(gmf, Number) and gmf != 1.0:
        problems.append(
            f"greedy_match_frac {gmf} != 1.0 — split-K must be invisible "
            "to greedy streams"
        )
    for key in ("ms_round_long_unsplit", "ms_round_long_split",
                "ms_round_short_unsplit", "ms_round_short_forced_split"):
        v = rec.get(key)
        if isinstance(v, Number) and v <= 0:
            problems.append(f"{key} {v} <= 0")
    return problems


def check_serve_gqa_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --gqa profile: GQA/MQA KV-capacity A/B at a
    fixed pool byte budget (docs/SERVING.md 'Attention variants'). The
    load-bearing invariants:

      * pages_ratio >= 0.75 * kv_groups — a GQA page is group-factor
        smaller, so the same budget must admit (nearly) group-factor more
        pages; the 0.75 floor absorbs the max(2, ...)/sink rounding of the
        byte-budgeted sizing (the acceptance shape, 4x grouping, must
        clear 3x).
      * strictly fewer GQA preemptions on an oversubscribed trace, with
        mha_preemptions > 0 required — a trace the MHA pool absorbs
        without preempting proves nothing about capacity.
      * BOTH greedy_match_frac_* == 1.0 EXACTLY — each variant's paged
        streams vs dense-cache engine.generate on the same params; any
        mismatch is a kernel/cache bug, not noise (capacity must be the
        only thing the A/B varies).

    kv_groups >= 2 keeps the record an actual A/B (an MHA-vs-MHA run
    would vacuously 'match')."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "max_slots": (int,),
            "page_size": (int,),
            "kv_dtype": (str,),
            "pool_hbm_bytes": (int,),
            "model": (dict,),
            "kv_groups": (int,),
            "n_kv_heads": (int,),
            "sliding_window": (int,),
            "attn_sinks": (int,),
            "mha_page_bytes": (int,),
            "gqa_page_bytes": (int,),
            "mha_num_pages": (int,),
            "gqa_num_pages": (int,),
            "pages_ratio": Number,
            "mha_slots_capacity": (int,),
            "gqa_slots_capacity": (int,),
            "mha_preemptions": (int,),
            "gqa_preemptions": (int,),
            "mha_tok_s": Number,
            "gqa_tok_s": Number,
            "window_reclaimed_pages": (int,),
            "greedy_match_frac_mha": Number,
            "greedy_match_frac_gqa": Number,
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_gqa":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_gqa'"
        )
    groups = rec.get("kv_groups")
    if isinstance(groups, int) and groups < 2:
        problems.append(f"kv_groups {groups} < 2 — the A/B is vacuous")
    ratio = rec.get("pages_ratio")
    if (
        isinstance(ratio, Number)
        and isinstance(groups, int)
        and ratio < 0.75 * groups
    ):
        problems.append(
            f"pages_ratio {ratio} < 0.75 * kv_groups ({0.75 * groups}) — "
            "the fixed byte budget did not convert into KV-head-scaled "
            "page capacity"
        )
    pe_m, pe_g = rec.get("mha_preemptions"), rec.get("gqa_preemptions")
    if isinstance(pe_m, int) and pe_m == 0:
        problems.append(
            "mha_preemptions == 0 — the trace never oversubscribed the MHA "
            "pool, so the preemption comparison proves nothing (shrink "
            "pool_hbm_bytes or grow the trace)"
        )
    if isinstance(pe_m, int) and isinstance(pe_g, int) and pe_g >= pe_m > 0:
        problems.append(
            f"gqa_preemptions {pe_g} >= mha_preemptions {pe_m} — the extra "
            "pages must buy strictly fewer recompute preemptions"
        )
    for key in ("greedy_match_frac_mha", "greedy_match_frac_gqa"):
        v = rec.get(key)
        if isinstance(v, Number) and v != 1.0:
            problems.append(
                f"{key} {v} != 1.0 — paged reads must be bit-identical to "
                "dense-cache reads per variant"
            )
    w = rec.get("sliding_window")
    if isinstance(w, int) and w < 0:
        problems.append(f"sliding_window {w} < 0")
    return problems


def check_serve_ops_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --hot-swap profile: zero-downtime model ops
    (docs/ROBUSTNESS.md 'Zero-downtime model ops'). A verified-checkpoint
    blue/green weight swap lands mid-trace, then the pool grows live; the
    record carries the downtime claim, so its gates are structural:

      * dropped == 0 — zero-downtime means every admitted stream finishes.
      * swap_recompiles == 0 EXACTLY — a same-shape swap device_puts the
        candidate onto the live shardings, so the serving jits' caches must
        not grow at all; any new program means the staged params took a new
        compile key and the 'live' in 'live swap' is a lie.
      * parity_old_side + parity_new_side == n_requests, both sides >= 1 —
        streams served before the flip must be bit-identical to the old
        weights' reference, streams admitted after to the new weights'; an
        empty side means the swap landed outside the traffic window and the
        A/B is vacuous.
      * pages_migrated >= 1 and pages_conserved — the resize leg actually
        moved a resident working set and the free+trie+live accounting
        closed at every boundary."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "model": (dict,),
            "num_pages": (int,),
            "kv_dtype": (str,),
            "checkpoint_step": (int,),
            "weights_version_before": (str,),
            "weights_version_after": (str,),
            "swap_latency_ms": Number,
            "streams_in_flight_at_flip": (int,),
            "staged_round": (int,),
            "flip_round": (int,),
            "dropped": (int,),
            "parity_old_side": (int,),
            "parity_new_side": (int,),
            "swap_recompiles": (int,),
            "resize_from_pages": (int,),
            "resize_to_pages": (int,),
            "pages_migrated": (int,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_ops":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_ops'"
        )
    if rec.get("dropped") != 0:
        problems.append(
            f"dropped {rec.get('dropped')!r} != 0 — a zero-downtime swap "
            "must finish every admitted stream"
        )
    if rec.get("swap_recompiles") != 0:
        problems.append(
            f"swap_recompiles {rec.get('swap_recompiles')!r} != 0 — a "
            "same-shape hot swap must reuse every compiled program"
        )
    po, pn, nr = (rec.get(k) for k in
                  ("parity_old_side", "parity_new_side", "n_requests"))
    if isinstance(po, int) and isinstance(pn, int):
        if po < 1 or pn < 1:
            problems.append(
                f"parity sides {po}/{pn} — the flip must land inside the "
                "traffic window (both sides non-empty)"
            )
        if isinstance(nr, int) and po + pn != nr:
            problems.append(
                f"parity_old_side {po} + parity_new_side {pn} != "
                f"n_requests {nr} — some stream matched neither reference"
            )
    if rec.get("weights_version_before") == rec.get("weights_version_after"):
        problems.append("weights_version did not change across the swap")
    pm = rec.get("pages_migrated")
    if isinstance(pm, int) and pm < 1:
        problems.append(f"pages_migrated {pm} < 1 — the resize leg was vacuous")
    if "pages_conserved" not in rec or rec["pages_conserved"] is not True:
        problems.append("field 'pages_conserved' must be literal true")
    sl = rec.get("swap_latency_ms")
    if isinstance(sl, Number) and sl < 0:
        problems.append(f"swap_latency_ms {sl} < 0")
    return problems


def check_serve_fleet_bench(rec: dict) -> tp.List[str]:
    """tools/bench_serve.py --fleet profile: the shared-template trace
    through one engine, then through an N-replica FleetRouter with a
    replica killed mid-trace (docs/ROBUSTNESS.md 'Fleet serving &
    failover'). The record carries the fleet's availability claim, so its
    gates are structural:

      * failovers >= 1 and dropped == 0 — a replica actually died and the
        fleet still finished every accepted stream (otherwise the record
        measured an unfaulted fleet and claims nothing).
      * greedy_match_frac == 1.0 EXACTLY with parity_checked ==
        n_requests — every stream, survivors and failover replays alike,
        bit-matches the single-engine pass; failover replays the original
        prompt with the full budget and greedy streams are
        batch-composition-independent, so any mismatch is a router bug
        (or a spill page that poisoned a decode), not noise.
      * fleet_hit_rate >= single_hit_rate — prefix-affinity routing
        exists so the fleet trie hit rate does NOT dilute toward 1/N of
        the single engine's; a lower rate means the rendezvous hash
        stopped steering templates to their pages.
      * pages_conserved — per-alive-replica pool law plus the spill
        ledger closed after the drain.

    With `procs` true (bench_serve.py --fleet --procs: replicas are
    worker PROCESSES behind the socket transport, the fault a real kill
    -9 — docs/ROBUSTNESS.md 'Cross-process fleet') two gates shift:
    the hit-rate ordering is NOT required — a SIGKILLed worker takes
    its per-process host-RAM tier with it, so the KV the in-process
    crash path spills and re-adopts is unrecoverable and the survivor
    honestly re-prefills (zero-drop and exact-parity still hold, and
    still ARE required) — and the record must carry the transport
    claim: proc_failovers >= 1 (the death was detected through the
    wire) plus rpc_p50_ms / rpc_p95_ms / wire_bytes. Both branches are
    drift-pinned by tests/test_bench_contract.py."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "n_requests": (int,),
            "total_new_tokens": (int,),
            "fleet_size": (int,),
            "model": (dict,),
            "kv_dtype": (str,),
            "num_pages": (int,),
            "n_templates": (int,),
            "single_tok_s": Number,
            "fleet_tok_s": Number,
            "single_hit_rate": Number,
            "fleet_hit_rate": Number,
            "failovers": (int,),
            "failed_over_streams": (int,),
            "dropped": (int,),
            "parity_checked": (int,),
            "greedy_match_frac": Number,
            "spill_readopted_pages": (int,),
            "spill": (dict,),
            "compile_counts": (dict,),
        },
        problems,
    )
    if rec.get("bench") != "serve_fleet":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_fleet'"
        )
    fs = rec.get("fleet_size")
    if isinstance(fs, int) and fs < 2:
        problems.append(
            f"fleet_size {fs} < 2 — a one-replica fleet cannot fail over"
        )
    if rec.get("failovers") == 0:
        problems.append(
            "failovers == 0 — no replica died, the availability A/B is vacuous"
        )
    if rec.get("dropped") != 0:
        problems.append(
            f"dropped {rec.get('dropped')!r} != 0 — failover must finish "
            "every accepted stream"
        )
    gmf = rec.get("greedy_match_frac")
    if isinstance(gmf, Number) and gmf != 1.0:
        problems.append(
            f"greedy_match_frac {gmf} != 1.0 — failover replays and spill "
            "re-adoption must be bit-invisible to greedy streams"
        )
    pc, nr = rec.get("parity_checked"), rec.get("n_requests")
    if isinstance(pc, int) and isinstance(nr, int) and pc != nr:
        problems.append(
            f"parity_checked {pc} != n_requests {nr} — some stream was "
            "never checked against the single-engine reference"
        )
    fh, sh = rec.get("fleet_hit_rate"), rec.get("single_hit_rate")
    for name, v in (("fleet_hit_rate", fh), ("single_hit_rate", sh)):
        if isinstance(v, Number) and not 0.0 <= v <= 1.0:
            problems.append(f"{name} {v} outside [0, 1]")
    procs = rec.get("procs", False)
    if not isinstance(procs, bool):
        problems.append(f"field 'procs' must be a bool, got {procs!r}")
        procs = False
    if (
        not procs
        and isinstance(fh, Number) and isinstance(sh, Number) and fh < sh
    ):
        problems.append(
            f"fleet_hit_rate {fh} < single_hit_rate {sh} — affinity "
            "routing failed to protect the trie hit rate"
        )
    if procs:
        _require(
            rec,
            {
                "proc_failovers": (int,),
                "worker_pids": (list,),
                "transport": (dict,),
                "rpc_p50_ms": Number,
                "rpc_p95_ms": Number,
                "wire_bytes": (int,),
            },
            problems,
        )
        pf = rec.get("proc_failovers")
        if isinstance(pf, int) and pf < 1:
            problems.append(
                f"proc_failovers {pf} < 1 — kill -9 never detected "
                "through the wire, the cross-process A/B is vacuous"
            )
        wb = rec.get("wire_bytes")
        if isinstance(wb, int) and wb < 1:
            problems.append(
                f"wire_bytes {wb} < 1 — no frame ever crossed the socket"
            )
        for key in ("rpc_p50_ms", "rpc_p95_ms"):
            v = rec.get(key)
            if isinstance(v, Number) and v < 0:
                problems.append(f"{key} {v} < 0")
    if "pages_conserved" not in rec or rec["pages_conserved"] is not True:
        problems.append("field 'pages_conserved' must be literal true")
    return problems


def check_serve_slo_bench(rec: dict) -> tp.List[str]:
    """tools/loadgen.py profile: TTFT/TPOT percentiles + shed fraction
    under a seeded arrival process, at >= 2 offered-load points (one point
    is a measurement; the contract wants the start of an SLO curve). The
    headline fields mirror the hottest point so drivers can gate without
    digging into `points`. NaN rejection rides parse_single_json_line."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "bench": (str,),
            "backend": (str,),
            "process": (str,),
            "scheduler": (str,),
            "seed": (int,),
            "n_requests": (int,),
            "error_budget": Number,
            "model": (dict,),
            "points": (list,),
            "ttft_p50_ms": Number,
            "ttft_p95_ms": Number,
            "tpot_p50_ms": Number,
            "tpot_p95_ms": Number,
            "shed_frac": Number,
            "timeout_frac": Number,
        },
        problems,
    )
    if rec.get("bench") != "serve_slo":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'serve_slo'"
        )
    _require_round_decomp(rec, problems)
    if rec.get("process") not in (None, "poisson", "bursty"):
        problems.append(f"field 'process' is {rec.get('process')!r}")
    if "slo_ok" not in rec or not isinstance(rec["slo_ok"], bool):
        problems.append("field 'slo_ok' must be a bool")
    points = rec.get("points")
    if isinstance(points, list):
        if len(points) < 2:
            problems.append(
                f"{len(points)} load point(s) — the SLO profile requires "
                ">= 2 offered-load points"
            )
        for i, p in enumerate(points):
            if not isinstance(p, dict):
                problems.append(f"points[{i}] is not an object")
                continue
            pp: tp.List[str] = []
            _require(
                p,
                {
                    "offered_rps": Number,
                    "n_offered": (int,),
                    "completed": (int,),
                    "shed": (int,),
                    "timeouts": (int,),
                    "shed_frac": Number,
                    "timeout_frac": Number,
                    "ttft_p50_ms": Number,
                    "ttft_p95_ms": Number,
                    "tpot_p50_ms": Number,
                    "tpot_p95_ms": Number,
                    "rounds": (int,),
                },
                pp,
            )
            _require_round_decomp(p, pp)
            problems.extend(f"points[{i}]: {q}" for q in pp)
            # optional: present when loadgen ran with --prefix-cache
            for frac in ("shed_frac", "timeout_frac", "prefix_hit_rate"):
                v = p.get(frac)
                if isinstance(v, Number) and not 0.0 <= v <= 1.0:
                    problems.append(f"points[{i}].{frac} {v} outside [0, 1]")
    sf = rec.get("shed_frac")
    if isinstance(sf, Number) and not 0.0 <= sf <= 1.0:
        problems.append(f"shed_frac {sf} outside [0, 1]")
    # optional fleet block: present when loadgen ran with --fleet N
    # (headline mirrors the hottest point, like the SLO percentiles)
    fs = rec.get("fleet_size")
    if fs is not None:
        if not isinstance(fs, int) or fs < 1:
            problems.append(f"fleet_size {fs!r} must be an int >= 1")
        for key in ("failovers", "spill_hits"):
            v = rec.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(
                    f"fleet record field {key!r} must be an int >= 0, "
                    f"got {v!r}"
                )
        hr = rec.get("prefix_hit_rate")
        if not isinstance(hr, Number) or not 0.0 <= hr <= 1.0:
            problems.append(
                f"fleet record 'prefix_hit_rate' {hr!r} outside [0, 1]"
            )
    # optional cross-process block: present when loadgen ran --fleet
    # --procs (replicas are worker processes behind the socket transport;
    # docs/ROBUSTNESS.md "Cross-process fleet")
    if rec.get("procs"):
        if fs is None:
            problems.append("procs is true but fleet_size is absent")
        for key in ("rpc_p50_ms", "rpc_p95_ms"):
            v = rec.get(key)
            if not isinstance(v, Number) or v < 0:
                problems.append(
                    f"procs record field {key!r} must be a number >= 0, "
                    f"got {v!r}"
                )
        wb = rec.get("wire_bytes")
        if not isinstance(wb, int) or isinstance(wb, bool) or wb < 1:
            problems.append(
                f"procs record 'wire_bytes' {wb!r} must be an int >= 1 — "
                "no frame ever crossed the socket"
            )
    return problems


def check_train_chaos(rec: dict) -> tp.List[str]:
    """tools/chaos_run.py degraded-IO / elastic-topology summary
    (docs/ROBUSTNESS.md "Elastic resume & watchdog"): a supervised training
    run with hang_step / ckpt_enospc / resume_reshard armed. The record
    carries the recovery claim, so its gates are structural:

      * status == "ok" and at least one requested fault actually FIRED —
        an unfaulted pass claims nothing about recovery.
      * detected_at_ms is a number >= 0 (the registry observer timestamped
        the first firing; a null means the plan never triggered).
      * loss_parity is literal true — the post-recovery trajectory matches
        an unfaulted reference run of the same config (rtol covers only
        the f32 reassociation of a re-derived data-axis all-reduce after
        a mesh change; the batch order is positional and exact).
      * final_mesh names the geometry the run FINISHED on (axes + device
        count) so a resume_reshard record proves the topology actually
        changed hands."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "tool": (str,),
            "bench": (str,),
            "status": (str,),
            "wall_s": Number,
            "faults_requested": (list,),
            "faults_fired": (dict,),
            "detected_at_ms": Number,
            "restarts": (int,),
            "final_mesh": (dict,),
            "n_devices_final": (int,),
            "loss_final": Number,
        },
        problems,
    )
    if rec.get("bench") != "train_chaos":
        problems.append(
            f"field 'bench' is {rec.get('bench')!r}, expected 'train_chaos'"
        )
    if rec.get("status") != "ok":
        problems.append(
            f"status {rec.get('status')!r} != 'ok' — recovery did not complete"
        )
    fired = rec.get("faults_fired")
    if isinstance(fired, dict) and sum(fired.values()) < 1:
        problems.append(
            "faults_fired is empty — no fault fired, the recovery claim is vacuous"
        )
    d = rec.get("detected_at_ms")
    if isinstance(d, Number) and d < 0:
        problems.append(f"detected_at_ms {d} < 0")
    if rec.get("loss_parity") is not True:
        problems.append(
            "field 'loss_parity' must be literal true — the recovered "
            "trajectory must match the unfaulted reference run"
        )
    fm = rec.get("final_mesh")
    if isinstance(fm, dict):
        if not isinstance(fm.get("n_devices"), int) or fm["n_devices"] < 1:
            problems.append(
                f"final_mesh.n_devices {fm.get('n_devices')!r} must be an int >= 1"
            )
        if not isinstance(fm.get("axes"), dict) or not fm.get("axes"):
            problems.append("final_mesh.axes must be a non-empty object")
    r = rec.get("restarts")
    if isinstance(r, int) and r < 0:
        problems.append(f"restarts {r} < 0")
    return problems


def check_graftcheck(rec: dict) -> tp.List[str]:
    """The graftcheck CLI's own --json line."""
    problems: tp.List[str] = []
    _require(
        rec,
        {
            "tool": (str,),
            "count": (int,),
            "suppressed": (int,),
            "files_scanned": (int,),
            "findings": (list,),
            "pass3_count": (int,),
            "pass3_suppressed": (int,),
            "pass3_wall_ms": (int, float),
            "pass4_count": (int,),
            "pass4_suppressed": (int,),
            "pass4_wall_ms": (int, float),
            "jit_surface_count": (int,),
        },
        problems,
    )
    for i, f in enumerate(rec.get("findings", [])):
        if not isinstance(f, dict):
            problems.append(f"findings[{i}] is not an object")
            continue
        _require(
            f,
            {"rule": (str,), "path": (str,), "line": (int,), "message": (str,)},
            problems,
        )
    return problems


PROFILES: tp.Dict[str, tp.Callable[[dict], tp.List[str]]] = {
    "train": check_train_bench,
    "serve": check_serve_bench,
    "serve_spec": check_serve_spec_bench,
    "serve_prefix": check_serve_prefix_bench,
    "serve_tp": check_serve_tp_bench,
    "serve_longctx": check_serve_longctx_bench,
    "serve_gqa": check_serve_gqa_bench,
    "serve_ops": check_serve_ops_bench,
    "serve_fleet": check_serve_fleet_bench,
    "serve_slo": check_serve_slo_bench,
    "train_chaos": check_train_chaos,
    "graftcheck": check_graftcheck,
}


def check_bench_stdout(
    stdout: str, profile: str
) -> tp.Tuple[tp.Optional[dict], tp.List[str]]:
    """Parse + schema-check a bench process's stdout against a profile."""
    rec, problems = parse_single_json_line(stdout)
    if rec is not None:
        problems.extend(PROFILES[profile](rec))
    return rec, problems
