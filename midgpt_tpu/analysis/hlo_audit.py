"""graftcheck pass 2: compiled-artifact audits over post-optimization HLO.

Extends utils/hlo.py (the parser the structural test pins already share)
with reusable assertions that turn scheduling/parity *claims* into
executable checks:

  * `CompileCounter` — counts actual XLA backend compiles via the
    jax.monitoring event stream, so tests can pin "N request mixes -> 0 new
    compiles" (SERVING.md: admitting/finishing requests never recompiles)
    and "the train step compiles exactly once".
  * `jit_cache_size` — the jit wrapper's executable-cache population (one
    entry per compiled program), for pinning the *total* compile set of a
    module-level jit like sampling/serve._serve_decode_chunk.
  * `while_body_collectives` / `assert_no_while_body_collectives` — a
    collective census of while-loop bodies (transitive through called
    computations), e.g. "no all-gathers inside the decode while body".
  * `entry_parameter_dtypes` / `assert_fp32_master_params` — the SURVEY.md
    §7.4 precision contract (fp32 master params, bf16 compute cast in-step)
    read off the lowered train step instead of trusted from a docstring.

Everything here imports jax lazily so `python -m midgpt_tpu.analysis`
(pass 1) stays free of backend initialization.
"""

from __future__ import annotations

import re
import typing as tp

from midgpt_tpu.utils.hlo import hlo_computations, while_body_names

# Event recorded once per actual XLA backend compilation (jax 0.4.x:
# jax/_src/compiler.py wraps backend.compile in record_event_duration_secs).
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(COLLECTIVE_OPS) + r")(?:-start|-done)?\("
)
# computations referenced by an instruction (fusions, while bodies, reducers)
_CALLEE_RE = re.compile(r"(?:calls|body|condition|to_apply)=%([\w.\-]+)")
_ENTRY_HEADER_RE = re.compile(r"^ENTRY\s+%?[\w.\-]+\s*\((?P<args>.*)\)\s*->")
_PARAM_TYPE_RE = re.compile(r":\s*\(?([a-z]+[0-9]*)\[")


class CompileCounter:
    """Counts XLA backend compiles within a `with` block.

    Wraps the jax.monitoring duration-event stream (the hook jax's own
    compile path reports through), so cache hits — the thing the serving
    pins care about distinguishing — count zero."""

    def __init__(self) -> None:
        self.count = 0

    def _listener(self, name: str, duration: float, **kw: tp.Any) -> None:
        if name == BACKEND_COMPILE_EVENT:
            self.count += 1

    def __enter__(self) -> "CompileCounter":
        import jax.monitoring

        self.count = 0
        jax.monitoring.register_event_duration_secs_listener(self._listener)
        return self

    def __exit__(self, *exc: tp.Any) -> None:
        from jax._src import monitoring as _monitoring

        _monitoring._unregister_event_duration_listener_by_callback(self._listener)


def jit_cache_size(fn: tp.Any) -> tp.Optional[int]:
    """Compiled-program count in a jit wrapper's cache (None if the jax
    version does not expose it). One entry per distinct (static args,
    input avals) combination that actually lowered + compiled."""
    probe = getattr(fn, "_cache_size", None)
    return probe() if callable(probe) else None


# ----------------------------------------------------------------------
# HLO text audits
# ----------------------------------------------------------------------


def _reachable(comps: tp.Dict[str, tp.List[str]], root: str) -> tp.Set[str]:
    seen = {root}
    frontier = [root]
    while frontier:
        name = frontier.pop()
        for line in comps.get(name, ()):
            for callee in _CALLEE_RE.findall(line):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
    return seen


def while_body_collectives(
    hlo_text: str, ops: tp.Sequence[str] = COLLECTIVE_OPS
) -> tp.Dict[str, tp.List[str]]:
    """{while_body_computation: [collective instruction lines]}, transitive
    through computations the body calls (fusions, nested control flow)."""
    comps = hlo_computations(hlo_text)
    wanted = re.compile(r"\b(" + "|".join(ops) + r")(?:-start|-done)?\(")
    census: tp.Dict[str, tp.List[str]] = {}
    for body in sorted(while_body_names(hlo_text)):
        hits: tp.List[str] = []
        for comp in _reachable(comps, body):
            hits.extend(l for l in comps.get(comp, ()) if wanted.search(l))
        census[body] = hits
    return census


def while_body_pool_copies(
    hlo_text: str, shape: str
) -> tp.Dict[str, tp.List[str]]:
    """{while_body: [copy instruction lines producing `shape`]}, transitive
    through called computations — the zero-in-loop-cache-copy census. The
    serving engine's perf story rests on its KV pools aliasing through loop
    carries (decode chunk AND speculative verify): a pool-sized copy inside
    a while body means every loop iteration re-materializes the pool
    (2.5 ms/token measured when the r1-r4 decode structure did exactly
    that, RESULTS.md §1). `shape` is the literal HLO shape string, e.g.
    'f32[2,2,9,8,16]'. One-time entry copies OUTSIDE loop bodies are fine
    and not counted."""
    comps = hlo_computations(hlo_text)
    wanted = re.compile(rf"= {re.escape(shape)}[^=]*copy\(")
    census: tp.Dict[str, tp.List[str]] = {}
    for body in sorted(while_body_names(hlo_text)):
        hits: tp.List[str] = []
        for comp in _reachable(comps, body):
            hits.extend(l for l in comps.get(comp, ()) if wanted.search(l))
        census[body] = hits
    return census


def assert_no_while_body_collectives(
    hlo_text: str, ops: tp.Sequence[str] = ("all-gather",)
) -> None:
    census = while_body_collectives(hlo_text, ops)
    offenders = {b: ls for b, ls in census.items() if ls}
    assert not offenders, (
        f"collectives {ops} found inside while bodies: "
        + "; ".join(f"{b}: {ls[0]}" for b, ls in offenders.items())
    )


def entry_parameter_dtypes(hlo_text: str) -> tp.List[str]:
    """Dtype strings of the ENTRY computation's parameters, in order."""
    for line in hlo_text.splitlines():
        m = _ENTRY_HEADER_RE.match(line.strip())
        if m:
            return _PARAM_TYPE_RE.findall(m.group("args"))
    raise ValueError("no ENTRY computation header found in HLO text")


def fp32_master_param_audit(hlo_text: str) -> tp.Dict[str, int]:
    """Counts used by assert_fp32_master_params (exposed for reporting)."""
    dtypes = entry_parameter_dtypes(hlo_text)
    return {
        "n_params": len(dtypes),
        "n_f32": sum(d == "f32" for d in dtypes),
        "n_reduced": sum(d in ("bf16", "f16") for d in dtypes),
        "has_bf16_compute": int(" bf16[" in hlo_text or "=bf16[" in hlo_text),
    }


def assert_fp32_master_params(
    hlo_text: str, expect_bf16_compute: bool = True
) -> tp.Dict[str, int]:
    """The SURVEY.md §7.4 precision contract on a lowered train step: every
    floating-point ENTRY parameter (master params + optimizer state) is f32
    — none arrive half-precision — while the program body still computes in
    bf16 (the per-step cast). Returns the audit counts."""
    audit = fp32_master_param_audit(hlo_text)
    assert audit["n_reduced"] == 0, (
        f"{audit['n_reduced']} reduced-precision entry parameters — master "
        "params/optimizer state must be fp32 (SURVEY.md §7.4)"
    )
    assert audit["n_f32"] > 0, "no f32 entry parameters found — wrong program?"
    if expect_bf16_compute:
        assert audit["has_bf16_compute"], (
            "no bf16 values anywhere in the program — the compute-dtype cast "
            "is missing (or the config under audit is not bf16-compute)"
        )
    return audit


# ----------------------------------------------------------------------
# built-in audit suite (CLI --audit)
# ----------------------------------------------------------------------


def run_audit() -> tp.Dict[str, tp.Any]:
    """Fast CPU-only audit of the two flagship compiled artifacts.

    Lowers (a) the train step of a tiny bf16-compute config and (b) the
    serving decode chunk, entirely against abstract inputs — no weights are
    materialized — then runs the fp32-master and while-body-collective
    audits. Returns a JSON-able report; raises AssertionError on violation.
    """
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis import budgets
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
    from midgpt_tpu.parallel.mesh import make_mesh
    from midgpt_tpu.utils.hlo import lower_abstract_train_step

    report: tp.Dict[str, tp.Any] = {"backend": jax.default_backend()}

    # All geometry and numeric budgets come from the declarative manifest
    # (analysis/budgets.py) — the same source tests/test_recompile_pins.py
    # asserts the report against, so audit and pins cannot drift.
    g = budgets.AUDIT
    mc = GPTConfig(
        block_size=g.block_size,
        vocab_size=g.vocab_size,
        n_layer=g.n_layer,
        n_head=g.n_head,
        n_embd=g.n_embd,
    )
    cfg = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=len(jax.devices()),
        warmup_steps=1,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.99,
        weight_decay=0.0,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="bfloat16",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        mesh=MeshConfig(data=-1, fsdp=-1),
        model_config=mc,
    )
    mesh = make_mesh(cfg.mesh)
    step_hlo = lower_abstract_train_step(cfg, mesh).compile().as_text()
    report["train_step_fp32_master"] = assert_fp32_master_params(step_hlo)

    # Decode program: the serving engine's fixed-shape decode chunk. Lowered
    # abstractly (eval_shape for params + paged cache); the while body (the
    # lax.scan over decode steps) must stay free of all-gathers — page
    # tables/lengths ride as plain jit inputs, nothing re-shards per step.
    from midgpt_tpu.sampling.serve import _serve_decode_chunk

    params_abs = jax.eval_shape(lambda k: GPT.init(mc, k), jax.random.PRNGKey(0))
    cache_abs = jax.eval_shape(
        lambda: PagedKVCache.init(
            mc, num_pages=g.num_pages, page_size=g.page_size, dtype=jnp.float32
        )
    )
    B, max_pages = g.batch, g.max_pages
    decode_hlo = (
        _serve_decode_chunk.lower(
            mc,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            g.decode_chunk,
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(decode_hlo)
    census = while_body_collectives(decode_hlo)
    report["decode_while_bodies"] = {b: len(ls) for b, ls in census.items()}
    assert census, "decode program lowered without a while loop (scan vanished?)"

    # Zero-in-loop-cache-copy census: the KV pool must alias through the
    # decode loop's carry (the r5/r6 perf pin held by tests/test_sampling.py
    # on bigger shapes), here audited on the same artifact the collective
    # census reads.
    pool_shape = budgets.pool_shape(g)
    copies = while_body_pool_copies(decode_hlo, pool_shape)
    report["decode_loop_pool_copies"] = {b: len(ls) for b, ls in copies.items()}
    assert all(not ls for ls in copies.values()), (
        "pool-sized copies inside the decode while body: "
        + str({b: ls[:1] for b, ls in copies.items() if ls})
    )

    # Speculative verify program (sampling/serve.py _spec_verify_chunk):
    # same two audits. Lowered with decode_layer_scan=True so the layer
    # loop is a while body — the unrolled lowering has no loop at all (its
    # scatters alias the donated pool directly); the rolled scan is where
    # a carry-aliasing regression would surface as in-loop pool copies.
    import dataclasses

    from midgpt_tpu.sampling.serve import _spec_verify_chunk

    mc_scan = dataclasses.replace(mc, decode_layer_scan=True)
    K = g.spec_k
    verify_hlo = (
        _spec_verify_chunk.lower(
            mc_scan,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((K, B), jnp.int32),
            jax.ShapeDtypeStruct((K, B, mc.vocab_size), jnp.float32),
            cache_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(verify_hlo)
    v_census = while_body_collectives(verify_hlo)
    report["verify_while_bodies"] = {b: len(ls) for b, ls in v_census.items()}
    assert v_census, "verify program lowered without its layer-scan while loop"
    v_copies = while_body_pool_copies(verify_hlo, pool_shape)
    report["verify_loop_pool_copies"] = {b: len(ls) for b, ls in v_copies.items()}
    assert all(not ls for ls in v_copies.values()), (
        "pool-sized copies inside the verify layer loop: "
        + str({b: ls[:1] for b, ls in v_copies.items() if ls})
    )

    # Int8 cache mode: the same zero-in-loop-copy property must hold for
    # the quantized pools AND their f32 scale side buffers (a scale-sized
    # copy per decode step would silently rebuild the side buffer every
    # token — small, but a per-token O(pool) cost of exactly the kind the
    # census exists to catch). Audited on all three serving programs:
    # decode, draft (the speculative proposer's scan of paged decode steps,
    # here a 1-layer prefix self-draft against the target pool), verify.
    from midgpt_tpu.sampling.serve import _spec_draft_chunk

    cache8_abs = jax.eval_shape(
        lambda: PagedKVCache.init(
            mc, num_pages=g.num_pages, page_size=g.page_size, dtype=jnp.int8
        )
    )
    pool8_shape = budgets.pool_shape(g, "s8")
    scale_shape = budgets.scale_shape(g)
    decode8_hlo = (
        _serve_decode_chunk.lower(
            mc,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache8_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            g.decode_chunk,
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    draft_cfg = dataclasses.replace(mc, n_layer=g.draft_n_layer)
    draft_abs = jax.eval_shape(
        lambda k: GPT.init(draft_cfg, k), jax.random.PRNGKey(0)
    )
    # prefix self-draft: the draft runs against the TARGET pool's first
    # layer(s), exactly how ServeEngine(draft_shares_cache=True) calls it
    draft8_hlo = (
        _spec_draft_chunk.lower(
            draft_cfg,
            draft_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache8_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            K,
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    verify8_hlo = (
        _spec_verify_chunk.lower(
            mc_scan,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((K, B), jnp.int32),
            jax.ShapeDtypeStruct((K, B, mc.vocab_size), jnp.float32),
            cache8_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    for name, hlo in (
        ("decode_int8", decode8_hlo),
        ("draft_int8", draft8_hlo),
        ("verify_int8", verify8_hlo),
    ):
        assert_no_while_body_collectives(hlo)
        assert while_body_names(hlo), f"{name} program lowered without a loop"
        for label, shape in (("pool", pool8_shape), ("scale", scale_shape)):
            copies = while_body_pool_copies(hlo, shape)
            report[f"{name}_loop_{label}_copies"] = {
                b: len(ls) for b, ls in copies.items()
            }
            assert all(not ls for ls in copies.values()), (
                f"{label}-sized copies inside the {name} loop: "
                + str({b: ls[:1] for b, ls in copies.items() if ls})
            )

    # ------------------------------------------------------------------
    # split-K lowerings: partitioning must add zero pool traffic
    # ------------------------------------------------------------------
    # split_k > 1 partitions the attention softmax statistics over key
    # partitions (kernels/decode_attention.py gather paths; the Pallas
    # template's extra grid dimension on TPU). The audit claim: the split
    # lowering reads the pool through the same single gather as the
    # unsplit pass — it must not copy the pool (or, int8, the scale side
    # buffers) inside the decode loop, and it introduces no collectives
    # (the partial merge is per-slot elementwise math). Censused on the
    # same three serving programs as the unsplit audits, at split_k=4.
    split4_decode_hlo = (
        _serve_decode_chunk.lower(
            mc,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            g.decode_chunk,
            0.0,
            None,
            None,
            "gather",
            None,
            None,
            g.split_k,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(split4_decode_hlo)
    s_census = while_body_collectives(split4_decode_hlo)
    report["split_decode_while_bodies"] = {b: len(ls) for b, ls in s_census.items()}
    assert s_census, "split-K decode lowered without its while loops"
    s_copies = while_body_pool_copies(split4_decode_hlo, pool_shape)
    report["split_decode_loop_pool_copies"] = {
        b: len(ls) for b, ls in s_copies.items()
    }
    assert all(not ls for ls in s_copies.values()), (
        "pool-sized copies inside the split-K decode loops: "
        + str({b: ls[:1] for b, ls in s_copies.items() if ls})
    )

    split4_verify_hlo = (
        _spec_verify_chunk.lower(
            mc_scan,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((K, B), jnp.int32),
            jax.ShapeDtypeStruct((K, B, mc.vocab_size), jnp.float32),
            cache_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            0.0,
            None,
            None,
            "gather",
            None,
            None,
            g.split_k,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(split4_verify_hlo)
    sv_copies = while_body_pool_copies(split4_verify_hlo, pool_shape)
    report["split_verify_loop_pool_copies"] = {
        b: len(ls) for b, ls in sv_copies.items()
    }
    assert all(not ls for ls in sv_copies.values()), (
        "pool-sized copies inside the split-K verify loops: "
        + str({b: ls[:1] for b, ls in sv_copies.items() if ls})
    )

    split4_decode8_hlo = (
        _serve_decode_chunk.lower(
            mc,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache8_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            g.decode_chunk,
            0.0,
            None,
            None,
            "gather",
            None,
            None,
            g.split_k,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(split4_decode8_hlo)
    for label, shape in (("pool", pool8_shape), ("scale", scale_shape)):
        copies = while_body_pool_copies(split4_decode8_hlo, shape)
        report[f"split_decode_int8_loop_{label}_copies"] = {
            b: len(ls) for b, ls in copies.items()
        }
        assert all(not ls for ls in copies.values()), (
            f"{label}-sized copies inside the split-K int8 decode loops: "
            + str({b: ls[:1] for b, ls in copies.items() if ls})
        )

    # ------------------------------------------------------------------
    # fused multi-round group lowerings: k rounds, one pool carry
    # ------------------------------------------------------------------
    # Round-overlap dispatch's group lever (sampling/serve.py
    # _serve_decode_group; docs/SERVING.md "Round-overlap dispatch") wraps
    # round_group decode rounds in one lax.scan, so a single in-loop pool
    # copy would be paid n_steps * round_group times PER DISPATCH — the
    # census that caught the r1-r4 structure (RESULTS.md §1) matters k
    # times more here. Lowered at every budgets.ROUND_GROUPS_AUDITED value
    # (f32) plus int8 at the smallest; the scan body is single-engine work
    # and must carry zero collectives of any kind.
    from midgpt_tpu.sampling.serve import _serve_decode_group

    for rg in budgets.ROUND_GROUPS_AUDITED:
        group_hlo = (
            _serve_decode_group.lower(
                mc,
                params_abs,
                jax.ShapeDtypeStruct((B,), jnp.int32),
                cache_abs,
                jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.bool_),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.bool_),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                g.decode_chunk,
                rg,
                0.0,
                None,
                None,
                "gather",
                None,
            )
            .compile()
            .as_text()
        )
        assert_no_while_body_collectives(group_hlo, ops=COLLECTIVE_OPS)
        g_census = while_body_collectives(group_hlo)
        report[f"group{rg}_decode_while_bodies"] = {
            b: len(ls) for b, ls in g_census.items()
        }
        assert g_census, f"group:{rg} decode lowered without its scan loop"
        g_copies = while_body_pool_copies(group_hlo, pool_shape)
        report[f"group{rg}_decode_loop_pool_copies"] = {
            b: len(ls) for b, ls in g_copies.items()
        }
        assert all(not ls for ls in g_copies.values()), (
            f"pool-sized copies inside the group:{rg} decode scan body: "
            + str({b: ls[:1] for b, ls in g_copies.items() if ls})
        )

    rg0 = budgets.ROUND_GROUPS_AUDITED[0]
    group8_hlo = (
        _serve_decode_group.lower(
            mc,
            params_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache8_abs,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            g.decode_chunk,
            rg0,
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    assert_no_while_body_collectives(group8_hlo, ops=COLLECTIVE_OPS)
    for label, shape in (("pool", pool8_shape), ("scale", scale_shape)):
        copies = while_body_pool_copies(group8_hlo, shape)
        report[f"group{rg0}_decode_int8_loop_{label}_copies"] = {
            b: len(ls) for b, ls in copies.items()
        }
        assert all(not ls for ls in copies.values()), (
            f"{label}-sized copies inside the group:{rg0} int8 scan body: "
            + str({b: ls[:1] for b, ls in copies.items() if ls})
        )

    # ------------------------------------------------------------------
    # attention-variant lowerings: GQA/MQA pools, sliding-window masking
    # ------------------------------------------------------------------
    # GQA shrinks the pool's head axis to the KV-head count — a geometry
    # change, which is exactly the kind of edit that silently breaks the
    # donation/aliasing match the decode loop depends on — so the variant
    # lowerings must hold the same zero-in-loop-copy and collective-free
    # pins as MHA, with the census grepping the KV-head pool shape.
    # Window+sinks masking is select math on scores: it must add zero pool
    # traffic. Audited at AUDIT_GQA (MQA, the extreme grouping) and
    # AUDIT_GQA_WINDOW (same pools + window masking), f32 and int8.
    gv = budgets.AUDIT_GQA
    mc_gqa = GPTConfig(
        block_size=gv.block_size,
        vocab_size=gv.vocab_size,
        n_layer=gv.n_layer,
        n_head=gv.n_head,
        n_embd=gv.n_embd,
        n_kv_heads=gv.n_kv_heads,
    )
    gw = budgets.AUDIT_GQA_WINDOW
    mc_gqa_win = dataclasses.replace(
        mc_gqa, sliding_window=gw.sliding_window, attn_sinks=gw.attn_sinks
    )
    params_gqa_abs = jax.eval_shape(
        lambda k: GPT.init(mc_gqa, k), jax.random.PRNGKey(0)
    )
    cache_gqa_abs = jax.eval_shape(
        lambda: PagedKVCache.init(
            mc_gqa, num_pages=gv.num_pages, page_size=gv.page_size,
            dtype=jnp.float32,
        )
    )
    cache_gqa8_abs = jax.eval_shape(
        lambda: PagedKVCache.init(
            mc_gqa, num_pages=gv.num_pages, page_size=gv.page_size,
            dtype=jnp.int8,
        )
    )

    def _variant_decode_lower(cfg, cache):
        return _serve_decode_chunk.lower(
            cfg,
            params_gqa_abs,
            jax.ShapeDtypeStruct((B,), jnp.int32),
            cache,
            jax.ShapeDtypeStruct((B, max_pages), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            g.decode_chunk,
            0.0,
            None,
            None,
            "gather",
            None,
        ).compile().as_text()

    gqa_hlo = _variant_decode_lower(mc_gqa, cache_gqa_abs)
    gqa_win_hlo = _variant_decode_lower(mc_gqa_win, cache_gqa_abs)
    gqa8_hlo = _variant_decode_lower(mc_gqa, cache_gqa8_abs)
    gqa_pool = budgets.pool_shape(gv)
    for name, hlo in (("gqa", gqa_hlo), ("gqa_window", gqa_win_hlo)):
        assert_no_while_body_collectives(hlo, ops=COLLECTIVE_OPS)
        v_census = while_body_collectives(hlo)
        report[f"{name}_decode_while_bodies"] = {
            b: len(ls) for b, ls in v_census.items()
        }
        assert v_census, f"{name} decode lowered without its scan loop"
        copies = while_body_pool_copies(hlo, gqa_pool)
        report[f"{name}_decode_loop_pool_copies"] = {
            b: len(ls) for b, ls in copies.items()
        }
        assert all(not ls for ls in copies.values()), (
            f"KV-head pool copies inside the {name} decode loop: "
            + str({b: ls[:1] for b, ls in copies.items() if ls})
        )
    assert_no_while_body_collectives(gqa8_hlo, ops=COLLECTIVE_OPS)
    for label, shape in (
        ("pool", budgets.pool_shape(gv, "s8")),
        ("scale", budgets.scale_shape(gv)),
    ):
        copies = while_body_pool_copies(gqa8_hlo, shape)
        report[f"gqa_decode_int8_loop_{label}_copies"] = {
            b: len(ls) for b, ls in copies.items()
        }
        assert all(not ls for ls in copies.values()), (
            f"{label}-sized copies inside the int8 GQA decode loop: "
            + str({b: ls[:1] for b, ls in copies.items() if ls})
        )

    # ------------------------------------------------------------------
    # tp serving mesh: per-program in-loop collective census
    # ------------------------------------------------------------------
    # The mesh-sharded engine's perf claim (docs/SERVING.md "Mesh-sharded
    # serving") is that tp decode pays ONLY the megatron activation
    # collectives — two all-reduces per layer per step, nothing else, and
    # in particular zero pool/scale traffic: the pools shard heads over
    # 'tp' and never cross shards. Audited on abstractly-lowered SHARDED
    # programs (ShapeDtypeStruct + NamedSharding; the partitioned modules
    # show per-shard pool shapes, which is what the copy census greps).
    # Budget per while body: 2 * n_layer all-reduces for the step-scan
    # programs (layers unrolled inside the body), 2 for the layer-scan
    # verify body (the body IS one layer), zero all-gather / all-to-all /
    # reduce-scatter / collective-permute anywhere in any loop.
    if len(jax.devices()) >= 2:
        from jax.sharding import NamedSharding

        from midgpt_tpu.parallel.serve_tp import (
            make_serve_mesh,
            serve_cache_specs,
            serve_param_specs,
        )

        smesh = make_serve_mesh(tp_size=g.tp)
        report["tp_mesh"] = budgets.tp_mesh_shape(g)
        # head-aligned qkv shards need the split3 einsum order — the same
        # config switch ServeEngine(mesh=...) makes (training/train.py)
        mc3 = dataclasses.replace(mc, qkv_proj="split3")
        mc3_scan = dataclasses.replace(mc_scan, qkv_proj="split3")
        draft3_cfg = dataclasses.replace(draft_cfg, qkv_proj="split3")

        def _shard_abs(tree, specs):
            return jax.tree.map(
                lambda l, s: jax.ShapeDtypeStruct(
                    l.shape, l.dtype, sharding=NamedSharding(smesh, s)
                ),
                tree,
                specs,
            )

        params_tp = _shard_abs(params_abs, serve_param_specs(params_abs, smesh))
        draft_tp = _shard_abs(draft_abs, serve_param_specs(draft_abs, smesh))
        cache_tp = _shard_abs(cache_abs, serve_cache_specs(cache_abs))
        cache8_tp = _shard_abs(cache8_abs, serve_cache_specs(cache8_abs))
        sds = jax.ShapeDtypeStruct
        i32, b1 = jnp.int32, jnp.bool_

        def _decode_lower(cfg, cache, split_k=1):
            return _serve_decode_chunk.lower(
                cfg, params_tp, sds((B,), i32), cache,
                sds((B, max_pages), i32), sds((B,), i32), sds((B,), b1),
                g.decode_chunk, 0.0, None, None, "gather", None, smesh,
                split_k,
            ).compile().as_text()

        # One lowering per budgets.TP_PROGRAMS entry; the per-program
        # all-reduce budget comes from the manifest, not from literals here.
        tp_lowered = {
            "tp_decode": _decode_lower(mc3, cache_tp),
            "tp_decode_int8": _decode_lower(mc3, cache8_tp),
            # split-K under tp: the partition scan rides INSIDE each head
            # shard — the all-reduce budget must not move by a single op
            "tp_decode_split": _decode_lower(mc3, cache_tp, split_k=g.split_k),
            "tp_verify": _spec_verify_chunk.lower(
                mc3_scan, params_tp, sds((B,), i32), sds((K, B), i32),
                sds((K, B, mc.vocab_size), jnp.float32), cache_tp,
                sds((B, max_pages), i32), sds((B,), i32), sds((B,), b1),
                0.0, None, None, "gather", None, smesh,
            ).compile().as_text(),
            "tp_draft_int8": _spec_draft_chunk.lower(
                draft3_cfg, draft_tp, sds((B,), i32), cache8_tp,
                sds((B, max_pages), i32), sds((B,), i32), sds((B,), b1),
                K, 0.0, None, None, "gather", None, smesh,
            ).compile().as_text(),
        }
        assert set(tp_lowered) == set(budgets.TP_PROGRAMS)
        # per-SHARD pool shapes: H/tp heads per shard (head axis 1 of the
        # pools, axis 2 of the scale side buffers)
        shard_shapes = budgets.shard_pool_shapes(g)
        other_ops = tuple(o for o in COLLECTIVE_OPS if o != "all-reduce")
        for name in budgets.TP_PROGRAMS:
            hlo = tp_lowered[name]
            budget = budgets.tp_loop_all_reduce_budget(name, g)
            assert_no_while_body_collectives(hlo, ops=other_ops)
            ar = while_body_collectives(hlo, ops=("all-reduce",))
            n_ar = sum(len(ls) for ls in ar.values())
            report[f"{name}_loop_all_reduces"] = n_ar
            assert n_ar == budget, (
                f"{name}: {n_ar} in-loop all-reduces, budget {budget} "
                "(two megatron activation collectives per layer per step)"
            )
            for shape in shard_shapes:
                copies = while_body_pool_copies(hlo, shape)
                n_cp = sum(len(ls) for ls in copies.values())
                assert n_cp == budgets.LOOP_POOL_COPY_BUDGET, (
                    f"{name}: {n_cp} in-loop {shape} pool/scale copies — "
                    "the sharded pool must alias through the loop carry"
                )
            report[f"{name}_loop_pool_copies"] = budgets.LOOP_POOL_COPY_BUDGET

        # GQA under tp (AUDIT_GQA_TP: 4 query heads, 2 KV heads, tp=2 —
        # one KV head, i.e. one whole query GROUP, per shard). The claim
        # docs/SERVING.md "Attention variants" makes: grouping shrinks the
        # per-shard pool BYTES by the group factor while the in-loop
        # all-reduce count stays exactly the megatron budget — the same
        # 2 * n_layer the MHA tp_decode program pays, not one op more.
        gtp = budgets.AUDIT_GQA_TP
        mc_gtp = GPTConfig(
            block_size=gtp.block_size,
            vocab_size=gtp.vocab_size,
            n_layer=gtp.n_layer,
            n_head=gtp.n_head,
            n_embd=gtp.n_embd,
            n_kv_heads=gtp.n_kv_heads,
            qkv_proj="split3",
        )
        params_gtp_abs = jax.eval_shape(
            lambda k: GPT.init(mc_gtp, k), jax.random.PRNGKey(0)
        )
        cache_gtp_abs = jax.eval_shape(
            lambda: PagedKVCache.init(
                mc_gtp, num_pages=gtp.num_pages, page_size=gtp.page_size,
                dtype=jnp.float32,
            )
        )
        params_gtp = _shard_abs(
            params_gtp_abs, serve_param_specs(params_gtp_abs, smesh)
        )
        cache_gtp = _shard_abs(cache_gtp_abs, serve_cache_specs(cache_gtp_abs))
        gqa_tp_hlo = _serve_decode_chunk.lower(
            mc_gtp, params_gtp, sds((B,), i32), cache_gtp,
            sds((B, max_pages), i32), sds((B,), i32), sds((B,), b1),
            g.decode_chunk, 0.0, None, None, "gather", None, smesh, 1,
        ).compile().as_text()
        assert_no_while_body_collectives(gqa_tp_hlo, ops=other_ops)
        ar = while_body_collectives(gqa_tp_hlo, ops=("all-reduce",))
        n_ar = sum(len(ls) for ls in ar.values())
        report["tp_decode_gqa_loop_all_reduces"] = n_ar
        gqa_budget = budgets.tp_loop_all_reduce_budget("tp_decode_gqa", gtp)
        assert n_ar == gqa_budget, (
            f"tp_decode_gqa: {n_ar} in-loop all-reduces, budget {gqa_budget} "
            "— GQA must not change the megatron activation collective count"
        )
        gqa_shard_pool = budgets.pool_shape(gtp, "f32", gtp.tp)
        copies = while_body_pool_copies(gqa_tp_hlo, gqa_shard_pool)
        n_cp = sum(len(ls) for ls in copies.values())
        assert n_cp == budgets.LOOP_POOL_COPY_BUDGET, (
            f"tp_decode_gqa: {n_cp} in-loop {gqa_shard_pool} pool copies — "
            "the KV-head-sharded pool must alias through the loop carry"
        )
        report["tp_decode_gqa_loop_pool_copies"] = budgets.LOOP_POOL_COPY_BUDGET
    return report
