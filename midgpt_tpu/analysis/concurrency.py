"""graftcheck pass 4: thread/process-boundary concurrency rules. JAX-free.

ROADMAP items 4-5 promote today's in-process seams (FleetRouter<->replicas,
handoff/spill queues, `jax.distributed` training) to real thread and process
boundaries. Pass 3's GC010 guards the async front door; these rules make the
remaining boundary disciplines lexical *before* the process split, so a
violation fails CI with a file:line instead of surfacing as a rare
interleaving (rationale and citations: docs/ANALYSIS.md pass-4 section):

  GC013  thread confinement: engine/pool/trie/scheduler state may only be
         mutated from the driver loop. Any function reachable from a
         non-driver execution context — a `threading.Thread`/`Timer`
         target, an `asyncio.to_thread`/`run_in_executor` callee other
         than the blessed bound-`step` funnel or a queued-command def
         nested in the awaiting coroutine (GC010's clean idiom), or an
         `on_expire=` watchdog callback — must not store to (or call
         mutating methods on) engine-owned state; workers hand results
         back through queues/events the driver drains.
  GC014  signal-handler safety: a handler registered via `signal.signal`
         runs at an arbitrary bytecode boundary on the main thread. It may
         only set pre-existing flags: no checkpoint/collective calls, no
         engine/pool calls, no prints/logging/IO, no lock acquisition or
         primitive construction, no comprehension allocation. The one-shot
         re-arm (`signal.signal(signum, previous)` inside the handler) is
         the blessed exception (robustness/preempt.py).
  GC015  wire contract: values placed into `PageHandoffQueue` / SpillTier /
         FleetRouter failover structures must be plain data by
         construction — host numpy pages under the quantized-page+scales
         keys {k, v, k_scale, v_scale}, ints/floats/strings for the rest.
         No device arrays (a bare jnp/jax call landing in a field), no
         closures/lambdas, no locks, no clock callables: every one of
         those dies (or silently diverges) at pickle time once the queue
         becomes a socket (ROADMAP item 4).
  GC016  structured-error contract: every `raise` of a registered
         structured error (analysis/error_contracts.py) must pass each
         field its class declares required, and only declared fields —
         a forgotten field fails in the *handler* (supervisor rollback,
         serving retry math) far from the raise site.

Scope model mirrors pass 1: execution contexts are resolved transitively by
bare name within the module (`_Module._closure`); cross-module workers are
out of lexical reach and documented as a scope limit. Suppression uses the
shared `# graftcheck: disable=GCnnn — justification` machinery.
"""

from __future__ import annotations

import ast
import typing as tp

from .error_contracts import ERROR_CONTRACTS
from .lint import (
    Finding,
    _FuncDef,
    _GC007_LEAVES,
    _Module,
    _call_name,
    _dotted,
    _unwrap_callable,
    iter_python_files,
    parse_suppressions,
)

CONCURRENCY_RULES: tp.Dict[str, str] = {
    "GC013": "engine-owned state mutated off the driver execution context",
    "GC014": "signal handler does more than set a pre-existing flag",
    "GC015": "non-plain-data value placed into a wire handoff structure",
    "GC016": "structured error raised without its declared fields",
}

# Attribute-chain parts that mark driver-owned serving/training state. A
# dotted chain like `self.engine.temperature` or `router.pool.pages` is
# engine-owned iff one of these appears as an exact chain part (substring
# matches would catch `engineering`).
_CONFINED_PARTS = frozenset(
    {
        "engine",
        "engines",
        "pool",
        "trie",
        "prefix_cache",
        "scheduler",
        "allocator",
    }
)

# The one blessed method on a confined receiver: the driver's own
# `await asyncio.to_thread(self.engine.step)` funnel (sampling/server.py).
_BLESSED_LEAF = "step"

_WorkerScopes = tp.Dict[_FuncDef, str]  # def -> human-readable context


def _confined_part(chain: tp.Optional[str]) -> tp.Optional[str]:
    """The engine-owned chain part of a dotted name, if any."""
    if not chain:
        return None
    for part in chain.split("."):
        if part in _CONFINED_PARTS:
            return part
    return None


# ----------------------------------------------------------------------
# GC013 — thread confinement
# ----------------------------------------------------------------------


def _worker_roots(
    mod: _Module,
) -> tp.Iterator[tp.Tuple[ast.AST, str, ast.Call]]:
    """(callable expr, context label, spawning call) per off-driver entry."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        leaf = name.split(".")[-1]
        if leaf == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    yield kw.value, "threading.Thread target", node
        elif leaf == "Timer":
            fn_expr: tp.Optional[ast.AST] = (
                node.args[1] if len(node.args) > 1 else None
            )
            for kw in node.keywords:
                if kw.arg == "function":
                    fn_expr = kw.value
            if fn_expr is not None:
                yield fn_expr, "threading.Timer callback", node
        elif leaf == "to_thread" and node.args:
            callee = node.args[0]
            dotted = _dotted(callee)
            # The blessed funnel: to_thread(self.engine.step) runs ONE
            # bound method whose receiver the driver owns; anything else
            # shipped to the thread pool is a worker context.
            if dotted and dotted.split(".")[-1] == _BLESSED_LEAF:
                continue
            yield callee, "asyncio.to_thread callee", node
        elif leaf == "run_in_executor" and len(node.args) > 1:
            dotted = _dotted(node.args[1])
            if dotted and dotted.split(".")[-1] == _BLESSED_LEAF:
                continue
            yield node.args[1], "run_in_executor callee", node
        # watchdog-style expiry callbacks, by keyword convention
        for kw in node.keywords:
            if kw.arg == "on_expire":
                yield kw.value, "on_expire callback", node

# Awaited-executor contexts where a lexically NESTED callee is the blessed
# queued-command shape (pass 3's GC010 clean idiom): the awaiting coroutine
# serializes the nested def, so it runs as the driver's own command, not a
# free-running worker. Threads/timers/expiry callbacks stay workers even
# when nested — they genuinely run concurrently with their definer.
_AWAITED_CTXS = ("asyncio.to_thread callee", "run_in_executor callee")


def _worker_scopes(mod: _Module) -> tp.Tuple[_WorkerScopes, tp.List[tp.Tuple[ast.Lambda, str]]]:
    """Worker defs (transitively closed) plus inline lambda workers."""
    scopes: _WorkerScopes = {}
    lambdas: tp.List[tp.Tuple[ast.Lambda, str]] = []
    for expr, ctx, spawn in _worker_roots(mod):
        if isinstance(expr, ast.Lambda):
            lambdas.append((expr, ctx))
            continue
        roots = set(mod.resolve_defs(_unwrap_callable(expr)))
        if ctx in _AWAITED_CTXS:
            spawner = mod.enclosing_function(spawn)
            roots = {
                d for d in roots if mod.enclosing_function(d) is not spawner
            }
        for d in mod._closure(roots):
            scopes.setdefault(d, ctx)
    return scopes, lambdas


def _gc013_violations(
    mod: _Module, body: ast.AST, where: str, ctx: str
) -> tp.Iterator[Finding]:
    for node in ast.walk(body):
        # (a) stores / deletes / augmented assigns on engine-owned chains
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), (ast.Store, ast.Del)
        ):
            chain = _dotted(node)
            part = _confined_part(chain)
            # a bare Name store (`pool = ...`) is a local rebind, not a
            # mutation of shared state — only dotted chains count
            if part and chain and "." in chain:
                yield Finding(
                    "GC013",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`{chain}` is mutated inside {where} ({ctx}) — "
                    f"`{part}`-owned state is confined to the driver loop; "
                    "hand results back via a queue/event the driver drains "
                    "(docs/ANALYSIS.md pass 4)",
                )
        # (b) mutating method calls on engine-owned receivers
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            chain = _dotted(node.func)
            if not chain:
                continue
            receiver = ".".join(chain.split(".")[:-1])
            leaf = chain.split(".")[-1]
            if _confined_part(receiver) and leaf != _BLESSED_LEAF:
                yield Finding(
                    "GC013",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`{chain}()` is called inside {where} ({ctx}) — "
                    "engine-owned objects may only be driven from the "
                    "driver loop; enqueue a command instead",
                )


def _rule_gc013(mod: _Module) -> tp.Iterator[Finding]:
    scopes, lambdas = _worker_scopes(mod)
    for d, ctx in scopes.items():
        yield from _gc013_violations(mod, d, f"worker `{d.name}`", ctx)
    for lam, ctx in lambdas:
        yield from _gc013_violations(mod, lam.body, "a worker lambda", ctx)


# ----------------------------------------------------------------------
# GC014 — signal-handler safety
# ----------------------------------------------------------------------

# Synchronization-primitive constructors a handler must never build (the
# allocation itself can deadlock under a held GIL-adjacent lock, and a
# fresh primitive in a handler is a design smell regardless).
_SYNC_CTORS = frozenset(
    {
        "Lock",
        "RLock",
        "Condition",
        "Semaphore",
        "BoundedSemaphore",
        "Event",
        "Barrier",
        "Queue",
        "SimpleQueue",
    }
)

_IO_CALLS = frozenset({"print", "open", "input"})
_LOG_LEAVES = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)


def _is_signal_signal(call: ast.Call) -> bool:
    name = _call_name(call) or ""
    parts = name.split(".")
    return parts[-1] == "signal" and (len(parts) == 1 or parts[-2] == "signal")


def _handler_defs(mod: _Module) -> tp.Set[_FuncDef]:
    roots: tp.Set[_FuncDef] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and _is_signal_signal(node)
            and len(node.args) > 1
        ):
            roots.update(mod.resolve_defs(_unwrap_callable(node.args[1])))
    return mod._closure(roots)


def _gc014_call_problem(node: ast.Call) -> tp.Optional[str]:
    name = _call_name(node) or ""
    parts = name.split(".")
    leaf = parts[-1]
    if name in _IO_CALLS:
        return f"`{name}()` performs IO"
    if leaf in _GC007_LEAVES and len(parts) > 1:
        return f"`{name}()` is a checkpoint/collective call"
    if _confined_part(".".join(parts[:-1])):
        return f"`{name}()` drives engine-owned state"
    if parts[0] == "logging" or (
        len(parts) > 1 and parts[0] in ("logger", "log") and leaf in _LOG_LEAVES
    ):
        return f"`{name}()` allocates/locks inside the logging machinery"
    if leaf == "acquire":
        return f"`{name}()` acquires a lock (deadlocks if the interrupted frame holds it)"
    if leaf in _SYNC_CTORS:
        return f"`{name}()` constructs a synchronization primitive"
    return None


def _rule_gc014(mod: _Module) -> tp.Iterator[Finding]:
    for handler in _handler_defs(mod):
        for node in ast.walk(handler):
            if isinstance(node, ast.Call):
                # blessed one-shot re-arm: signal.signal(signum, previous)
                # inside the handler restores the prior disposition
                if _is_signal_signal(node):
                    continue
                problem = _gc014_call_problem(node)
                if problem:
                    yield Finding(
                        "GC014",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"signal handler `{handler.name}`: {problem} — "
                        "handlers run at an arbitrary bytecode boundary and "
                        "may only set pre-existing flags "
                        "(robustness/preempt.py is the pattern)",
                    )
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                yield Finding(
                    "GC014",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"signal handler `{handler.name}` allocates a "
                    "comprehension — handlers may only set pre-existing "
                    "flags",
                )


# ----------------------------------------------------------------------
# GC015 — wire contract for handoff/spill/failover payloads
# ----------------------------------------------------------------------

# Queue/tier/transport classes whose contents cross a process boundary
# (literally so since sampling/fleet_proc.py: ReplicaTransport frames them
# onto a socket), and the item classes that ride them.
_WIRE_QUEUE_CTORS = frozenset(
    {"PageHandoffQueue", "SpillTier", "ReplicaTransport"}
)
_WIRE_ITEM_CTORS = frozenset(
    {"HandoffItem", "FailoverItem", "_SpillEntry", "SpillTransferItem"}
)
_WIRE_CHAIN_HINTS = ("handoff", "failover", "spill", "transport")

# The quantized-page wire shape: int8 pages + their dequant scales, nothing
# else (sampling/disagg.py `_gather_pages` is the blessed producer).
_BLESSED_BLOCK_KEYS = frozenset({"k", "v", "k_scale", "v_scale"})

# Host-landing calls that terminate the device-array scan: the value is
# host numpy by construction past this point.
_HOST_LANDING = frozenset({"asarray", "array"})
_NP_ROOTS = frozenset({"np", "numpy"})
_DEVICE_ROOTS = frozenset({"jnp", "jax"})


def _wire_queue_chains(mod: _Module) -> tp.Set[str]:
    """Dotted chains assigned from a wire-queue constructor (self.queue...)."""
    chains: tp.Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        name = _call_name(node.value) or ""
        if name.split(".")[-1] not in _WIRE_QUEUE_CTORS:
            continue
        for t in node.targets:
            chain = _dotted(t)
            if chain:
                chains.add(chain)
    return chains


def _is_wire_push(node: ast.Call, queue_chains: tp.Set[str]) -> bool:
    if not (isinstance(node.func, ast.Attribute) and node.func.attr == "push"):
        return False
    receiver = _dotted(node.func.value)
    if receiver is None:
        return False
    if receiver in queue_chains:
        return True
    low = receiver.lower()
    return any(h in low for h in _WIRE_CHAIN_HINTS)


def _field_problems(expr: ast.AST) -> tp.Iterator[tp.Tuple[ast.AST, str]]:
    """Scan one wire-item field value for non-plain-data content."""

    def visit(node: ast.AST) -> tp.Iterator[tp.Tuple[ast.AST, str]]:
        if isinstance(node, ast.Lambda):
            yield node, "a lambda/closure cannot cross the wire"
            return
        if isinstance(node, ast.Call):
            name = _call_name(node) or ""
            parts = name.split(".")
            if parts[0] in _NP_ROOTS and parts[-1] in _HOST_LANDING:
                return  # host-landed by construction; stop descending
            if parts[0] in _DEVICE_ROOTS:
                yield (
                    node,
                    f"`{name}(...)` is a device array — land it on host "
                    "first (`np.asarray(jnp.take(...))`, the "
                    "`_gather_pages` idiom)",
                )
                return
            # a call RESULT is data; scan only its inputs (so a clock
            # *read* like `self._clock()` passes while a clock *reference*
            # in a field fails below)
            for a in node.args:
                yield from visit(a)
            for kw in node.keywords:
                yield from visit(kw.value)
            return
        if isinstance(node, (ast.Name, ast.Attribute)):
            chain = _dotted(node)
            if chain:
                leaf = chain.split(".")[-1].lower()
                # word-boundary match so `blocks`/`block_size` never trip it
                if (
                    leaf in ("lock", "_lock", "rlock", "_rlock", "mutex")
                    or "_lock" in leaf
                    or leaf.startswith("lock_")
                ):
                    yield node, f"`{chain}` looks like a lock"
                    return
                if leaf in ("clock", "_clock"):
                    yield (
                        node,
                        f"`{chain}` is a clock callable — stamp a float "
                        "(`self._clock()`) instead",
                    )
                    return
            return
        for child in ast.iter_child_nodes(node):
            yield from visit(child)

    yield from visit(expr)


def _bad_block_keys(expr: ast.AST) -> tp.Iterator[tp.Tuple[ast.AST, str]]:
    """Non-blessed string keys in a dict literal bound to `blocks=`."""
    if isinstance(expr, ast.Dict):
        for k in expr.keys:
            if (
                isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and k.value not in _BLESSED_BLOCK_KEYS
            ):
                yield k, k.value


def _check_item_call(mod: _Module, call: ast.Call) -> tp.Iterator[Finding]:
    for kw in call.keywords:
        if kw.arg == "blocks":
            for node, key in _bad_block_keys(kw.value):
                yield Finding(
                    "GC015",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"block key `{key}` is outside the quantized-page wire "
                    "shape {k, v, k_scale, v_scale} — the dequant consumer "
                    "on the far side will not recognize it",
                )
        for node, why in _field_problems(kw.value):
            yield Finding(
                "GC015",
                mod.path,
                node.lineno,
                node.col_offset,
                f"wire-item field `{kw.arg or '**'}`: {why}",
            )


def _producer_defs(mod: _Module, queue_chains: tp.Set[str]) -> tp.Set[_FuncDef]:
    """Functions that construct wire items or push to wire queues."""
    out: tp.Set[_FuncDef] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        if name.split(".")[-1] in _WIRE_ITEM_CTORS or _is_wire_push(
            node, queue_chains
        ):
            fn = mod.enclosing_function(node)
            if fn is not None:
                out.add(fn)
    return out


def _rule_gc015(mod: _Module) -> tp.Iterator[Finding]:
    queue_chains = _wire_queue_chains(mod)
    checked: tp.Set[ast.Call] = set()

    # 1) every wire-item constructor call, wherever it appears
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = _call_name(node) or ""
            if name.split(".")[-1] in _WIRE_ITEM_CTORS:
                checked.add(node)
                yield from _check_item_call(mod, node)

    # 2) direct `queue.push(<expr>)` arguments: a constructor call gets the
    #    field check; a Name is traced one hop to its producing assignment
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_wire_push(node, queue_chains)):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Call) and arg not in checked:
                checked.add(arg)
                yield from _check_item_call(mod, arg)
            elif isinstance(arg, (ast.Lambda,)):
                yield Finding(
                    "GC015",
                    mod.path,
                    arg.lineno,
                    arg.col_offset,
                    "a lambda pushed into a wire queue cannot cross the wire",
                )

    # 3) inside producer functions, `blocks[...] = value` stores must use
    #    blessed keys and host-landed values
    for fn in _producer_defs(mod, queue_chains):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "blocks"
                ):
                    continue
                key = t.slice
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value not in _BLESSED_BLOCK_KEYS
                ):
                    yield Finding(
                        "GC015",
                        mod.path,
                        t.lineno,
                        t.col_offset,
                        f"block key `{key.value}` is outside the "
                        "quantized-page wire shape {k, v, k_scale, v_scale}",
                    )
                for sub, why in _field_problems(node.value):
                    yield Finding(
                        "GC015",
                        mod.path,
                        sub.lineno,
                        sub.col_offset,
                        f"wire block store: {why}",
                    )


# ----------------------------------------------------------------------
# GC016 — structured-error raise contract
# ----------------------------------------------------------------------


def _rule_gc016(mod: _Module) -> tp.Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Raise) or not isinstance(node.exc, ast.Call):
            continue
        call = node.exc
        name = _call_name(call) or ""
        leaf = name.split(".")[-1]
        contract = ERROR_CONTRACTS.get(leaf)
        if contract is None:
            continue
        if any(kw.arg is None for kw in call.keywords):
            continue  # **splat: not statically checkable
        passed = {kw.arg for kw in call.keywords}
        missing = [f for f in contract.required if f not in passed]
        declared = set(contract.required) | set(contract.optional)
        undeclared = sorted(passed - declared)
        if len(call.args) > 1:
            yield Finding(
                "GC016",
                mod.path,
                call.lineno,
                call.col_offset,
                f"`{leaf}` takes its structured fields keyword-only — "
                "positional args beyond the message will TypeError at "
                "raise time",
            )
        if missing:
            yield Finding(
                "GC016",
                mod.path,
                call.lineno,
                call.col_offset,
                f"`raise {leaf}` is missing required field(s) "
                f"{missing} declared in analysis/error_contracts.py — "
                "the handler that unpacks this error will read garbage",
            )
        if undeclared:
            yield Finding(
                "GC016",
                mod.path,
                call.lineno,
                call.col_offset,
                f"`raise {leaf}` passes undeclared field(s) {undeclared} — "
                "not in the class contract (typo, or update "
                "analysis/error_contracts.py with the class)",
            )


_ALL_RULES = (_rule_gc013, _rule_gc014, _rule_gc015, _rule_gc016)


# ----------------------------------------------------------------------
# driver — mirrors lint_source / lint_paths
# ----------------------------------------------------------------------


def concurrency_source(
    source: str,
    path: str = "<string>",
    rules: tp.Optional[tp.Iterable[str]] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding]]:
    """Run pass 4 on one module's source. Returns (active, suppressed).

    Syntax errors yield nothing — pass 1 already reports GC000 for the
    same file."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return [], []
    mod = _Module(path, source, tree)
    wanted = set(rules) if rules is not None else set(CONCURRENCY_RULES)
    suppress_at: tp.Dict[int, tp.Set[str]] = {}
    for s in parse_suppressions(source):
        suppress_at.setdefault(s.line, set()).update(s.rules)
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    for rule_fn in _ALL_RULES:
        for f in rule_fn(mod):
            if f.rule not in wanted:
                continue
            if f.rule in suppress_at.get(f.line, ()):
                suppressed.append(f)
            else:
                active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return active, suppressed


def concurrency_paths(
    paths: tp.Sequence[str],
    rules: tp.Optional[tp.Iterable[str]] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding], int]:
    """Run pass 4 over files/trees. Returns (active, suppressed, n_files)."""
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    n = 0
    for path in iter_python_files(paths):
        n += 1
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        a, s = concurrency_source(src, path, rules)
        active.extend(a)
        suppressed.extend(s)
    return active, suppressed, n
