"""graftcheck pass 1: repo-specific AST lint. Deliberately JAX-free.

Every rule encodes a gotcha this repo has already paid for (rationale and
the CLAUDE.md / RESULTS.md citations live in docs/ANALYSIS.md):

  GC001  lax.cond / lax.while_loop / lax.fori_loop inside a Pallas kernel
         body (kills Mosaic pipelining — use straight-line selects).
  GC002  host materialization of traced values inside jit/scan/kernel
         scopes: float()/int() on non-constants, .item(), np.asarray/array.
  GC003  BlockSpec literal shapes whose last two dims are neither
         (8, 128)-divisible nor a plausible full-dim singleton.
  GC004  reading a donated argument after the donating call site.
  GC005  time.time()-style wall clock or np.random reachable from traced
         scopes (baked in at trace time — silently constant).
  GC006  function docstrings claiming parity without a `reference file:line`
         citation or a pinning-test citation (tests/...py).
  GC007  bare/broad `except` that swallows failures of checkpoint or
         collective call sites (a silently-dropped save/restore/collective
         is how runs lose state or deadlock half a mesh — robustness PR).
  GC008  bare `.astype(int8)` with no rounding in sight: the cast TRUNCATES
         toward zero, so float values quantized that way lose up to a full
         step of precision and bias toward 0 — quantization must round
         (ops/quant.py quantize_q8 is the blessed path; int8 KV cache PR).
  GC012  bare wall-clock CALL (`time.time()` / `time.perf_counter()` /
         `time.monotonic()` ...) in a `sampling/` or `robustness/` module:
         those hot paths measure latency through the injectable clock
         (`clock=` ctor param threaded to `self._clock`), which is what
         keeps round decomposition tunnel-consistent and lets tests fake
         time. Default-arg REFERENCES (`clock=time.perf_counter`) are the
         plumbing itself, not a read — only Call nodes are flagged, and
         `time.sleep()` is not a clock read (observability PR).

Scope model: a function is *traced* if it is jit-decorated (including
`functools.partial(jax.jit, ...)` and `name = jax.jit(fn)` rebinding), a
Pallas kernel (passed — possibly via functools.partial — to pallas_call),
or a named lax.scan body; plus, transitively, any same-module function it
calls by bare name. Lexically nested defs are analyzed as part of the
enclosing scope's subtree. Cross-module calls are not resolved — this is a
lint, not an interpreter; it trades soundness for zero false-positive noise
on idiomatic code.

Suppression: `# graftcheck: disable=GC001[,GC002] — one-line justification`
on the flagged line. The justification text is kept so the lint-clean gate
(tests/test_lint_clean.py) can reject bare, unexplained suppressions.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
import typing as tp

RULES: tp.Dict[str, str] = {
    "GC001": "lax control flow inside a Pallas kernel body",
    "GC002": "host materialization of a traced value inside a traced scope",
    "GC003": "BlockSpec literal block shape violates the (8, 128) tiling rule",
    "GC004": "donated argument read after the donating call site",
    "GC005": "wall clock / numpy RNG reachable from a traced scope",
    "GC006": "parity claim without a reference or pinning-test citation",
    "GC007": "swallowed exception around a checkpoint/collective call site",
    "GC008": "truncating .astype(int8) cast — quantization must round",
    "GC012": "bare wall-clock call in a serving/robustness hot path",
}

# Default lint roots, relative to the repo root (tests are excluded on
# purpose: fixture snippets there *are* violations).
DEFAULT_LINT_ROOTS = ("midgpt_tpu", "tools", "bench.py", "launch.py", "sample.py")

_SUPPRESS_RE = re.compile(
    r"graftcheck:\s*disable=((?:GC\d{3})(?:\s*,\s*GC\d{3})*)\s*(.*)", re.DOTALL
)
_PARITY_RE = re.compile(r"\bparit(?:y|ies)\b", re.IGNORECASE)
_REFERENCE_CITE_RE = re.compile(r"\breference\s+[\w./\\-]+:\d+")
_TEST_CITE_RE = re.compile(r"\btests[/\\]\w+\.py\b")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int
    rules: tp.Tuple[str, ...]
    justification: str


def parse_suppressions(source: str) -> tp.List[Suppression]:
    """All `# graftcheck: disable=...` comments with their line numbers."""
    out: tp.List[Suppression] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = tuple(r.strip() for r in m.group(1).split(","))
                out.append(Suppression(tok.start[0], rules, m.group(2).strip()))
    except tokenize.TokenError:
        pass  # syntax problems surface via ast.parse instead
    return out


# ----------------------------------------------------------------------
# AST helpers
# ----------------------------------------------------------------------


def _dotted(node: ast.AST) -> tp.Optional[str]:
    """'a.b.c' for a Name/Attribute chain rooted at a Name, else None."""
    parts: tp.List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_name(call: ast.Call) -> tp.Optional[str]:
    return _dotted(call.func)


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _partial_of(call: ast.Call) -> tp.Optional[ast.AST]:
    """The wrapped callable if `call` is functools.partial(fn, ...)."""
    if _call_name(call) in ("functools.partial", "partial") and call.args:
        return call.args[0]
    return None


def _unwrap_callable(node: ast.AST) -> tp.Optional[str]:
    """Bare name of a callable expr: Name, partial(Name, ...), or dotted."""
    if isinstance(node, ast.Call):
        inner = _partial_of(node)
        if inner is not None:
            return _unwrap_callable(inner)
        return None
    return _dotted(node)


_FuncDef = tp.Union[ast.FunctionDef, ast.AsyncFunctionDef]


class _Module:
    """One parsed module with the scope/donation index the rules share."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.parents: tp.Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.defs: tp.List[_FuncDef] = [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.defs_by_name: tp.Dict[str, tp.List[_FuncDef]] = {}
        for d in self.defs:
            self.defs_by_name.setdefault(d.name, []).append(d)
        # `kernel = functools.partial(_fwd_kernel, ...)` style indirection:
        # an alias map so pallas_call(kernel, ...) still resolves. Multi-
        # valued: the same variable may bind different kernels per branch.
        self.aliases: tp.Dict[str, tp.Set[str]] = {}
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                target = _unwrap_callable(node.value)
                if target:
                    self.aliases.setdefault(node.targets[0].id, set()).add(target)
        self.kernel_defs = self._kernel_defs()
        self.traced_defs = self._traced_defs()
        self.donators = self._donators()

    # -- scope discovery ------------------------------------------------

    def resolve_defs(self, name: tp.Optional[str]) -> tp.List[_FuncDef]:
        """Defs a (dotted) callable name may refer to, following aliases."""
        if not name:
            return []
        out: tp.List[_FuncDef] = []
        seen: tp.Set[str] = set()
        frontier = [name]
        while frontier:
            leaf = frontier.pop().split(".")[-1]
            if leaf in seen:
                continue
            seen.add(leaf)
            if leaf in self.defs_by_name:
                out.extend(self.defs_by_name[leaf])
            else:
                frontier.extend(self.aliases.get(leaf, ()))
        return out

    def _jit_root_defs(self) -> tp.Set[_FuncDef]:
        roots: tp.Set[_FuncDef] = set()
        for d in self.defs:
            for deco in d.decorator_list:
                if _is_jax_jit(deco):
                    roots.add(d)
                elif isinstance(deco, ast.Call):
                    inner = _partial_of(deco)
                    if inner is not None and _is_jax_jit(inner):
                        roots.add(d)
                    elif _is_jax_jit(deco.func):
                        roots.add(d)
        # name = jax.jit(fn, ...) rebinding of a module function
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func) and node.args:
                for d in self.resolve_defs(_unwrap_callable(node.args[0])):
                    roots.add(d)
        return roots

    def _kernel_defs(self) -> tp.Set[_FuncDef]:
        """Functions used as Pallas kernel bodies (first arg of pallas_call)."""
        kernels: tp.Set[_FuncDef] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if not name or name.split(".")[-1] != "pallas_call":
                continue
            args = list(node.args)
            for kw in node.keywords:
                if kw.arg == "kernel":
                    args.insert(0, kw.value)
            if not args:
                continue
            for d in self.resolve_defs(_unwrap_callable(args[0])):
                kernels.add(d)
        return self._closure(kernels)

    def _scan_body_defs(self) -> tp.Set[_FuncDef]:
        bodies: tp.Set[_FuncDef] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if not name:
                continue
            leaf = name.split(".")[-1]
            if leaf not in ("scan", "while_loop", "fori_loop", "cond"):
                continue
            for arg in node.args:
                for d in self.resolve_defs(_unwrap_callable(arg)):
                    bodies.add(d)
        return bodies

    def _closure(self, roots: tp.Set[_FuncDef]) -> tp.Set[_FuncDef]:
        """roots plus same-module functions they call by bare name."""
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            d = frontier.pop()
            for node in ast.walk(d):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    for callee in self.defs_by_name.get(node.func.id, []):
                        if callee not in seen:
                            seen.add(callee)
                            frontier.append(callee)
        return seen

    def _traced_defs(self) -> tp.Set[_FuncDef]:
        roots = self._jit_root_defs() | self.kernel_defs | self._scan_body_defs()
        return self._closure(roots)

    # -- donation index -------------------------------------------------

    def _donators(self) -> tp.Dict[str, tp.Tuple[_FuncDef, tp.Tuple[int, ...]]]:
        """name -> (def, donated positional indices) for this module."""
        out: tp.Dict[str, tp.Tuple[_FuncDef, tp.Tuple[int, ...]]] = {}

        def donated_from_call(call: ast.Call) -> tp.Tuple[int, ...]:
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    v = kw.value
                    if isinstance(v, ast.Constant) and isinstance(v.value, int):
                        return (v.value,)
                    if isinstance(v, (ast.Tuple, ast.List)):
                        idx = [
                            e.value
                            for e in v.elts
                            if isinstance(e, ast.Constant) and isinstance(e.value, int)
                        ]
                        return tuple(idx)
            return ()

        for d in self.defs:
            for deco in d.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                donated = donated_from_call(deco)
                if donated and (
                    _is_jax_jit(deco.func) or (_partial_of(deco) is not None and _is_jax_jit(_partial_of(deco)))
                ):
                    out[d.name] = (d, donated)
        # name = jax.jit(fn, donate_argnums=...) rebinding
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if not _is_jax_jit(call.func) or not call.args:
                continue
            donated = donated_from_call(call)
            target = _unwrap_callable(call.args[0])
            if donated and target:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        for d in self.resolve_defs(target):
                            out[tgt.id] = (d, donated)
        return out

    # -- generic lookups ------------------------------------------------

    def enclosing_stmt(self, node: ast.AST) -> ast.stmt:
        cur = node
        while not isinstance(cur, ast.stmt):
            cur = self.parents[cur]
        return cur

    def enclosing_function(self, node: ast.AST) -> tp.Optional[_FuncDef]:
        cur: tp.Optional[ast.AST] = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_loop(
        self, node: ast.AST, within: tp.Optional[ast.AST] = None
    ) -> tp.Optional[ast.stmt]:
        cur: tp.Optional[ast.AST] = self.parents.get(node)
        while cur is not None and cur is not within:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                return cur
            cur = self.parents.get(cur)
        return None


# ----------------------------------------------------------------------
# rules
# ----------------------------------------------------------------------


def _rule_gc001(mod: _Module) -> tp.Iterator[Finding]:
    targets = {"cond", "while_loop", "fori_loop"}
    for kern in mod.kernel_defs:
        for node in ast.walk(kern):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if not name:
                continue
            parts = name.split(".")
            if parts[-1] in targets and (len(parts) == 1 or "lax" in parts[:-1]):
                yield Finding(
                    "GC001",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`{name}` inside Pallas kernel `{kern.name}` defeats Mosaic "
                    "pipelining — use straight-line selects / pl.when "
                    "(CLAUDE.md Mosaic gotchas)",
                )


def _has_static_shape_arg(node: ast.AST) -> bool:
    """int()/float() of .shape/.ndim/.size/len() is static — not a sync."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size", "dtype", "itemsize", "nbytes"):
            return True
        if isinstance(sub, ast.Call) and _dotted(sub.func) == "len":
            return True
    return False


def _rule_gc002(mod: _Module) -> tp.Iterator[Finding]:
    for fn in mod.traced_defs:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in ("float", "int", "bool", "complex"):
                if node.args and not any(
                    isinstance(a, ast.Constant) or _has_static_shape_arg(a)
                    for a in node.args
                ):
                    yield Finding(
                        "GC002",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"`{name}()` on a traced value inside `{fn.name}` forces a "
                        "host sync at trace time (ConcretizationTypeError or a "
                        "silent constant)",
                    )
            elif isinstance(node.func, ast.Attribute) and node.func.attr == "item":
                yield Finding(
                    "GC002",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`.item()` inside traced `{fn.name}` is a device->host sync",
                )
            elif name in ("np.asarray", "numpy.asarray", "np.array", "numpy.array"):
                yield Finding(
                    "GC002",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`{name}` inside traced `{fn.name}` materializes the traced "
                    "value on host (use jnp)",
                )


def _rule_gc003(mod: _Module) -> tp.Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if not name or name.split(".")[-1] != "BlockSpec":
            continue
        shape: tp.Optional[ast.AST] = node.args[0] if node.args else None
        if shape is None:
            for kw in node.keywords:
                if kw.arg == "block_shape":
                    shape = kw.value
        if not isinstance(shape, (ast.Tuple, ast.List)) or len(shape.elts) < 2:
            continue
        last_two = shape.elts[-2:]
        if not all(
            isinstance(e, ast.Constant) and isinstance(e.value, int) for e in last_two
        ):
            continue  # symbolic dims: not statically checkable
        sublane, lane = (e.value for e in last_two)  # type: ignore[union-attr]
        # 1 is accepted as a plausible full singleton dim; anything else must
        # obey the (8, 128) tiling rule unless it spans the full array dim —
        # which a literal cannot prove, so suppress with justification if so.
        bad_sublane = sublane != 1 and sublane % 8 != 0
        bad_lane = lane != 1 and lane % 128 != 0
        if bad_sublane or bad_lane:
            yield Finding(
                "GC003",
                mod.path,
                node.lineno,
                node.col_offset,
                f"BlockSpec last-two dims ({sublane}, {lane}) are not "
                "(8, 128)-divisible; Mosaic requires divisibility or spanning "
                "the full array dim (CLAUDE.md) — suppress with justification "
                "if these span the array",
            )


def _stores_in(node: ast.AST) -> tp.Set[str]:
    """Dotted names assigned anywhere under `node`."""
    out: tp.Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
            getattr(sub, "ctx", None), (ast.Store, ast.Del)
        ):
            d = _dotted(sub)
            if d:
                out.add(d)
    return out


def _rule_gc004(mod: _Module) -> tp.Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
            continue
        entry = mod.donators.get(node.func.id)
        if entry is None:
            continue
        fdef, donated = entry
        params = [a.arg for a in fdef.args.args]
        donated_exprs: tp.List[str] = []
        for idx in donated:
            expr: tp.Optional[ast.AST] = None
            if idx < len(node.args):
                expr = node.args[idx]
            elif idx < len(params):
                for kw in node.keywords:
                    if kw.arg == params[idx]:
                        expr = kw.value
            if expr is not None:
                d = _dotted(expr)
                if d:
                    donated_exprs.append(d)
        if not donated_exprs:
            continue
        stmt = mod.enclosing_stmt(node)
        scope: ast.AST = mod.enclosing_function(node) or mod.tree
        reassigned_here = _stores_in(stmt)
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for expr in donated_exprs:
            if expr in reassigned_here:
                continue  # rebound by the donating statement itself
            # first later occurrence in the scope decides: Load -> stale read
            later: tp.List[tp.Tuple[int, int, bool]] = []
            for sub in ast.walk(scope):
                if isinstance(sub, (ast.Name, ast.Attribute)) and _dotted(sub) == expr:
                    if sub.lineno > end:
                        is_store = isinstance(sub.ctx, (ast.Store, ast.Del))
                        later.append((sub.lineno, sub.col_offset, is_store))
            later.sort()
            if later and not later[0][2]:
                yield Finding(
                    "GC004",
                    mod.path,
                    later[0][0],
                    later[0][1],
                    f"`{expr}` was donated to `{node.func.id}` at line "
                    f"{node.lineno} — its buffer is deleted; reading it here "
                    "raises (or silently aliases) at runtime",
                )
                continue
            loop = mod.enclosing_loop(stmt, within=scope)
            if loop is not None and expr not in _stores_in(loop):
                yield Finding(
                    "GC004",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"`{expr}` is donated to `{node.func.id}` inside a loop but "
                    "never rebound in the loop body — the next iteration reads "
                    "a deleted buffer",
                )


def _rule_gc005(mod: _Module) -> tp.Iterator[Finding]:
    clock_fns = {"time", "perf_counter", "monotonic", "process_time", "time_ns"}
    for fn in mod.traced_defs:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name and "." in name:
                    root, leaf = name.split(".")[0], name.split(".")[-1]
                    if root == "time" and leaf in clock_fns:
                        yield Finding(
                            "GC005",
                            mod.path,
                            node.lineno,
                            node.col_offset,
                            f"`{name}()` inside traced `{fn.name}` is evaluated "
                            "once at trace time — the compiled program sees a "
                            "frozen constant",
                        )
            if isinstance(node, ast.Attribute) and node.attr == "random":
                root = _dotted(node)
                if root in ("np.random", "numpy.random"):
                    yield Finding(
                        "GC005",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"`{root}` inside traced `{fn.name}`: host RNG is baked "
                        "in at trace time — use jax.random with a threaded key",
                    )


def _rule_gc006(mod: _Module) -> tp.Iterator[Finding]:
    for fn in mod.defs:
        doc = ast.get_docstring(fn, clean=False)
        if not doc or not _PARITY_RE.search(doc):
            continue
        if _REFERENCE_CITE_RE.search(doc) or _TEST_CITE_RE.search(doc):
            continue
        yield Finding(
            "GC006",
            mod.path,
            fn.lineno,
            fn.col_offset,
            f"docstring of `{fn.name}` claims parity but cites neither "
            "`reference file:line` nor a pinning test (CLAUDE.md convention)",
        )


# Leaf names of checkpoint-manager and cross-device/host collective calls:
# the operations whose failure must never be silently dropped (a swallowed
# save means lost state; a swallowed collective means half the mesh enters
# the op and deadlocks). Dotted calls only — bare local helpers named `save`
# are not checkpoint ops.
_GC007_LEAVES = frozenset(
    {
        "save",
        "restore",
        "wait_until_finished",
        "check_for_errors",
        "delete",
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "all_reduce",
        "ppermute",
        "all_to_all",
        "sync_global_devices",
        "process_allgather",
        "broadcast_one_to_all",
    }
)


def _gc007_broad(handler: ast.ExceptHandler) -> tp.Optional[str]:
    """The broad class name a handler catches, or None if it is specific."""
    t = handler.type
    if t is None:
        return "<bare>"
    names = [e for e in (t.elts if isinstance(t, ast.Tuple) else [t])]
    for e in names:
        d = _dotted(e)
        if d in ("Exception", "BaseException"):
            return d
    return None


def _rule_gc007(mod: _Module) -> tp.Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Try):
            continue
        calls: tp.Set[str] = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    name = _call_name(sub)
                    if name and "." in name and name.split(".")[-1] in _GC007_LEAVES:
                        calls.add(name)
        if not calls:
            continue
        for handler in node.handlers:
            broad = _gc007_broad(handler)
            if broad is None:
                continue
            swallows = not any(
                isinstance(sub, ast.Raise)
                for stmt in handler.body
                for sub in ast.walk(stmt)
            )
            if swallows:
                caught = "bare `except:`" if broad == "<bare>" else f"`except {broad}`"
                yield Finding(
                    "GC007",
                    mod.path,
                    handler.lineno,
                    handler.col_offset,
                    f"{caught} swallows failures of checkpoint/collective "
                    f"call(s) {sorted(calls)} — a dropped save/restore loses "
                    "state and a dropped collective deadlocks the mesh; "
                    "catch specific exceptions or re-raise (suppress with "
                    "justification if the swallow is deliberate)",
                )


# int8 dtype spellings GC008 recognizes as a quantizing cast target.
_INT8_DTYPES = frozenset(
    {"int8", "jnp.int8", "np.int8", "numpy.int8", "jax.numpy.int8"}
)
# Calls in the cast's receiver that count as rounding evidence. `clip` is
# deliberately NOT enough — clip(x, -127, 127).astype(int8) still truncates.
_ROUNDING_LEAVES = frozenset({"round", "rint", "around", "round_"})


def _rule_gc008(mod: _Module) -> tp.Iterator[Finding]:
    """`x.astype(jnp.int8)` / `x.astype("int8")` with no rounding call in
    the receiver expression. AST-only, so the source's float-ness cannot be
    proven — an int-to-int8 narrowing is a legitimate suppression (the
    justification documents why truncation is safe there)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "astype"):
            continue
        target: tp.Optional[ast.AST] = node.args[0] if node.args else None
        if target is None:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    target = kw.value
        if target is None:
            continue
        is_int8 = _dotted(target) in _INT8_DTYPES or (
            isinstance(target, ast.Constant) and target.value == "int8"
        )
        if not is_int8:
            continue
        rounded = any(
            isinstance(sub, ast.Call)
            and (_call_name(sub) or "").split(".")[-1] in _ROUNDING_LEAVES
            for sub in ast.walk(f.value)
        )
        if not rounded:
            yield Finding(
                "GC008",
                mod.path,
                node.lineno,
                node.col_offset,
                "`.astype(int8)` truncates toward zero — quantization must "
                "round-to-nearest first (jnp.round / ops/quant.py "
                "quantize_q8); suppress with justification if the source "
                "is already integral",
            )


# Wall-clock reads GC012 recognizes. `sleep` is absent on purpose (a delay,
# not a measurement) and so are the *_ns variants' non-time roots — only
# calls rooted at the `time` module count.
_GC012_CLOCK_LEAVES = frozenset(
    {
        "time",
        "perf_counter",
        "monotonic",
        "process_time",
        "time_ns",
        "perf_counter_ns",
        "monotonic_ns",
        "process_time_ns",
    }
)


def _gc012_in_scope(path: str) -> bool:
    """Path-scoped: only `sampling/` and `robustness/` trees — the hot
    paths where the injectable-clock discipline is load-bearing."""
    parts = re.split(r"[/\\]", path)
    return "sampling" in parts or "robustness" in parts


def _rule_gc012(mod: _Module) -> tp.Iterator[Finding]:
    """Bare clock CALLS in injectable-clock territory. A reference like
    `clock=time.perf_counter` (ctor default) is the plumbing itself and is
    a Name/Attribute node, not a Call — never flagged."""
    if not _gc012_in_scope(mod.path):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if not name or "." not in name:
            continue
        parts = name.split(".")
        if parts[0] == "time" and parts[-1] in _GC012_CLOCK_LEAVES:
            yield Finding(
                "GC012",
                mod.path,
                node.lineno,
                node.col_offset,
                f"`{name}()` bypasses the injected clock in a serving/"
                "robustness hot path — read `self._clock()` (or the "
                "module's `clock` parameter) so tests can fake time and "
                "round decomposition stays tunnel-consistent "
                "(docs/OBSERVABILITY.md); suppress with justification "
                "for genuinely wall-anchored timestamps",
            )


_ALL_RULES = (
    _rule_gc001,
    _rule_gc002,
    _rule_gc003,
    _rule_gc004,
    _rule_gc005,
    _rule_gc006,
    _rule_gc007,
    _rule_gc008,
    _rule_gc012,
)


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------


def lint_source(
    source: str,
    path: str = "<string>",
    rules: tp.Optional[tp.Iterable[str]] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding]]:
    """Lint one module's source. Returns (active, suppressed) findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        f = Finding("GC000", path, e.lineno or 0, e.offset or 0, f"syntax error: {e.msg}")
        return [f], []
    mod = _Module(path, source, tree)
    wanted = set(rules) if rules is not None else set(RULES)
    suppress_at: tp.Dict[int, tp.Set[str]] = {}
    for s in parse_suppressions(source):
        suppress_at.setdefault(s.line, set()).update(s.rules)
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    for rule_fn in _ALL_RULES:
        for f in rule_fn(mod):
            if f.rule not in wanted:
                continue
            if f.rule in suppress_at.get(f.line, ()):
                suppressed.append(f)
            else:
                active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return active, suppressed


def iter_python_files(roots: tp.Sequence[str]) -> tp.Iterator[str]:
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
        else:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: tp.Sequence[str],
    rules: tp.Optional[tp.Iterable[str]] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding], int]:
    """Lint files/trees. Returns (active, suppressed, files_scanned)."""
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    n = 0
    for path in iter_python_files(paths):
        n += 1
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        a, s = lint_source(src, path, rules)
        active.extend(a)
        suppressed.extend(s)
    return active, suppressed, n
