"""graftcheck CLI: `python -m midgpt_tpu.analysis [paths...] [options]`.

Exit status: 0 when no active findings (and, with --audit, every audit
passes); 1 otherwise. Default output is one `path:line:col: GCnnn message`
line per finding; --json emits ONE JSON line (the bench.py driver
convention — schema in analysis/bench_contract.py) so automated drivers
can consume findings without scraping.

Pass 1 (the lint), pass 3 (the lifecycle/dataflow pass) and pass 4 (the
concurrency/boundary pass) perform no JAX backend initialization; --audit
opts into pass 2, which forces the CPU backend before first JAX use (the
axon TPU plugin ignores JAX_PLATFORMS — CLAUDE.md) and compiles two tiny
abstract programs.

--fail-on-new compares active findings against the committed baseline
(analysis/graftcheck_baseline.json, keyed by (rule, relative path,
message) — line-number-free so unrelated edits don't churn it) and exits
nonzero only on NEW findings; it also diffs the static jit-wrapper census
against analysis/jit_surface_baseline.json (keyed (path, name)) so a new
jit wrapper or a widened static-arg set fails until deliberately re-pinned.
--update-baseline rewrites both baselines from the current tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import typing as tp

from midgpt_tpu.analysis.concurrency import CONCURRENCY_RULES, concurrency_paths
from midgpt_tpu.analysis.jit_surface import (
    diff_surface,
    jit_surface,
    load_baseline,
    save_baseline,
)
from midgpt_tpu.analysis.lifecycle import LIFECYCLE_RULES, lifecycle_paths
from midgpt_tpu.analysis.lint import DEFAULT_LINT_ROOTS, RULES, lint_paths

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "graftcheck_baseline.json")


def _repo_root() -> str:
    import midgpt_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(midgpt_tpu.__file__)))


def _default_paths() -> tp.List[str]:
    """Resolve DEFAULT_LINT_ROOTS against the repo root (the parent of the
    midgpt_tpu package), so the CLI works from any cwd."""
    repo = _repo_root()
    return [p for p in (os.path.join(repo, r) for r in DEFAULT_LINT_ROOTS) if os.path.exists(p)]


def _baseline_key(f, repo: str) -> tp.Tuple[str, str, str]:
    path = os.path.abspath(f.path) if isinstance(f.path, str) else f.path
    try:
        rel = os.path.relpath(path, repo)
    except ValueError:
        rel = f.path
    return (f.rule, rel.replace(os.sep, "/"), f.message)


def main(argv: tp.Optional[tp.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck", description="JAX/TPU-aware static analysis for midgpt_tpu"
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the package, tools/ and "
        "the top-level entry points; tests/ is excluded — fixtures there "
        "are deliberate violations)",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line (driver contract)")
    ap.add_argument(
        "--rules",
        type=str,
        default=None,
        help="comma-separated rule subset, e.g. GC001,GC009",
    )
    ap.add_argument(
        "--audit",
        action="store_true",
        help="also run pass 2 (compiled-artifact audit; imports jax, CPU-only)",
    )
    ap.add_argument(
        "--fail-on-new",
        action="store_true",
        help="exit nonzero only on findings absent from the committed "
        "baseline (analysis/graftcheck_baseline.json)",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the committed baseline from the current findings",
    )
    args = ap.parse_args(argv)

    known = {**RULES, **LIFECYCLE_RULES, **CONCURRENCY_RULES}
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",")]
        unknown = [r for r in rules if r not in known]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; known: {sorted(known)}")

    paths = args.paths or _default_paths()
    lint_rules = None if rules is None else [r for r in rules if r in RULES]
    life_rules = None if rules is None else [r for r in rules if r in LIFECYCLE_RULES]
    conc_rules = None if rules is None else [r for r in rules if r in CONCURRENCY_RULES]
    active: tp.List = []
    suppressed: tp.List = []
    n_files = 0
    if rules is None or lint_rules:
        active, suppressed, n_files = lint_paths(paths, lint_rules)
    p3_active: tp.List = []
    p3_suppressed: tp.List = []
    t0 = time.perf_counter()
    if rules is None or life_rules:
        p3_active, p3_suppressed, p3_files = lifecycle_paths(paths, life_rules)
        n_files = max(n_files, p3_files)
    pass3_wall_ms = (time.perf_counter() - t0) * 1000.0
    p4_active: tp.List = []
    p4_suppressed: tp.List = []
    t0 = time.perf_counter()
    if rules is None or conc_rules:
        p4_active, p4_suppressed, p4_files = concurrency_paths(paths, conc_rules)
        n_files = max(n_files, p4_files)
    pass4_wall_ms = (time.perf_counter() - t0) * 1000.0
    active = sorted(
        active + p3_active + p4_active,
        key=lambda f: (f.path, f.line, f.col, f.rule),
    )
    suppressed = suppressed + p3_suppressed + p4_suppressed

    # jit-surface census (always computed: `jit_surface_count` is part of
    # the --json contract); the baseline diff only gates under
    # --fail-on-new, like the findings baseline.
    surface = jit_surface(paths, rel_to=_repo_root())

    audit_report: tp.Optional[tp.Dict[str, tp.Any]] = None
    audit_error: tp.Optional[str] = None
    if args.audit:
        # Force CPU before any backend touch: the axon TPU plugin overrides
        # JAX_PLATFORMS, so env alone cannot keep the audit off the tunnel.
        import jax

        jax.config.update("jax_platforms", "cpu")
        from midgpt_tpu.analysis.hlo_audit import run_audit

        try:
            audit_report = run_audit()
        except AssertionError as e:
            audit_error = str(e)

    repo = _repo_root()
    new_findings = active
    surface_problems: tp.List[str] = []
    if args.update_baseline:
        with open(BASELINE_PATH, "w", encoding="utf-8") as fh:
            json.dump(
                [
                    {"rule": r, "path": p, "message": m}
                    for r, p, m in sorted(_baseline_key(f, repo) for f in active)
                ],
                fh,
                indent=1,
            )
            fh.write("\n")
        save_baseline(surface)
    if args.fail_on_new:
        baseline: tp.Set[tp.Tuple[str, str, str]] = set()
        if os.path.exists(BASELINE_PATH):
            with open(BASELINE_PATH, "r", encoding="utf-8") as fh:
                baseline = {
                    (e["rule"], e["path"], e["message"]) for e in json.load(fh)
                }
        new_findings = [f for f in active if _baseline_key(f, repo) not in baseline]
        surface_problems = diff_surface(surface, load_baseline())

    failed = (
        bool(new_findings)
        or bool(surface_problems)
        or audit_error is not None
    )
    if args.json:
        out: tp.Dict[str, tp.Any] = {
            "tool": "graftcheck",
            "count": len(active),
            "suppressed": len(suppressed),
            "files_scanned": n_files,
            "findings": [f.to_dict() for f in active],
            "pass3_count": len(p3_active),
            "pass3_suppressed": len(p3_suppressed),
            "pass3_wall_ms": pass3_wall_ms,
            "pass4_count": len(p4_active),
            "pass4_suppressed": len(p4_suppressed),
            "pass4_wall_ms": pass4_wall_ms,
            "jit_surface_count": len(surface),
        }
        if args.fail_on_new:
            out["new_count"] = len(new_findings)
            out["jit_surface_new"] = len(surface_problems)
        if args.audit:
            out["audit"] = audit_report if audit_error is None else {"error": audit_error}
        print(json.dumps(out))
    else:
        report = new_findings if args.fail_on_new else active
        for f in report:
            print(f.format())
        for p in surface_problems:
            print(f"jit-surface: {p}")
        if audit_error is not None:
            print(f"audit: FAILED — {audit_error}")
        elif audit_report is not None:
            print(f"audit: ok — {json.dumps(audit_report)}")
        tail = (
            f"graftcheck: {len(active)} finding(s), {len(suppressed)} "
            f"suppressed, {n_files} file(s) scanned "
            f"(pass 3: {len(p3_active)} finding(s) in {pass3_wall_ms:.0f} ms; "
            f"pass 4: {len(p4_active)} finding(s) in {pass4_wall_ms:.0f} ms; "
            f"jit surface: {len(surface)} wrapper(s))"
        )
        if args.fail_on_new:
            tail += (
                f"; {len(new_findings)} new vs baseline, "
                f"{len(surface_problems)} jit-surface change(s)"
            )
        print(tail)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
