"""graftcheck CLI: `python -m midgpt_tpu.analysis [paths...] [options]`.

Exit status: 0 when no active findings (and, with --audit, every audit
passes); 1 otherwise. Default output is one `path:line:col: GCnnn message`
line per finding; --json emits ONE JSON line (the bench.py driver
convention — schema in analysis/bench_contract.py) so automated drivers
can consume findings without scraping.

Pass 1 (the lint) performs no JAX backend initialization; --audit opts into
pass 2, which forces the CPU backend before first JAX use (the axon TPU
plugin ignores JAX_PLATFORMS — CLAUDE.md) and compiles two tiny abstract
programs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import typing as tp

from midgpt_tpu.analysis.lint import DEFAULT_LINT_ROOTS, RULES, lint_paths


def _default_paths() -> tp.List[str]:
    """Resolve DEFAULT_LINT_ROOTS against the repo root (the parent of the
    midgpt_tpu package), so the CLI works from any cwd."""
    import midgpt_tpu

    repo = os.path.dirname(os.path.dirname(os.path.abspath(midgpt_tpu.__file__)))
    return [p for p in (os.path.join(repo, r) for r in DEFAULT_LINT_ROOTS) if os.path.exists(p)]


def main(argv: tp.Optional[tp.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck", description="JAX/TPU-aware static analysis for midgpt_tpu"
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the package, tools/ and "
        "the top-level entry points; tests/ is excluded — fixtures there "
        "are deliberate violations)",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line (driver contract)")
    ap.add_argument(
        "--rules",
        type=str,
        default=None,
        help="comma-separated rule subset, e.g. GC001,GC003",
    )
    ap.add_argument(
        "--audit",
        action="store_true",
        help="also run pass 2 (compiled-artifact audit; imports jax, CPU-only)",
    )
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",")]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; known: {sorted(RULES)}")

    paths = args.paths or _default_paths()
    active, suppressed, n_files = lint_paths(paths, rules)

    audit_report: tp.Optional[tp.Dict[str, tp.Any]] = None
    audit_error: tp.Optional[str] = None
    if args.audit:
        # Force CPU before any backend touch: the axon TPU plugin overrides
        # JAX_PLATFORMS, so env alone cannot keep the audit off the tunnel.
        import jax

        jax.config.update("jax_platforms", "cpu")
        from midgpt_tpu.analysis.hlo_audit import run_audit

        try:
            audit_report = run_audit()
        except AssertionError as e:
            audit_error = str(e)

    failed = bool(active) or audit_error is not None
    if args.json:
        out: tp.Dict[str, tp.Any] = {
            "tool": "graftcheck",
            "count": len(active),
            "suppressed": len(suppressed),
            "files_scanned": n_files,
            "findings": [f.to_dict() for f in active],
        }
        if args.audit:
            out["audit"] = audit_report if audit_error is None else {"error": audit_error}
        print(json.dumps(out))
    else:
        for f in active:
            print(f.format())
        if audit_error is not None:
            print(f"audit: FAILED — {audit_error}")
        elif audit_report is not None:
            print(f"audit: ok — {json.dumps(audit_report)}")
        print(
            f"graftcheck: {len(active)} finding(s), {len(suppressed)} suppressed, "
            f"{n_files} file(s) scanned"
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
