"""graftcheck — JAX/TPU-aware static analysis for this repo.

Two passes (docs/ANALYSIS.md is the rule catalog):

  * **Pass 1 — AST lint** (`analysis.lint`, no JAX import): walks package
    source and flags the compilation-behavior footguns that CLAUDE.md and
    RESULTS.md record as hard-won gotchas — control flow in Pallas kernel
    bodies, host syncs inside jitted scopes, untiled BlockSpec literals,
    use-after-donate, wall-clock/np.random reachable from traced code, and
    uncited parity claims. Rules GC001-GC006, suppressible inline with
    `# graftcheck: disable=GCnnn — justification`.
  * **Pass 2 — compiled-artifact audit** (`analysis.hlo_audit`, builds on
    utils/hlo.py): executable pins over post-optimization HLO and the jit
    compile cache — recompile counting, while-body collective census, fp32
    master-param presence — so the scheduling/parity claims in SERVING.md
    and SURVEY.md §7 are tested, not remembered.

`analysis.bench_contract` is the shared checker for the one-JSON-line
driver contract that bench.py / tools/bench_serve.py (and the graftcheck
CLI's own --json mode) must honor.

CLI: `python -m midgpt_tpu.analysis [paths...] [--json] [--audit]`
(tools/graftcheck.py is a path-setup wrapper). Pass 1 never initializes a
JAX backend, so the lint gate is safe to run on hosts where device init is
slow or unavailable.
"""

from midgpt_tpu.analysis.lint import (
    DEFAULT_LINT_ROOTS,
    Finding,
    RULES,
    lint_paths,
    lint_source,
)

__all__ = [
    "DEFAULT_LINT_ROOTS",
    "Finding",
    "RULES",
    "lint_paths",
    "lint_source",
]
