"""graftcheck — JAX/TPU-aware static analysis for this repo.

Three passes (docs/ANALYSIS.md is the rule catalog):

  * **Pass 1 — AST lint** (`analysis.lint`, no JAX import): walks package
    source and flags the compilation-behavior footguns that CLAUDE.md and
    RESULTS.md record as hard-won gotchas — control flow in Pallas kernel
    bodies, host syncs inside jitted scopes, untiled BlockSpec literals,
    use-after-donate, wall-clock/np.random reachable from traced code, and
    uncited parity claims. Rules GC001-GC006, suppressible inline with
    `# graftcheck: disable=GCnnn — justification`.
  * **Pass 2 — compiled-artifact audit** (`analysis.hlo_audit`, builds on
    utils/hlo.py): executable pins over post-optimization HLO and the jit
    compile cache — recompile counting, while-body collective census, fp32
    master-param presence — so the scheduling/parity claims in SERVING.md
    and SURVEY.md §7 are tested, not remembered. Its numeric budgets live
    in `analysis.budgets`, the single manifest both the audit and
    tests/test_recompile_pins.py consume.
  * **Pass 3 — lifecycle/dataflow** (`analysis.lifecycle`, no JAX import):
    interprocedural checks over the serving stack — page-ownership
    balance on every path including exception edges (GC009), ServeEngine
    mutation confinement to the driver-loop serialization boundary and
    no-await-mid-mutation (GC010), and bounded-domain proofs for values
    flowing into trailing static jit args (GC011). Same suppression
    machinery as pass 1.

`analysis.bench_contract` is the shared checker for the one-JSON-line
driver contract that bench.py / tools/bench_serve.py (and the graftcheck
CLI's own --json mode) must honor.

CLI: `python -m midgpt_tpu.analysis [paths...] [--json] [--audit]
[--fail-on-new] [--update-baseline]` (tools/graftcheck.py is a path-setup
wrapper). Passes 1 and 3 never initialize a JAX backend, so the lint gate
is safe to run on hosts where device init is slow or unavailable;
--fail-on-new gates CI on the committed graftcheck_baseline.json.
"""

from midgpt_tpu.analysis.lifecycle import (
    LIFECYCLE_RULES,
    lifecycle_paths,
    lifecycle_source,
)
from midgpt_tpu.analysis.lint import (
    DEFAULT_LINT_ROOTS,
    Finding,
    RULES,
    lint_paths,
    lint_source,
)

__all__ = [
    "DEFAULT_LINT_ROOTS",
    "Finding",
    "LIFECYCLE_RULES",
    "RULES",
    "lifecycle_paths",
    "lifecycle_source",
    "lint_paths",
    "lint_source",
]
