"""graftcheck pass 3: lifecycle + concurrency dataflow over the serving stack.

Deliberately JAX-free, like pass 1 (analysis/lint.py), whose Finding and
suppression machinery this pass shares. Where pass 1 flags single-site
footguns, pass 3 tracks *obligations* across paths:

  GC009  page-set / refcount lifecycle. Every acquisition site — pool
         `allocator.alloc`, trie `prefix_cache.match` (takes refs),
         `prefix_cache.evict` / `prefix_cache.release` (both RETURN freed
         page lists that must reach `allocator.free`) — must reach exactly
         one release funnel on every path, including explicit `raise`
         edges. Flags: discarded acquisition results, rebinding a variable
         that still holds pages, falling off a return/raise/function end
         with pages pending, releasing the same pages twice, `.refs`
         mutations outside the trie module, and a `.refs -=` with no
         adjacent underflow guard.
  GC010  async discipline around the serving driver loop
         (sampling/server.py): engine state is single-threaded by
         CONVENTION — only the driver loop (between `to_thread(step)`
         dispatches) may touch ServeEngine/trie/allocator state. Flags a
         direct `*.engine.*` method call or attribute store inside an
         `async def` body (must route through the command queue /
         `_call`), and an `await` interleaved between two mutations of
         the same `self.<attr>` in one block (a coroutine observing the
         half-updated state is the bug chaos_serve can only catch
         trace-by-trace).
  GC011  bounded static domains. Values flowing into a static jit
         argument (`static_argnums`) key the compile cache; an unbounded
         Python value there is an unbounded compile set (the recompile
         pins' bug class, made lexical). Every call-site expression at a
         static position must be PROVABLY drawn from a finite domain:
         literals, init-frozen `self` attributes, pow2 ladders
         (`.bit_length()`), normalizer/bucket/clamp calls, min/max against
         a bound, or parameters whose in-repo call sites all pass bounded
         values (interprocedural, depth-limited).

Scope model and limits (docs/ANALYSIS.md "Pass 3"): receiver names are
matched by hint (`allocator` / `prefix_cache` / `trie` path components, or
locals aliased from one), so the trie module's own internals — which by
design mutate `.refs` and shuffle page lists — are exempt, as is any
`re.match`-style lookalike. Analysis is per-function for GC009/GC010 and
interprocedural-by-bare-name for GC011; like pass 1 it trades soundness
for zero false-positive noise on idiomatic code, and an unprovable-but-
intended domain takes a justified suppression.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import typing as tp

from midgpt_tpu.analysis.lint import (
    Finding,
    _FuncDef,
    _call_name,
    _dotted,
    _is_jax_jit,
    _partial_of,
    _unwrap_callable,
    iter_python_files,
    parse_suppressions,
)

LIFECYCLE_RULES: tp.Dict[str, str] = {
    "GC009": "page-set/refcount obligation leaked, discarded, or double-released",
    "GC010": "engine state touched outside the driver-loop serialization boundary",
    "GC011": "unbounded value feeds a static jit argument (compile-cache key)",
}

# ----------------------------------------------------------------------
# shared helpers
# ----------------------------------------------------------------------

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _own_nodes(root: ast.AST) -> tp.Iterator[ast.AST]:
    """Walk `root` without descending into nested function/class scopes."""
    stack: tp.List[ast.AST] = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _NESTED_SCOPES):
            stack.extend(ast.iter_child_nodes(node))


def _chain(node: ast.AST) -> tp.Tuple[str, ...]:
    """('a', 'b', 'c') for an a.b.c Name/Attribute chain, else ()."""
    dotted = _dotted(node)
    return tuple(dotted.split(".")) if dotted else ()


_ALLOC_HINTS = ("allocator",)
_TRIE_HINTS = ("prefix_cache", "trie")


def _hinted(func: ast.AST, hints: tp.Tuple[str, ...], aliases: tp.Set[str]) -> bool:
    """Does the receiver chain of a call target carry a structure hint?"""
    parts = _chain(func)
    if len(parts) < 2:
        return False
    recv = parts[:-1]
    return any(p in hints for p in recv) or recv[0] in aliases


# ----------------------------------------------------------------------
# GC009 — page-set / refcount lifecycle
# ----------------------------------------------------------------------

_PENDING, _RELEASED, _TRANSFERRED = "pending", "released", "transferred"

# call leaves that transfer ownership of a page-list argument into a
# container (slot.pages.extend(got), table.append(pages), ...)
_TRANSFER_LEAVES = {"extend", "append", "appendleft", "insert", "add", "push"}


@dataclasses.dataclass
class _Ob:
    """One outstanding page-set obligation bound to a local name."""

    line: int
    kind: str  # "alloc" | "match" | "evict" | "release"
    state: str = _PENDING


class _PageWalker:
    """Path-sensitive walk of one function body tracking page obligations."""

    def __init__(self, path: str, fn: _FuncDef, findings: tp.List[Finding]):
        self.path = path
        self.fn = fn
        self.findings = findings
        # locals aliased to a hinted structure: `pc = self.prefill.prefix_cache`
        self.alloc_aliases: tp.Set[str] = set()
        self.trie_aliases: tp.Set[str] = set()
        for node in _own_nodes(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                parts = _chain(node.value)
                if any(p in _ALLOC_HINTS for p in parts):
                    self.alloc_aliases.add(node.targets[0].id)
                if any(p in _TRIE_HINTS for p in parts):
                    self.trie_aliases.add(node.targets[0].id)

    # -- call classification -------------------------------------------

    def _acquire_kind(self, call: ast.Call) -> tp.Optional[str]:
        parts = _chain(call.func)
        if not parts:
            return None
        leaf = parts[-1]
        if leaf == "alloc" and _hinted(call.func, _ALLOC_HINTS, self.alloc_aliases):
            return "alloc"
        if leaf in ("match", "evict", "release") and _hinted(
            call.func, _TRIE_HINTS, self.trie_aliases
        ):
            return leaf
        return None

    def _is_consume(self, call: ast.Call) -> bool:
        """A call that retires a page-set obligation passed as an argument."""
        parts = _chain(call.func)
        if not parts:
            return False
        leaf = parts[-1]
        if leaf == "free" and _hinted(call.func, _ALLOC_HINTS, self.alloc_aliases):
            return True
        # trie release(tokens, pages, n_shared): the pages arg is donated
        if leaf == "release" and _hinted(call.func, _TRIE_HINTS, self.trie_aliases):
            return True
        return False

    def _is_transfer_call(self, call: ast.Call) -> bool:
        parts = _chain(call.func)
        return bool(parts) and parts[-1] in _TRANSFER_LEAVES

    # -- findings -------------------------------------------------------

    def _emit(self, line: int, col: int, message: str) -> None:
        self.findings.append(Finding("GC009", self.path, line, col, message))

    # -- statement walk -------------------------------------------------

    def run(self) -> None:
        env: tp.Dict[str, _Ob] = {}
        terminated = self._walk_block(self.fn.body, env)
        if terminated is None:
            for name, ob in env.items():
                if ob.state == _PENDING:
                    self._emit(
                        ob.line,
                        0,
                        f"pages acquired into `{name}` (via .{ob.kind}) never "
                        "reach a release funnel on the fall-through path",
                    )

    def _walk_block(
        self, stmts: tp.Sequence[ast.stmt], env: tp.Dict[str, _Ob]
    ) -> tp.Optional[str]:
        for st in stmts:
            t = self._walk_stmt(st, env)
            if t is not None:
                return t
        return None

    def _walk_stmt(self, st: ast.stmt, env: tp.Dict[str, _Ob]) -> tp.Optional[str]:
        if isinstance(st, _NESTED_SCOPES):
            # a nested def/class capturing a pending name => ownership
            # escapes local reasoning; treat as transferred
            for node in ast.walk(st):
                if isinstance(node, ast.Name) and node.id in env:
                    if env[node.id].state == _PENDING:
                        env[node.id].state = _TRANSFERRED
            return None
        if isinstance(st, ast.If):
            return self._walk_if(st, env)
        if isinstance(st, (ast.For, ast.While, ast.AsyncFor)):
            return self._walk_loop(st, env)
        if isinstance(st, ast.Try):
            return self._walk_try(st, env)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._process_expr(item.context_expr, env, in_test=False)
            return self._walk_block(st.body, env)
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._process_expr(st.value, env, in_test=False)
            self._leak_check(env, st.lineno, "at this return")
            return "return"
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self._process_expr(st.exc, env, in_test=False)
            if not self._inside_protected_try(st):
                self._leak_check(env, st.lineno, "on this exception edge")
            return "raise"
        if isinstance(st, (ast.Break, ast.Continue)):
            return "break"
        if isinstance(st, ast.Assign):
            return self._walk_assign(st, env)
        if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if getattr(st, "value", None) is not None:
                self._process_expr(st.value, env, in_test=False, binds=True)
            return None
        if isinstance(st, ast.Expr):
            self._process_expr(st.value, env, in_test=False)
            return None
        if isinstance(st, ast.Assert):
            self._process_expr(st.test, env, in_test=True)
            return None
        # default: scan any embedded expressions conservatively
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._process_expr(child, env, in_test=False)
        return None

    def _walk_assign(self, st: ast.Assign, env: tp.Dict[str, _Ob]) -> None:
        value = st.value
        simple_name = (
            st.targets[0].id
            if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name)
            else None
        )
        kind = self._acquire_kind(value) if isinstance(value, ast.Call) else None
        if kind is not None and simple_name is not None:
            # process the acquire call's ARGUMENTS (they may consume other
            # tracked names), but not the call itself
            for arg in list(value.args) + [kw.value for kw in value.keywords]:
                self._process_expr(arg, env, in_test=False)
            old = env.get(simple_name)
            if old is not None and old.state == _PENDING:
                self._emit(
                    st.lineno,
                    st.col_offset,
                    f"`{simple_name}` rebound while still holding pages "
                    f"acquired at line {old.line} — the old pages leak",
                )
            env[simple_name] = _Ob(st.lineno, kind)
            return None
        self._process_expr(value, env, in_test=False, binds=True)
        if simple_name is not None:
            old = env.get(simple_name)
            if old is not None and old.state == _PENDING:
                # RHS uses were processed above; a rebind that did not
                # route the old pages anywhere loses them
                if not any(
                    isinstance(n, ast.Name) and n.id == simple_name
                    for n in ast.walk(value)
                ):
                    self._emit(
                        st.lineno,
                        st.col_offset,
                        f"`{simple_name}` rebound while still holding pages "
                        f"acquired at line {old.line} — the old pages leak",
                    )
            env.pop(simple_name, None)
        return None

    def _walk_if(self, st: ast.If, env: tp.Dict[str, _Ob]) -> tp.Optional[str]:
        self._process_expr(st.test, env, in_test=True)
        refine_body, refine_else = self._refiners(st.test)
        env_body = {k: dataclasses.replace(v) for k, v in env.items()}
        env_else = {k: dataclasses.replace(v) for k, v in env.items()}
        refine_body(env_body)
        refine_else(env_else)
        t_body = self._walk_block(st.body, env_body)
        t_else = self._walk_block(st.orelse, env_else) if st.orelse else None
        branches = []
        if t_body is None:
            branches.append(env_body)
        if t_else is None:
            branches.append(env_else)
        if not branches:
            env.clear()
            return "return"  # both arms terminated: this block is done
        self._merge_into(env, branches)
        return None

    def _walk_loop(self, st: ast.stmt, env: tp.Dict[str, _Ob]) -> tp.Optional[str]:
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._process_expr(st.iter, env, in_test=False)
        else:
            self._process_expr(st.test, env, in_test=True)
        env_body = {k: dataclasses.replace(v) for k, v in env.items()}
        self._walk_block(st.body, env_body)
        if st.orelse:
            self._walk_block(st.orelse, env_body)
        self._merge_into(env, [env, env_body])
        return None

    def _walk_try(self, st: ast.Try, env: tp.Dict[str, _Ob]) -> tp.Optional[str]:
        entry = {k: dataclasses.replace(v) for k, v in env.items()}
        t_body = self._walk_block(st.body, env)
        exits: tp.List[tp.Dict[str, _Ob]] = []
        if t_body is None:
            exits.append(env)
        for handler in st.handlers:
            # the exception may land anywhere in the body: the handler sees
            # anything between the entry state and the body-exit state —
            # union with pending winning is the pessimistic approximation
            env_h = {k: dataclasses.replace(v) for k, v in entry.items()}
            self._merge_into(env_h, [env_h, env])
            t_h = self._walk_block(handler.body, env_h)
            if t_h is None:
                exits.append(env_h)
        merged: tp.Dict[str, _Ob] = {}
        if exits:
            self._merge_into(merged, exits)
        t_final = None
        if st.finalbody:
            t_final = self._walk_block(st.finalbody, merged)
        env.clear()
        env.update(merged)
        if not exits:
            return "return"
        return t_final

    def _inside_protected_try(self, node: ast.AST) -> bool:
        """Is `node` lexically inside a try-with-handlers of this function?
        The handler walk covers those paths; flagging the raise too would
        double-report guarded cleanup idioms."""
        for anc in ast.walk(self.fn):
            if isinstance(anc, ast.Try) and anc.handlers:
                for sub in ast.walk(anc):
                    if sub is node:
                        return True
        return False

    def _leak_check(self, env: tp.Dict[str, _Ob], line: int, where: str) -> None:
        for name, ob in env.items():
            if ob.state == _PENDING:
                self._emit(
                    line,
                    0,
                    f"pages acquired into `{name}` at line {ob.line} "
                    f"(via .{ob.kind}) are still unreleased {where}",
                )
                ob.state = _TRANSFERRED  # one report per obligation per path

    def _merge_into(
        self, dst: tp.Dict[str, _Ob], branches: tp.List[tp.Dict[str, _Ob]]
    ) -> None:
        names: tp.Set[str] = set()
        for b in branches:
            names.update(b)
        out: tp.Dict[str, _Ob] = {}
        for name in names:
            obs = [b[name] for b in branches if name in b]
            pending = [o for o in obs if o.state == _PENDING]
            out[name] = dataclasses.replace(pending[0] if pending else obs[0])
        dst.clear()
        dst.update(out)

    # -- expression-level processing -----------------------------------

    def _process_expr(
        self,
        expr: ast.expr,
        env: tp.Dict[str, _Ob],
        in_test: bool,
        binds: bool = False,
    ) -> None:
        """Handle acquires and tracked-name uses inside one expression.

        `in_test` — condition position: uses refine, never transfer.
        `binds` — the expression's value is stored/returned: plain uses
        transfer ownership instead of being neutral reads.
        """
        consume_args: tp.Set[int] = set()
        transfer_args: tp.Set[int] = set()
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if self._is_consume(node):
                for sub in node.args:
                    for n2 in ast.walk(sub):
                        consume_args.add(id(n2))
            elif self._is_transfer_call(node):
                for sub in node.args:
                    for n2 in ast.walk(sub):
                        transfer_args.add(id(n2))
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            kind = self._acquire_kind(node)
            if kind is None:
                continue
            if id(node) in consume_args or id(node) in transfer_args:
                continue  # free(release(...)) — acquired and retired inline
            if binds:
                continue  # bound into a larger value: ownership escapes
            self._emit(
                node.lineno,
                node.col_offset,
                f"result of .{kind}() is discarded — the returned pages/refs "
                "can never reach a release funnel",
            )
        for node in ast.walk(expr):
            if not isinstance(node, ast.Name) or node.id not in env:
                continue
            ob = env[node.id]
            if id(node) in consume_args:
                if ob.state == _RELEASED:
                    self._emit(
                        node.lineno,
                        node.col_offset,
                        f"`{node.id}` released again — pages from line "
                        f"{ob.line} already reached a release funnel",
                    )
                ob.state = _RELEASED
            elif id(node) in transfer_args:
                if ob.state == _PENDING:
                    ob.state = _TRANSFERRED
            elif in_test:
                pass  # condition reads refine (see _refiners), never move
            elif ob.state == _PENDING:
                ob.state = _TRANSFERRED

    def _refiners(
        self, test: ast.expr
    ) -> tp.Tuple[tp.Callable[[tp.Dict[str, _Ob]], None], tp.Callable[[tp.Dict[str, _Ob]], None]]:
        """Falsy-acquisition refinement: alloc may return None, match/evict
        may return an empty set — the falsy branch carries no obligation."""

        def clear(name: str) -> tp.Callable[[tp.Dict[str, _Ob]], None]:
            return lambda env: env.pop(name, None)

        def keep(env: tp.Dict[str, _Ob]) -> None:
            return None

        root = self._test_root(test)
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left_root = self._test_root(test.left)
            is_none = (
                isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None
            )
            if left_root and is_none:
                if isinstance(test.ops[0], ast.Is):
                    return clear(left_root), keep
                if isinstance(test.ops[0], ast.IsNot):
                    return keep, clear(left_root)
            return keep, keep
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._test_root(test.operand)
            if inner:
                return clear(inner), keep
            return keep, keep
        if root:
            return keep, clear(root)
        return keep, keep

    @staticmethod
    def _test_root(node: ast.expr) -> tp.Optional[str]:
        parts = _chain(node)
        return parts[0] if parts else None


def _rule_gc009(path: str, tree: ast.Module) -> tp.Iterator[Finding]:
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings: tp.List[Finding] = []
            _PageWalker(path, fn, findings).run()
            yield from findings
    yield from _refs_protocol(path, tree)


def _refs_protocol(path: str, tree: ast.Module) -> tp.Iterator[Finding]:
    """The trie refcount protocol: `.refs` is mutated ONLY inside the trie
    module, and every decrement carries an adjacent underflow guard."""
    owning = os.path.basename(path) == "prefix_cache.py"
    for node in ast.walk(tree):
        blocks: tp.List[tp.List[ast.stmt]] = []
        for field in ("body", "orelse", "finalbody"):
            b = getattr(node, field, None)
            if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
                blocks.append(b)
        for block in blocks:
            for i, st in enumerate(block):
                tgt = None
                if isinstance(st, (ast.Assign, ast.AugAssign)):
                    targets = st.targets if isinstance(st, ast.Assign) else [st.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute) and t.attr == "refs":
                            tgt = t
                if tgt is None:
                    continue
                if not owning:
                    yield Finding(
                        "GC009",
                        path,
                        st.lineno,
                        st.col_offset,
                        "`.refs` mutated outside the trie module — refcount "
                        "conservation is prefix_cache.py-internal protocol",
                    )
                    continue
                if isinstance(st, ast.AugAssign) and isinstance(st.op, ast.Sub):
                    nxt = block[i + 1] if i + 1 < len(block) else None
                    guarded = isinstance(nxt, ast.Assert) and any(
                        isinstance(n, ast.Attribute) and n.attr == "refs"
                        for n in ast.walk(nxt.test)
                    )
                    if not guarded:
                        yield Finding(
                            "GC009",
                            path,
                            st.lineno,
                            st.col_offset,
                            "`.refs -=` without an adjacent underflow guard "
                            "(assert ... refs >= 0) — a silent negative "
                            "refcount unbalances the trie",
                        )


# ----------------------------------------------------------------------
# GC010 — async discipline around the driver loop
# ----------------------------------------------------------------------

_MUT_LEAVES = {
    "append",
    "appendleft",
    "extend",
    "insert",
    "add",
    "pop",
    "popleft",
    "remove",
    "discard",
    "clear",
    "update",
    "setdefault",
}


def _self_mutations(st: ast.stmt) -> tp.Set[str]:
    """First-level `self` attributes this statement mutates."""
    out: tp.Set[str] = set()
    for node in _own_nodes_stmt(st):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _self_attr_root(t)
                if attr:
                    out.add(attr)
        elif isinstance(node, ast.Call):
            parts = _chain(node.func)
            if len(parts) >= 3 and parts[0] == "self" and parts[-1] in _MUT_LEAVES:
                out.add(parts[1])
    return out


def _self_attr_root(target: ast.expr) -> tp.Optional[str]:
    """'x' for self.x..., self.x[...] = ... store targets."""
    node = target
    if isinstance(node, ast.Subscript):
        node = node.value
    parts = _chain(node)
    if len(parts) >= 2 and parts[0] == "self":
        return parts[1]
    return None


def _own_nodes_stmt(st: ast.stmt) -> tp.Iterator[ast.AST]:
    yield st
    yield from _own_nodes(st)


def _has_await(st: ast.stmt) -> bool:
    return any(isinstance(n, ast.Await) for n in _own_nodes_stmt(st))


def _rule_gc010(path: str, tree: ast.Module) -> tp.Iterator[Finding]:
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # A: direct engine access from the event-loop context. The engine
        # is stepped on a worker thread; only queued commands (nested defs
        # and lambdas — excluded from _own_nodes — drained by the driver)
        # may call into it.
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                parts = _chain(node.func)
                if len(parts) >= 3 and "engine" in parts[1:-1] or (
                    len(parts) >= 2 and parts[0] == "engine"
                ):
                    yield Finding(
                        "GC010",
                        path,
                        node.lineno,
                        node.col_offset,
                        f"direct engine call `{'.'.join(parts)}` inside "
                        f"`async def {fn.name}` — engine state is driver-"
                        "loop-only; route through the command queue "
                        "(_call / to_thread boundary)",
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    parts = _chain(t if not isinstance(t, ast.Subscript) else t.value)
                    if "engine" in parts[:-1]:
                        yield Finding(
                            "GC010",
                            path,
                            node.lineno,
                            node.col_offset,
                            f"store to `{'.'.join(parts)}` inside "
                            f"`async def {fn.name}` — engine state is "
                            "driver-loop-only; route through the command "
                            "queue",
                        )
        # B: await interleaved inside a mutation-in-progress region — two
        # mutations of the same self attribute in one block with an await
        # between them hand the half-updated state to other coroutines.
        yield from _await_mid_mutation(path, fn)


def _await_mid_mutation(path: str, fn: ast.AsyncFunctionDef) -> tp.Iterator[Finding]:
    blocks: tp.List[tp.List[ast.stmt]] = []
    stack: tp.List[ast.AST] = [fn]
    while stack:
        node = stack.pop()
        for field in ("body", "orelse", "finalbody"):
            b = getattr(node, field, None)
            if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
                blocks.append(b)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, _NESTED_SCOPES):
                stack.append(child)
        if isinstance(node, ast.Try):
            stack.extend(h for h in node.handlers)
    for block in blocks:
        muts = [(_self_mutations(st), _has_await(st), st) for st in block]
        attrs: tp.Set[str] = set()
        for m, _, _ in muts:
            attrs.update(m)
        for attr in sorted(attrs):
            idx = [i for i, (m, _, _) in enumerate(muts) if attr in m]
            if len(idx) < 2:
                continue
            for j in range(idx[0] + 1, idx[-1]):
                if j in idx:
                    continue
                if muts[j][1]:
                    st = muts[j][2]
                    yield Finding(
                        "GC010",
                        path,
                        st.lineno,
                        st.col_offset,
                        f"`await` between two mutations of `self.{attr}` "
                        "in one block — another coroutine can observe the "
                        "mutation-in-progress state",
                    )


# ----------------------------------------------------------------------
# GC011 — bounded static jit-argument domains
# ----------------------------------------------------------------------


@dataclasses.dataclass
class _JitInfo:
    name: str
    path: str
    fn: _FuncDef
    statics: tp.Tuple[int, ...]


class _ModuleInfo:
    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.parents: tp.Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.defs_by_name: tp.Dict[str, tp.List[_FuncDef]] = {}
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(n.name, []).append(n)
        # module-level constants (Name = <expr> at module scope)
        self.module_assigns: tp.Dict[str, tp.List[ast.expr]] = {}
        for st in tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                if isinstance(t, ast.Name):
                    self.module_assigns.setdefault(t.id, []).append(st.value)

    def enclosing_function(self, node: ast.AST) -> tp.Optional[_FuncDef]:
        cur: tp.Optional[ast.AST] = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST) -> tp.Optional[ast.ClassDef]:
        cur: tp.Optional[ast.AST] = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


class _Index:
    """Cross-module (bare-name) index for the GC011 boundedness prover."""

    def __init__(self, modules: tp.List[_ModuleInfo]):
        self.modules = modules
        self.jits: tp.Dict[str, _JitInfo] = {}
        self.callsites: tp.Dict[
            str, tp.List[tp.Tuple[_ModuleInfo, ast.Call]]
        ] = {}
        for mod in modules:
            self._index_jits(mod)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    name = _call_name(node)
                    if name:
                        leaf = name.split(".")[-1]
                        self.callsites.setdefault(leaf, []).append((mod, node))

    @staticmethod
    def _statics_from_call(call: ast.Call) -> tp.Tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(
                        e.value
                        for e in v.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, int)
                    )
        return ()

    def _index_jits(self, mod: _ModuleInfo) -> None:
        for defs in mod.defs_by_name.values():
            for d in defs:
                for deco in d.decorator_list:
                    if not isinstance(deco, ast.Call):
                        continue
                    inner = _partial_of(deco)
                    is_jit = _is_jax_jit(deco.func) or (
                        inner is not None and _is_jax_jit(inner)
                    )
                    statics = self._statics_from_call(deco)
                    if is_jit and statics:
                        self.jits[d.name] = _JitInfo(d.name, mod.path, d, statics)
        # name = jax.jit(fn, static_argnums=...) rebinding
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            call = node.value
            if not _is_jax_jit(call.func) or not call.args:
                continue
            statics = self._statics_from_call(call)
            target = _unwrap_callable(call.args[0])
            if statics and target:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        leaf = target.split(".")[-1]
                        for d in mod.defs_by_name.get(leaf, []):
                            self.jits[t.id] = _JitInfo(
                                t.id, mod.path, d, statics
                            )


_BOUNDED_CALL_MARKERS = ("bucket", "clamp")
_MAX_DEPTH = 6


class _BoundProver:
    """Proves a call-site expression draws from a finite domain."""

    def __init__(self, index: _Index):
        self.index = index

    def bounded(
        self,
        expr: ast.expr,
        mod: _ModuleInfo,
        fn: tp.Optional[_FuncDef],
        depth: int = 0,
        seen: tp.Optional[tp.Set[tp.Tuple]] = None,
    ) -> bool:
        seen = seen if seen is not None else set()
        if depth > _MAX_DEPTH:
            return True  # deep chains: give up optimistically (lint, not proof)
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self.bounded(e, mod, fn, depth + 1, seen) for e in expr.elts)
        if isinstance(expr, ast.Compare):
            return True  # bool domain
        if isinstance(expr, ast.BoolOp):
            return all(
                self.bounded(v, mod, fn, depth + 1, seen) for v in expr.values
            )
        if isinstance(expr, ast.UnaryOp):
            return self.bounded(expr.operand, mod, fn, depth + 1, seen)
        if isinstance(expr, ast.BinOp):
            return self.bounded(
                expr.left, mod, fn, depth + 1, seen
            ) and self.bounded(expr.right, mod, fn, depth + 1, seen)
        if isinstance(expr, ast.IfExp):
            return self.bounded(
                expr.body, mod, fn, depth + 1, seen
            ) and self.bounded(expr.orelse, mod, fn, depth + 1, seen)
        if isinstance(expr, ast.Call):
            return self._bounded_call(expr, mod, fn, depth, seen)
        if isinstance(expr, ast.Attribute):
            return self._bounded_attr(expr, mod, fn, depth, seen)
        if isinstance(expr, ast.Name):
            return self._bounded_name(expr.id, mod, fn, depth, seen)
        return False

    def _bounded_call(
        self,
        call: ast.Call,
        mod: _ModuleInfo,
        fn: tp.Optional[_FuncDef],
        depth: int,
        seen: tp.Set[tp.Tuple],
    ) -> bool:
        name = _call_name(call)
        leaf = name.split(".")[-1] if name else ""
        if leaf == "bit_length":
            return True  # 1 << (x.bit_length() - 1): the pow2 ladder idiom
        if leaf.startswith("normalize") or any(
            m in leaf for m in _BOUNDED_CALL_MARKERS
        ):
            return True  # by convention: normalizers/buckets clamp to a menu
        if leaf in ("min", "max"):
            return any(
                self.bounded(a, mod, fn, depth + 1, seen) for a in call.args
            )
        # same-module def: bounded iff every return expression is bounded
        key = ("ret", mod.path, leaf)
        if key in seen:
            return True
        candidates = mod.defs_by_name.get(leaf, [])
        if candidates:
            seen.add(key)
            for d in candidates:
                for node in _own_nodes(d):
                    if isinstance(node, ast.Return) and node.value is not None:
                        if not self.bounded(node.value, mod, d, depth + 1, seen):
                            return False
            return True
        return False

    def _bounded_attr(
        self,
        expr: ast.Attribute,
        mod: _ModuleInfo,
        fn: tp.Optional[_FuncDef],
        depth: int,
        seen: tp.Set[tp.Tuple],
    ) -> bool:
        parts = _chain(expr)
        if not parts:
            return False
        if parts[0] == "self" and len(parts) >= 2 and fn is not None:
            return self._init_frozen(parts[1], mod, fn)
        # non-self root: an attribute of a bounded-identity object is drawn
        # from a finite per-object set
        return self._bounded_name(parts[0], mod, fn, depth + 1, seen)

    def _init_frozen(self, attr: str, mod: _ModuleInfo, fn: _FuncDef) -> bool:
        """self.<attr> is bounded when every store in the class happens in
        __init__ — the value is fixed per live instance."""
        cls = mod.enclosing_class(fn)
        if cls is None:
            return False
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                t2 = t.value if isinstance(t, ast.Subscript) else t
                p = _chain(t2)
                if len(p) >= 2 and p[0] == "self" and p[1] == attr:
                    owner = mod.enclosing_function(node)
                    if owner is None or owner.name != "__init__":
                        return False
        return True

    def _bounded_name(
        self,
        name: str,
        mod: _ModuleInfo,
        fn: tp.Optional[_FuncDef],
        depth: int,
        seen: tp.Set[tp.Tuple],
    ) -> bool:
        # resolve through the lexical scope chain: the function itself,
        # then enclosing functions (closure variables), then module scope
        scope = fn
        while scope is not None:
            key = ("name", mod.path, scope.name, name)
            if key in seen:
                return True  # self-referential clamp chains: bounded iff base
            assigns: tp.List[ast.expr] = []
            is_loop_target = False
            loop_iters: tp.List[ast.expr] = []
            for node in _own_nodes(scope):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            assigns.append(node.value)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            # element-wise unpack: a, b = x, y
                            for j, e in enumerate(t.elts):
                                if not (isinstance(e, ast.Name) and e.id == name):
                                    continue
                                v = node.value
                                if isinstance(v, (ast.Tuple, ast.List)) and len(
                                    v.elts
                                ) == len(t.elts):
                                    assigns.append(v.elts[j])
                                else:
                                    assigns.append(v)  # opaque unpack source
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    if (
                        isinstance(node.target, ast.Name)
                        and node.target.id == name
                        and getattr(node, "value", None) is not None
                    ):
                        assigns.append(node.value)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name) and t.id == name:
                            is_loop_target = True
                            loop_iters.append(node.iter)
            if assigns or is_loop_target:
                seen.add(key)
                ok = all(
                    self.bounded(a, mod, scope, depth + 1, seen)
                    for a in assigns
                )
                ok = ok and all(
                    isinstance(it, (ast.Tuple, ast.List))
                    and all(isinstance(e, ast.Constant) for e in it.elts)
                    for it in loop_iters
                )
                return ok
            params = [a.arg for a in scope.args.args + scope.args.kwonlyargs]
            if name in params:
                return self._bounded_param(name, mod, scope, depth, seen)
            scope = mod.enclosing_function(scope)
        if name in mod.module_assigns:
            key = ("mod", mod.path, name)
            if key in seen:
                return True
            seen.add(key)
            return all(
                self.bounded(a, mod, None, depth + 1, seen)
                for a in mod.module_assigns[name]
            )
        return False

    def _bounded_param(
        self,
        name: str,
        mod: _ModuleInfo,
        fn: _FuncDef,
        depth: int,
        seen: tp.Set[tp.Tuple],
    ) -> bool:
        """A parameter is bounded when EVERY in-repo call site passes a
        bounded value (interprocedural, by bare callee name)."""
        key = ("param", mod.path, fn.name, name)
        if key in seen:
            return True
        seen.add(key)
        pos_params = [a.arg for a in fn.args.args]
        offset = 1 if pos_params and pos_params[0] in ("self", "cls") else 0
        try:
            pidx = pos_params.index(name)
        except ValueError:
            pidx = None
        defaults = fn.args.defaults
        default_expr: tp.Optional[ast.expr] = None
        if pidx is not None and defaults:
            d0 = len(pos_params) - len(defaults)
            if pidx >= d0:
                default_expr = defaults[pidx - d0]
        for kwp, kwd in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if kwp.arg == name and kwd is not None:
                default_expr = kwd
        sites = self.index.callsites.get(fn.name, [])
        if not sites:
            return False  # callers unknown: the domain cannot be proven
        for smod, call in sites:
            arg_expr: tp.Optional[ast.expr] = None
            if pidx is not None:
                # instance-method call sites (obj.meth(...)) bind `self`
                # implicitly, shifting positional args left by one
                ai = pidx - (offset if isinstance(call.func, ast.Attribute) else 0)
                if 0 <= ai < len(call.args):
                    arg_expr = call.args[ai]
            if arg_expr is None:
                for kw in call.keywords:
                    if kw.arg == name:
                        arg_expr = kw.value
            if arg_expr is None:
                if default_expr is None:
                    continue  # not passed, no default: not this overload
                arg_expr = default_expr
                if isinstance(arg_expr, ast.Constant):
                    continue
            caller_fn = smod.enclosing_function(call)
            if not self.bounded(arg_expr, smod, caller_fn, depth + 1, seen):
                return False
        return True


def _rule_gc011(
    mod: _ModuleInfo, index: _Index
) -> tp.Iterator[Finding]:
    prover = _BoundProver(index)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
            continue
        info = index.jits.get(node.func.id)
        if info is None or mod.enclosing_function(node) is info.fn:
            continue
        params = [a.arg for a in info.fn.args.args]
        caller = mod.enclosing_function(node)
        for i in info.statics:
            arg_expr: tp.Optional[ast.expr] = None
            if i < len(node.args):
                arg_expr = node.args[i]
            elif i < len(params):
                for kw in node.keywords:
                    if kw.arg == params[i]:
                        arg_expr = kw.value
            if arg_expr is None:
                continue  # defaulted: the def's literal default is bounded
            if prover.bounded(arg_expr, mod, caller):
                continue
            pname = params[i] if i < len(params) else str(i)
            yield Finding(
                "GC011",
                mod.path,
                arg_expr.lineno,
                arg_expr.col_offset,
                f"static arg {i} (`{pname}`) of `{info.name}` takes a value "
                "not provably drawn from a finite domain — every distinct "
                "value compiles a new program; clamp through a normalizer/"
                "bucket or a literal menu",
            )


# ----------------------------------------------------------------------
# driver — mirrors lint_source / lint_paths
# ----------------------------------------------------------------------


def lifecycle_source(
    source: str,
    path: str = "<string>",
    rules: tp.Optional[tp.Iterable[str]] = None,
    index: tp.Optional[_Index] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding]]:
    """Run pass 3 on one module's source. Returns (active, suppressed).

    Without `index`, a single-module index is built (fixtures, ad-hoc
    runs); lifecycle_paths supplies the cross-module one. Syntax errors
    yield nothing — pass 1 already reports GC000 for the same file."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return [], []
    wanted = set(rules) if rules is not None else set(LIFECYCLE_RULES)
    mod = _ModuleInfo(path, tree)
    if index is None:
        index = _Index([mod])
    findings: tp.List[Finding] = []
    if "GC009" in wanted:
        findings.extend(_rule_gc009(path, tree))
    if "GC010" in wanted:
        findings.extend(_rule_gc010(path, tree))
    if "GC011" in wanted:
        findings.extend(_rule_gc011(mod, index))
    suppress_at: tp.Dict[int, tp.Set[str]] = {}
    for s in parse_suppressions(source):
        suppress_at.setdefault(s.line, set()).update(s.rules)
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    for f in findings:
        if f.rule not in wanted:
            continue
        if f.rule in suppress_at.get(f.line, ()):
            suppressed.append(f)
        else:
            active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return active, suppressed


def lifecycle_paths(
    paths: tp.Sequence[str],
    rules: tp.Optional[tp.Iterable[str]] = None,
) -> tp.Tuple[tp.List[Finding], tp.List[Finding], int]:
    """Run pass 3 over files/trees with a shared cross-module index."""
    sources: tp.List[tp.Tuple[str, str]] = []
    modules: tp.List[_ModuleInfo] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        sources.append((path, src))
        try:
            modules.append(_ModuleInfo(path, ast.parse(src)))
        except SyntaxError:
            pass
    index = _Index(modules)
    active: tp.List[Finding] = []
    suppressed: tp.List[Finding] = []
    for path, src in sources:
        a, s = lifecycle_source(src, path, rules, index)
        active.extend(a)
        suppressed.extend(s)
    return active, suppressed, len(sources)
