"""Committed jit-surface manifest: a static census of every jit wrapper.

Each jit wrapper in the tree is one compile surface: its `static_argnums`/
`static_argnames` multiply compiled-program count by the static domain size,
and its `donate_argnums` are load-bearing aliasing contracts (GC004). Today
that surface only grows by diff review luck; this module makes it a reviewed
artifact the way findings already are — `python -m midgpt_tpu.analysis
--fail-on-new` diffs the live census against the committed
`jit_surface_baseline.json`, so a new jit wrapper, a widened static-arg set,
or a regressed GC011 boundedness verdict fails CI until the baseline is
deliberately updated (`--update-baseline`).

Census entries are keyed (module path, wrapper name) — line-number-free like
the findings baseline, so pure code motion never churns the manifest. Three
wrapper forms are recognized, mirroring pass 1/3's scope model:

  decorator  `@jax.jit` / `@jax.jit(...)` / `@functools.partial(jax.jit, …)`
  rebinding  `name = jax.jit(fn, ...)` (any scope; `name` is the key)
  inline     any other `jax.jit(...)` call, e.g. immediately invoked —
             keyed `<inline:lambda#0>` with a per-module occurrence counter

Per static argument the manifest records the GC011 domain verdict, computed
with pass 3's cross-module `_BoundProver`: "bounded" (every bare-name
callsite's value provably draws from a finite domain), "unproven" (at least
one callsite the prover cannot bound — including GC011-suppressed sites:
the suppression silences the finding, not the census), or "uncalled" (no
bare-name callsite in the scanned tree). JAX-free, like every pass.
"""

from __future__ import annotations

import ast
import json
import os
import typing as tp

from .lifecycle import _BoundProver, _Index, _ModuleInfo
from .lint import (
    _FuncDef,
    _call_name,
    _is_jax_jit,
    _partial_of,
    _unwrap_callable,
    iter_python_files,
)

JIT_SURFACE_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "jit_surface_baseline.json"
)


def _int_tuple(v: ast.AST) -> tp.Tuple[int, ...]:
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in v.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def _str_tuple(v: ast.AST) -> tp.Tuple[str, ...]:
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in v.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _wrapper_opts(call: tp.Optional[ast.Call]) -> tp.Dict[str, tp.Tuple]:
    """static/donate options off the jit (or partial-of-jit) call."""
    out: tp.Dict[str, tp.Tuple] = {
        "static_argnums": (),
        "static_argnames": (),
        "donate_argnums": (),
    }
    if call is None:
        return out
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            out["static_argnums"] = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            out["static_argnames"] = _str_tuple(kw.value)
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            out["donate_argnums"] = _int_tuple(kw.value)
    return out


def _jit_decorator_call(deco: ast.AST) -> tp.Optional[tp.Tuple[bool, tp.Optional[ast.Call]]]:
    """(is_jit, options-bearing call) for one decorator expression."""
    if _is_jax_jit(deco):
        return True, None  # bare @jax.jit
    if isinstance(deco, ast.Call):
        inner = _partial_of(deco)
        if inner is not None and _is_jax_jit(inner):
            return True, deco  # @functools.partial(jax.jit, ...)
        if _is_jax_jit(deco.func):
            return True, deco  # @jax.jit(...)
    return None


def _static_indices(
    opts: tp.Dict[str, tp.Tuple], fn: tp.Optional[_FuncDef]
) -> tp.List[tp.Tuple[int, str]]:
    """(positional index, display name) per static argument."""
    params = [a.arg for a in fn.args.args] if fn is not None else []
    out: tp.List[tp.Tuple[int, str]] = []
    for i in opts["static_argnums"]:
        name = params[i] if i < len(params) else str(i)
        out.append((i, name))
    for pname in opts["static_argnames"]:
        if pname in params:
            out.append((params.index(pname), pname))
    return out


def _verdicts(
    wrapper_name: str,
    fn: tp.Optional[_FuncDef],
    opts: tp.Dict[str, tp.Tuple],
    modules: tp.List[_ModuleInfo],
    prover: _BoundProver,
) -> tp.Dict[str, str]:
    """GC011 boundedness verdict per static arg, across all modules'
    bare-name callsites of the wrapper."""
    statics = _static_indices(opts, fn)
    if not statics:
        return {}
    verdicts: tp.Dict[str, str] = {}
    params = [a.arg for a in fn.args.args] if fn is not None else []
    for i, display in statics:
        n_sites = 0
        all_bounded = True
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == wrapper_name
                ):
                    continue
                if fn is not None and mod.enclosing_function(node) is fn:
                    continue  # recursion, not a callsite
                n_sites += 1
                arg_expr: tp.Optional[ast.expr] = None
                if i < len(node.args):
                    arg_expr = node.args[i]
                elif i < len(params):
                    for kw in node.keywords:
                        if kw.arg == params[i]:
                            arg_expr = kw.value
                if arg_expr is None:
                    continue  # defaulted: the literal default is bounded
                if not prover.bounded(arg_expr, mod, mod.enclosing_function(node)):
                    all_bounded = False
        if n_sites == 0:
            verdicts[display] = "uncalled"
        else:
            verdicts[display] = "bounded" if all_bounded else "unproven"
    return verdicts


def jit_surface(
    paths: tp.Sequence[str], rel_to: tp.Optional[str] = None
) -> tp.List[tp.Dict[str, tp.Any]]:
    """Static census of every jit wrapper under `paths`, sorted by
    (path, name). `rel_to` relativizes entry paths (the repo root in CLI
    use) so the committed baseline is machine-independent."""
    sources: tp.List[tp.Tuple[str, str]] = []
    modules: tp.List[_ModuleInfo] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules.append(_ModuleInfo(path, ast.parse(src)))
            sources.append((path, src))
        except SyntaxError:
            continue  # pass 1 reports GC000 for this file
    prover = _BoundProver(_Index(modules))

    entries: tp.List[tp.Dict[str, tp.Any]] = []

    def rel(path: str) -> str:
        if rel_to:
            try:
                return os.path.relpath(path, rel_to).replace(os.sep, "/")
            except ValueError:
                pass
        return path.replace(os.sep, "/")

    def add(
        mod: _ModuleInfo,
        name: str,
        form: str,
        opts: tp.Dict[str, tp.Tuple],
        fn: tp.Optional[_FuncDef],
    ) -> None:
        entries.append(
            {
                "path": rel(mod.path),
                "name": name,
                "form": form,
                "static_argnums": sorted(opts["static_argnums"]),
                "static_argnames": sorted(opts["static_argnames"]),
                "donate_argnums": sorted(opts["donate_argnums"]),
                "static_verdicts": _verdicts(name, fn, opts, modules, prover),
            }
        )

    for mod in modules:
        consumed: tp.Set[ast.Call] = set()
        # 1) decorator form
        for defs in mod.defs_by_name.values():
            for d in defs:
                for deco in d.decorator_list:
                    hit = _jit_decorator_call(deco)
                    if hit is None:
                        continue
                    _is_jit, opt_call = hit
                    if isinstance(opt_call, ast.Call):
                        consumed.add(opt_call)
                    cls = mod.enclosing_class(d)
                    name = f"{cls.name}.{d.name}" if cls is not None else d.name
                    add(mod, name, "decorator", _wrapper_opts(opt_call), d)
        # 2) `name = jax.jit(fn, ...)` rebinding (any scope)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_jax_jit(node.value.func)
                and node.value.args
            ):
                continue
            call = node.value
            consumed.add(call)
            target_names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not target_names:
                target_names = ["<unnamed>"]
            wrapped = _unwrap_callable(call.args[0])
            fn: tp.Optional[_FuncDef] = None
            if wrapped:
                defs = mod.defs_by_name.get(wrapped.split(".")[-1], [])
                fn = defs[0] if defs else None
            for tname in target_names:
                add(mod, tname, "rebinding", _wrapper_opts(call), fn)
        # 3) every other jit call: inline, keyed by occurrence order
        counter = 0
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and _is_jax_jit(node.func)
                and node not in consumed
            ):
                continue
            wrapped_leaf = "lambda"
            if node.args and not isinstance(node.args[0], ast.Lambda):
                wrapped = _unwrap_callable(node.args[0])
                if wrapped:
                    wrapped_leaf = wrapped.split(".")[-1]
            add(
                mod,
                f"<inline:{wrapped_leaf}#{counter}>",
                "inline",
                _wrapper_opts(node),
                None,
            )
            counter += 1

    entries.sort(key=lambda e: (e["path"], e["name"]))
    # duplicate (path, name) keys — e.g. two same-named defs — get a
    # stable ordinal suffix so the baseline diff stays keyable
    seen: tp.Dict[tp.Tuple[str, str], int] = {}
    for e in entries:
        key = (e["path"], e["name"])
        n = seen.get(key, 0)
        seen[key] = n + 1
        if n:
            e["name"] = f"{e['name']}#{n + 1}"
    return entries


def load_baseline(path: str = JIT_SURFACE_BASELINE_PATH) -> tp.List[tp.Dict]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def save_baseline(
    entries: tp.List[tp.Dict], path: str = JIT_SURFACE_BASELINE_PATH
) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, indent=1, sort_keys=True)
        fh.write("\n")


def diff_surface(
    current: tp.List[tp.Dict], baseline: tp.List[tp.Dict]
) -> tp.List[str]:
    """Human-readable problems: wrappers that are new or changed relative
    to the committed baseline. Removals are allowed (shrinking the compile
    surface needs no ceremony); `--update-baseline` re-pins them away."""
    base = {(e["path"], e["name"]): e for e in baseline}
    problems: tp.List[str] = []
    for e in current:
        key = (e["path"], e["name"])
        pinned = base.get(key)
        if pinned is None:
            problems.append(
                f"new jit wrapper `{e['name']}` in {e['path']} "
                "(not in jit_surface_baseline.json — review, then "
                "--update-baseline)"
            )
            continue
        for field in (
            "form",
            "static_argnums",
            "static_argnames",
            "donate_argnums",
            "static_verdicts",
        ):
            if e.get(field) != pinned.get(field):
                problems.append(
                    f"jit wrapper `{e['name']}` in {e['path']} changed "
                    f"{field}: baseline {pinned.get(field)!r} -> "
                    f"current {e.get(field)!r}"
                )
    return problems
