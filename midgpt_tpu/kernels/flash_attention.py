"""Pallas TPU flash attention (causal, FlashAttention-2 style) with custom VJP.

Replaces the reference's materialized T×T attention (reference model.py:71-77)
— the O(T²) memory wall that caps its context at 1024 — with tiled
online-softmax kernels:

  * forward: grid (B*H, n_q, n_k), KV innermost. TPU grid steps execute
    sequentially over the minor dimension, so the (m, l, acc) running
    statistics live in VMEM scratch across the KV sweep of each Q tile.
    Blocks strictly above the causal diagonal are predicated off with
    pl.when; diagonal-straddling blocks are masked elementwise; fully-valid
    blocks skip the mask entirely (the common case at long T).
  * backward: two kernels — dQ (grid over KV for each Q tile) and dK/dV
    (grid over Q for each KV tile) — recomputing p = exp(s - lse) from the
    saved log-sum-exp rather than storing T×T probabilities. The
    delta = rowsum(dO ⊙ O) softmax-jacobian correction is computed in-kernel
    from the O / dO tiles already in VMEM: no separate delta pass and no
    broadcast side buffers.
  * lse is stored 8 lanes wide (f32), not broadcast to a 128-lane buffer —
    16x less statistics traffic than a full-tile store.

Numerics match the reference semantics: QK^T and PV matmuls run on the MXU
in the input dtype (bf16) with float32 accumulation (preferred_element_type),
the softmax/statistics are float32, and the 1/sqrt(C) scale is applied to the
f32 scores exactly as reference model.py:76 does. Masking uses large-negative
finite values (not -inf): the running max starts at M_INIT > MASK, so
exp(MASK - m) underflows to exactly 0 and no NaN-scrubbing selects are needed
in the hot loop.

On non-TPU backends the kernels run in Pallas interpret mode (tests);
numerical parity against the naive path is asserted in tests/test_flash.py.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 names the Mosaic params class TPUCompilerParams; same fields
    # (midgpt_tpu.utils.compat documents the shim policy).
    pltpu.CompilerParams = pltpu.TPUCompilerParams

# Finite stand-ins for -inf (see module docstring), re-exported from the
# canonical home of the shared online-softmax math. Kept as module names
# because the kernel-template/decode/ring modules import them from here
# historically and the backward kernels below use them directly.
from midgpt_tpu.ops.online_softmax import (  # noqa: E402
    M_INIT,
    MASK,
    finalize,
    online_block,
)
# lane width of the statistics outputs/scratch (min useful; padded to a
# 128-lane tile in VMEM but only these lanes are stored in HBM)
_STATS_LANES = 8

# Grid semantics: batch*heads and Q tiles are independent ("parallel");
# the KV/Q sweep of the reduction is the sequential dimension ("arbitrary").
# Lets Mosaic pipeline/parallelize grid steps instead of running them serially.
_COMPILER_PARAMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)

# Run the kernels in interpret mode off-TPU (tests set this; the normal
# dispatcher in ops/attention.py falls back to blockwise instead, because
# interpret mode is orders of magnitude slower than compiled jnp).
RUN_INTERPRET_OFF_TPU = False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(T: int, block_q: int, block_k: int) -> tp.Tuple[int, int]:
    """Clamp requested block sizes to ones that tile T exactly.

    Requested blocks are honored when they divide T; otherwise the KV block
    widens to the full sequence and the Q block falls back to the KV block
    (the dispatcher-side policy, ops.attention.flash_block_sizes, differs:
    it always picks bq=min(512, bk) and is only reached when the block
    divides T). Deterministic in (T, block_q, block_k), so the forward and
    backward passes of the custom VJP always agree. Widened blocks are
    bounded by the f32 score-tile budget (bq*bk <= 1M elements = 4 MB, the
    size the fused T=1024 backward already proves fits the ~16 MB scoped
    VMEM alongside its operand tiles): past that, an explicit error beats a
    Mosaic compile failure — long indivisible sequences belong on the
    blockwise path."""
    bq = min(block_q, T)
    bk = min(block_k, T)
    if T % bk:
        bk = T
    if T % bq:
        bq = bk
    if bq * bk > 1024 * 1024:
        raise ValueError(
            f"blocks ({bq}, {bk}) for seq len {T} need a {bq}x{bk} f32 "
            "score tile that cannot fit VMEM; pass block sizes that divide "
            "T (or use the blockwise path)"
        )
    return bq, bk


def _masked(s: Array, iq, ik, block_q: int, block_k: int) -> Array:
    """Apply the causal mask elementwise (straight-line select — a lax.cond
    that skips it on fully-valid blocks measured slower end-to-end: Mosaic
    pipelines the unconditional kernel body better than the branchy one)."""
    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(row >= col, s, MASK)


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel_single(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_q, block_k, causal):
    """Specialization for n_k == 1 (block_k covers the whole sequence): the
    softmax over each row is complete in one visit, so the online-softmax
    running statistics — scratch init, alpha rescale, m/l carry, separate
    finalize — all vanish. This is the hot configuration for T <= block_k.

    causal=False computes full (unmasked) attention — the off-diagonal
    pair case of ring attention, where the causal structure is decided per
    K/V shard at the ring level, not per element."""
    iq = pl.program_id(1)
    q = q_ref[0]  # (block_q, C)
    k = k_ref[0]  # (block_k, C)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (block_q, block_k) f32
    if causal:
        s = _masked(s, iq, 0, block_q, block_k)
    # One online_block step from the empty state IS the direct softmax:
    # alpha underflows to 0, l = sum(p), and every row has >= 1 valid key
    # so finalize's safe_l/lse guards are bitwise no-ops (l >= 1).
    m, _, p, l = online_block(
        jnp.full(s.shape[:-1], M_INIT, jnp.float32),
        jnp.zeros(s.shape[:-1], jnp.float32),
        s,
    )
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out, lse = finalize(m, l, pv, dtype=o_ref.dtype)
    o_ref[0] = out
    lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc, *, scale, block_q, block_k, causal):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, M_INIT)
        l_sc[:] = jnp.zeros_like(l_sc)

    def _compute():
        q = q_ref[0]  # (block_q, C)
        k = k_ref[0]  # (block_k, C)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k) f32
        if causal:
            s = _masked(s, iq, ik, block_q, block_k)

        # shared online-softmax update (ops/online_softmax.online_block):
        # alpha underflows to 0 at first visit, masked entries' p to 0
        m_new, alpha, p, l_new = online_block(m_sc[:, 0], l_sc[:, 0], s)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    if causal:
        # causal: KV block strictly above the diagonal contributes nothing
        pl.when(ik * block_k <= iq * block_q + (block_q - 1))(_compute)
    else:
        _compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        out, lse = finalize(m_sc[:, 0], l_sc[:, 0], acc_sc[:], dtype=o_ref.dtype)
        o_ref[0] = out
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _flash_forward(
    q: Array, k: Array, v: Array, block_q: int, block_k: int, causal: bool = True
) -> tp.Tuple[Array, Array]:
    B, H, T, C = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    scale = 1.0 / math.sqrt(C)
    qf = q.reshape(B * H, T, C)
    kf = k.reshape(B * H, T, C)
    vf = v.reshape(B * H, T, C)
    single = T // bk == 1

    if single:
        kernel = functools.partial(
            _fwd_kernel_single, scale=scale, block_q=bq, block_k=bk, causal=causal
        )
        grid = (B * H, T // bq)
        idx_q = lambda b, iq: (b, iq, 0)
        idx_k = lambda b, iq: (b, 0, 0)
        scratch = []
        params = pltpu.CompilerParams(dimension_semantics=("parallel", "parallel"))
    else:
        kernel = functools.partial(
            _fwd_kernel, scale=scale, block_q=bq, block_k=bk, causal=causal
        )
        grid = (B * H, T // bq, T // bk)
        idx_q = lambda b, iq, ik: (b, iq, 0)
        idx_k = lambda b, iq, ik: (b, ik, 0)
        scratch = [
            pltpu.VMEM((bq, C), jnp.float32),
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),
        ]
        params = _COMPILER_PARAMS

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, C), idx_q, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, C), idx_k, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, C), idx_k, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, C), idx_q, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _STATS_LANES), idx_q, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, C), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, _STATS_LANES), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(B, H, T, C), lse.reshape(B, H, T, _STATS_LANES)


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------


def _bwd_fused_single(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dk_ref, dv_ref,
    *, scale, seq_len, causal,
):
    """Fully-fused backward for T <= block: computes dQ, dK and dV from ONE
    score/probability reconstruction — versus the two-kernel split, this
    saves a full QK^T matmul, a mask+exp pass and a second round of
    q/k/v/o/do DMAs. Grid is (B*H,): one grid step per head."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (T, T) f32
    if causal:
        s = _masked(s, 0, 0, seq_len, seq_len)
    lse = lse_ref[0][:, 0]
    p = jnp.exp(s - lse[:, None])  # (T, T)
    pb = p.astype(do.dtype)
    dv_ref[0] = jax.lax.dot_general(
        pb, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = jnp.sum(o_ref[0].astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)  # (T, T) bf16
    dq_ref[0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dk_ref.dtype)


def _bwd_dq_kernel_single(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *, scale, block_q, block_k, causal
):
    """n_k == 1 specialization: no accumulation scratch, one straight pass."""
    iq = pl.program_id(1)
    q = q_ref[0]
    k = k_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        s = _masked(s, iq, 0, block_q, block_k)
    lse = lse_ref[0][:, 0]
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(
        do, v_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = jnp.sum(
        o_ref[0].astype(jnp.float32) * do.astype(jnp.float32), axis=-1
    )
    ds = p * (dp - delta[:, None]) * scale
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dq_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_sc, delta_sc,
    *, scale, block_q, block_k, causal,
):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)
        # delta = rowsum(dO ⊙ O): computed once per Q tile from tiles already
        # in VMEM (no separate pass, no broadcast side buffer)
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        delta = jnp.sum(o * do, axis=-1)  # (block_q,)
        delta_sc[:] = jnp.broadcast_to(delta[:, None], delta_sc.shape)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _masked(s, iq, ik, block_q, block_k)
        lse = lse_ref[0][:, 0]  # (block_q,)
        p = jnp.exp(s - lse[:, None])  # masked entries underflow to 0
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        ds = p * (dp - delta_sc[:, 0][:, None]) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(ik * block_k <= iq * block_q + (block_q - 1))(_compute)
    else:
        _compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref, dk_sc, dv_sc,
    *, scale, block_q, block_k, causal,
):
    ik, iq = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _masked(s, iq, ik, block_q, block_k)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])  # (bq, bk)
        do = do_ref[0]
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, C)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(
            o_ref[0].astype(jnp.float32) * do.astype(jnp.float32), axis=-1
        )  # (block_q,)
        ds = p * (dp - delta[:, None]) * scale  # (bq, bk)
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, C)

    if causal:
        # causal: only Q blocks at/below the diagonal see this KV block
        pl.when(iq * block_q + (block_q - 1) >= ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(iq == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_backward(block_q, block_k, residuals, g, causal=True):
    q, k, v, out, lse = residuals  # q/k/v/out (B,H,T,C); lse (B,H,T,8) f32
    B, H, T, C = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    scale = 1.0 / math.sqrt(C)

    qf, kf, vf = (a.reshape(B * H, T, C) for a in (q, k, v))
    of = out.reshape(B * H, T, C)
    dof = g.reshape(B * H, T, C)
    lsef = lse.reshape(B * H, T, _STATS_LANES)

    if T // bk == 1 and T <= 1024:
        # One fused kernel for the whole backward: the (T, T) f32 score tile
        # plus its bf16 shadows fit VMEM up to T=1024.
        full_spec = pl.BlockSpec((1, T, C), lambda b: (b, 0, 0), memory_space=pltpu.VMEM)
        stat_spec = pl.BlockSpec(
            (1, T, _STATS_LANES), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
        )
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_single, scale=scale, seq_len=T, causal=causal),
            grid=(B * H,),
            in_specs=[full_spec] * 5 + [stat_spec],
            out_specs=[full_spec] * 3,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, T, C), q.dtype),
                jax.ShapeDtypeStruct((B * H, T, C), k.dtype),
                jax.ShapeDtypeStruct((B * H, T, C), v.dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)
            ),
            interpret=_interpret(),
        )(qf, kf, vf, of, dof, lsef)
        return (
            dq.reshape(B, H, T, C),
            dk.reshape(B, H, T, C),
            dv.reshape(B, H, T, C),
        )

    if T // bk == 1:  # single KV step: stateless dq kernel, 2D grid
        q_spec = pl.BlockSpec((1, bq, C), lambda b, iq: (b, iq, 0), memory_space=pltpu.VMEM)
        k_spec = pl.BlockSpec((1, bk, C), lambda b, iq: (b, 0, 0), memory_space=pltpu.VMEM)
        stat_q_spec = pl.BlockSpec(
            (1, bq, _STATS_LANES), lambda b, iq: (b, iq, 0), memory_space=pltpu.VMEM
        )
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel_single, scale=scale, block_q=bq, block_k=bk, causal=causal),
            grid=(B * H, T // bq),
            in_specs=[q_spec, k_spec, k_spec, q_spec, q_spec, stat_q_spec],
            out_specs=[q_spec],
            out_shape=[jax.ShapeDtypeStruct((B * H, T, C), q.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")
            ),
            interpret=_interpret(),
        )(qf, kf, vf, of, dof, lsef)[0]
    else:
        q_spec = pl.BlockSpec((1, bq, C), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM)
        k_spec = pl.BlockSpec((1, bk, C), lambda b, iq, ik: (b, ik, 0), memory_space=pltpu.VMEM)
        stat_q_spec = pl.BlockSpec(
            (1, bq, _STATS_LANES), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM
        )
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk, causal=causal),
            grid=(B * H, T // bq, T // bk),
            in_specs=[q_spec, k_spec, k_spec, q_spec, q_spec, stat_q_spec],
            out_specs=[q_spec],
            out_shape=[jax.ShapeDtypeStruct((B * H, T, C), q.dtype)],
            scratch_shapes=[
                pltpu.VMEM((bq, C), jnp.float32),
                pltpu.VMEM((bq, _STATS_LANES), jnp.float32),
            ],
            compiler_params=_COMPILER_PARAMS,
            interpret=_interpret(),
        )(qf, kf, vf, of, dof, lsef)[0]

    # dk/dv: KV tile is the outer loop, Q sweep is innermost. (T <= 1024
    # always takes the fused branch above, so this is the long-context path
    # and keeps the tiled Q sweep — a full-sequence Q block would blow the
    # VMEM budget exactly where this branch is reachable.)
    q_spec2 = pl.BlockSpec((1, bq, C), lambda b, ik, iq: (b, iq, 0), memory_space=pltpu.VMEM)
    k_spec2 = pl.BlockSpec((1, bk, C), lambda b, ik, iq: (b, ik, 0), memory_space=pltpu.VMEM)
    stat_q_spec2 = pl.BlockSpec(
        (1, bq, _STATS_LANES), lambda b, ik, iq: (b, iq, 0), memory_space=pltpu.VMEM
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk, causal=causal),
        grid=(B * H, T // bk, T // bq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, q_spec2, stat_q_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, C), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, C), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, C), jnp.float32),
            pltpu.VMEM((bk, C), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=_interpret(),
    )(qf, kf, vf, of, dof, lsef)
    return (
        dq.reshape(B, H, T, C),
        dk.reshape(B, H, T, C),
        dv.reshape(B, H, T, C),
    )


# ----------------------------------------------------------------------
# public ops
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: Array, k: Array, v: Array, block_q: int = 512, block_k: int = 1024
) -> Array:
    """Causal flash attention over (B, H, T, C). Block sizes that do not
    tile T are adjusted by `_block_sizes` (KV block widens to T, Q block
    falls back to the KV block) rather than raising."""
    out, _ = _flash_forward(q, k, v, block_q, block_k)
    return out


def _fwd_rule(q, k, v, block_q, block_k):
    out, lse = _flash_forward(q, k, v, block_q, block_k)
    # Named so a remat policy can keep the kernel's residuals: with
    # {attn_out, attn_lse} (plus the rotated q/k/v named in the model) saved,
    # the backward pass never re-runs the forward kernel.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


flash_attention.defvjp(_fwd_rule, _flash_backward)


def flash_attention_bthc(
    q: Array, k: Array, v: Array, block_q: int = 512, block_k: int = 1024
) -> Array:
    """(B, T, H, C) wrapper: transposes to head-major around the kernel.

    Kept for sequence-major callers; the per-head (B, H, T, C) layout is the
    primary one (Mosaic requires the last two block dims to tile cleanly,
    which rules out singleton-head blocks on sequence-major arrays, and a
    heads-fused sequence-major kernel measured slower than the per-head grid
    plus explicit transposes)."""
    out = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        block_q, block_k,
    )
    return out.transpose(0, 2, 1, 3)
