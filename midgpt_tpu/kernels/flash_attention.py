"""Pallas TPU flash attention (causal, FlashAttention-2 style) with custom VJP.

Replaces the reference's materialized T×T attention (reference model.py:71-77)
— the O(T²) memory wall that caps its context at 1024 — with tiled
online-softmax kernels:

  * forward: grid (B*H, n_q, n_k), KV innermost. TPU grid steps execute
    sequentially over the minor dimension, so the (m, l, acc) running
    statistics live in VMEM scratch across the KV sweep of each Q tile.
    Blocks strictly above the causal diagonal are predicated off with
    pl.when; the diagonal block is masked elementwise.
  * backward: two kernels — dQ (grid over KV for each Q tile) and dK/dV
    (grid over Q for each KV tile) — recomputing p = exp(s - lse) from the
    saved log-sum-exp rather than storing T×T probabilities.

Numerics match the reference semantics: QK^T and PV matmuls run on the MXU
in the input dtype (bf16) with float32 accumulation (preferred_element_type),
the softmax/statistics are float32, and the 1/sqrt(C) scale is applied to the
f32 scores exactly as reference model.py:76 does.

On non-TPU backends the kernels run in Pallas interpret mode (tests);
numerical parity against the naive path is asserted in tests/test_flash.py.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

NEG_INF = float("-inf")
# lane width of the statistics scratch (TPU vector registers are (8, 128))
_STATS_LANES = 128

# Grid semantics: batch*heads and Q tiles are independent ("parallel");
# the KV sweep is the sequential reduction dimension ("arbitrary"). Lets
# Mosaic pipeline/parallelize grid steps instead of running them serially.
_COMPILER_PARAMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)

# Run the kernels in interpret mode off-TPU (tests set this; the normal
# dispatcher in ops/attention.py falls back to blockwise instead, because
# interpret mode is orders of magnitude slower than compiled jnp).
RUN_INTERPRET_OFF_TPU = False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(T: int, block_q: int, block_k: int) -> tp.Tuple[int, int]:
    bq = min(block_q, T)
    bk = min(block_k, T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} must be a multiple of block sizes ({bq}, {bk})")
    return bq, bk


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc, *, scale, block_q, block_k):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # causal: KV block strictly above the diagonal contributes nothing
    @pl.when(ik * block_k <= iq * block_q + (block_q - 1))
    def _compute():
        q = q_ref[0]  # (block_q, C)
        k = k_ref[0]  # (block_k, C)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k) f32

        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(row >= col, s, NEG_INF)

        m_prev = m_sc[:, 0]  # (block_q,)
        l_prev = l_sc[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.where(m_prev == NEG_INF, 0.0, jnp.exp(m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])  # rows with all -inf give exp(-inf)=0
        p = jnp.where(s == NEG_INF, 0.0, p)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    @pl.when(ik == n_k - 1)
    def _finalize():
        l = l_sc[:, 0]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_sc[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m_sc[:, 0] + jnp.log(safe_l), NEG_INF)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _flash_forward(
    q: Array, k: Array, v: Array, block_q: int, block_k: int
) -> tp.Tuple[Array, Array]:
    B, H, T, C = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    scale = 1.0 / math.sqrt(C)
    qf = q.reshape(B * H, T, C)
    kf = k.reshape(B * H, T, C)
    vf = v.reshape(B * H, T, C)
    grid = (B * H, T // bq, T // bk)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=bq, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, C), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, C), lambda b, iq, ik: (b, ik, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, C), lambda b, iq, ik: (b, ik, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, C), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, bq, _STATS_LANES), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, C), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, _STATS_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, C), jnp.float32),
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(B, H, T, C), lse[:, :, 0].reshape(B, H, T)


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc, *, scale, block_q, block_k
):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    @pl.when(ik * block_k <= iq * block_q + (block_q - 1))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        masked = row >= col
        lse = lse_ref[0][:, 0]  # (block_q,)
        p = jnp.where(masked, jnp.exp(s - lse[:, None]), 0.0)
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        delta = delta_ref[0][:, 0]  # (block_q,)
        ds = p * (dp - delta[:, None]) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc,
    *, scale, block_q, block_k,
):
    ik, iq = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    # causal: only Q blocks at/below the diagonal see this KV block
    @pl.when(iq * block_q + (block_q - 1) >= ik * block_k)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        masked = row >= col
        lse = lse_ref[0][:, 0]
        p = jnp.where(masked, jnp.exp(s - lse[:, None]), 0.0)  # (bq, bk)
        do = do_ref[0]
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, C)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = delta_ref[0][:, 0]
        ds = p * (dp - delta[:, None]) * scale  # (bq, bk)
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, C)

    @pl.when(iq == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_backward(block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    B, H, T, C = q.shape
    bq, bk = _block_sizes(T, block_q, block_k)
    scale = 1.0 / math.sqrt(C)

    # delta_i = rowsum(dO * O): the softmax-jacobian correction term.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # (B,H,T)

    qf, kf, vf = (a.reshape(B * H, T, C) for a in (q, k, v))
    dof = g.reshape(B * H, T, C)
    lsef = jnp.broadcast_to(lse.reshape(B * H, T, 1), (B * H, T, _STATS_LANES))
    deltaf = jnp.broadcast_to(delta.reshape(B * H, T, 1), (B * H, T, _STATS_LANES))

    q_spec = pl.BlockSpec((1, bq, C), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, C), lambda b, iq, ik: (b, ik, 0), memory_space=pltpu.VMEM)
    stat_q_spec = pl.BlockSpec(
        (1, bq, _STATS_LANES), lambda b, iq, ik: (b, iq, 0), memory_space=pltpu.VMEM
    )

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk),
        grid=(B * H, T // bq, T // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, stat_q_spec, stat_q_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B * H, T, C), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, C), jnp.float32)],
        compiler_params=_COMPILER_PARAMS,
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf)[0]

    # dk/dv: KV tile is the outer loop, Q sweep is innermost.
    q_spec2 = pl.BlockSpec((1, bq, C), lambda b, ik, iq: (b, iq, 0), memory_space=pltpu.VMEM)
    k_spec2 = pl.BlockSpec((1, bk, C), lambda b, ik, iq: (b, ik, 0), memory_space=pltpu.VMEM)
    stat_q_spec2 = pl.BlockSpec(
        (1, bq, _STATS_LANES), lambda b, ik, iq: (b, iq, 0), memory_space=pltpu.VMEM
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk),
        grid=(B * H, T // bk, T // bq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, stat_q_spec2, stat_q_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, C), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, C), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, C), jnp.float32),
            pltpu.VMEM((bk, C), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf)

    return (
        dq.reshape(B, H, T, C),
        dk.reshape(B, H, T, C),
        dv.reshape(B, H, T, C),
    )


# ----------------------------------------------------------------------
# public op
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: Array, k: Array, v: Array, block_q: int = 256, block_k: int = 256
) -> Array:
    """Causal flash attention over (B, H, T, C); T must divide the blocks."""
    out, _ = _flash_forward(q, k, v, block_q, block_k)
    return out


def _fwd_rule(q, k, v, block_q, block_k):
    out, lse = _flash_forward(q, k, v, block_q, block_k)
    return out, (q, k, v, out, lse)


flash_attention.defvjp(_fwd_rule, _flash_backward)
