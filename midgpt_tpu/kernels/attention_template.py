"""Unified paged-attention Pallas kernel TEMPLATE.

One parameterized kernel body serves every paged-attention variant the
serving engine compiles, where kernels/decode_attention.py previously
hand-wrote a skeleton per variant (plain decode and multi-row verify, each
duplicating the page translation, the online-softmax sweep, and the int8
dequant read path). The template's axes of variation are *specs*, not new
kernels:

  * `n_rows` — query rows per slot: 1 for plain decode, k+1 for
    speculative verify (each row masks to its own visible-key count);
  * `quantized` — bf16/f32 direct reads vs int8 pages with fused in-VMEM
    f32-scale dequant (one (1, H, page_size) scale row per page, riding
    the same scalar-prefetched page translation as its page);
  * `split_k` — 1 emits the finalized output in-kernel (the classic
    sweep); s > 1 partitions the visible key sequence across a second
    parallel grid dimension, each partition sweeping max_pages/s pages and
    emitting RAW (m, l, acc) online-softmax partials that are merged
    outside the kernel with ops/online_softmax.merge_partials — the
    FlashAttention-2-style work partitioning that keeps the chip busy when
    a single long request is the whole batch.

Skeleton (shared by every mode):

  grid (B, split_k, pages_per_split), pages innermost/sequential. The page
  table and per-row counts ride PrefetchScalarGridSpec scalar prefetch, so
  the K/V BlockSpec index maps translate (slot, partition, logical page)
  -> physical page BEFORE the DMA is issued. Online-softmax running
  statistics (ops/online_softmax.online_block) live in VMEM scratch across
  each partition's page sweep; pages past the slot's last visible key are
  predicated off with pl.when (no lax.cond anywhere — graftcheck GC001).

Split-K partial buffers fold the partition axis into the slot axis
((B*split_k, H, R, C) f32 acc + (B*split_k, H, R, 8) stats) so every
block's last two dims either span the full array dim or are the 8-lane
statistics tile — Mosaic-tileable with no 5-D layouts. The merge is
per-(slot, head, row) elementwise math: under a tensor-parallel shard_map
it runs inside each head shard with ZERO new collectives.

Variants ARE specs over this template, not new sweeps:

  * GQA/MQA — q arrives with H_q = groups * H_kv heads (query head h
    reads K/V head h // groups, consecutive grouping); the wrapper FOLDS
    the group axis into the row axis — q (B, H_q, R, C) reshapes (free:
    contiguous) to (B, H_kv, groups*R, C) and counts tile per group — so
    the kernel body runs unchanged over the pool's H_kv heads with
    groups*R rows per tile. The fold preserves the nondecreasing-counts
    sweep bound (the last tiled row is still a maximal count) and the
    per-row mask (each folded row carries its own count).
  * sliding window (+ attention sinks) — a wider column-mask expression
    (straight-line selects, no lax.cond): a row with `count` visible keys
    keeps cols in [count - sliding_window, count) ∪ [0, attn_sinks), and
    the page sweep additionally SKIPS pages that are fully behind every
    row's window and past the sink prefix — the resident work per row is
    O(window), which is what makes long windowed sessions O(1) in T.
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from midgpt_tpu.kernels.flash_attention import _STATS_LANES, _interpret
from midgpt_tpu.ops.online_softmax import (
    M_INIT,
    MASK,
    finalize,
    merge_partials,
    online_block,
)

Array = jax.Array


def normalize_split_k(split_k: int, max_pages: int) -> int:
    """Largest pow2 <= split_k that divides the page-table width.

    Serving page buckets are pow2 (or the pow2-capped max), so any pow2
    split <= max_pages divides it; the loop is the general-case guard for
    direct kernel callers with odd table widths."""
    s = max(1, int(split_k))
    s = min(s, max_pages)
    s = 1 << (s.bit_length() - 1)  # pow2 floor (applied after the clamp)
    while max_pages % s:
        s //= 2
    return s


def _tpl_kernel(
    pt_ref,  # (B, max_pages) int32 scalar-prefetch: page table
    cnt_ref,  # (B, R) int32 scalar-prefetch: visible keys per row
    q_ref,  # (1, H, R, C) — head-major rows
    k_ref,  # (H, 1, page_size, C)
    v_ref,  # (H, 1, page_size, C)
    *rest,  # int8 mode: ks_ref, vs_ref (1, H, page_size) f32; then outputs
    # split_k == 1: o_ref (1, H, R, C)
    # split_k > 1:  o_ref (1, H, R, C) f32, m_ref/l_ref (1, H, R, 8) f32
    # then scratch: acc_sc (H, R, C) f32, m_sc/l_sc (H, R, 8) f32
    scale: float,
    page_size: int,
    n_rows: int,
    split_k: int,
    pages_per_split: int,
    quantized: bool,
    sliding_window: int,
    attn_sinks: int,
):
    if quantized:
        ks_ref, vs_ref, *outs = rest
    else:
        outs = rest
    if split_k > 1:
        o_ref, m_ref, l_ref, acc_sc, m_sc, l_sc = outs
    else:
        o_ref, acc_sc, m_sc, l_sc = outs
    b, si, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, M_INIT)
        l_sc[:] = jnp.zeros_like(l_sc)

    # Per-row counts from SMEM, assembled by a static unroll over the
    # (small, static) row count. Counts are nondecreasing in the row index
    # (verify rows see lengths + t + 1 keys), so the last row's count
    # bounds the page sweep for the whole tile.
    counts = jnp.stack([cnt_ref[b, t] for t in range(n_rows)])  # (R,)
    page0 = (si * pages_per_split + p) * page_size

    # Sweep predicate: skip pages past the last row's visible keys, and —
    # under a sliding window — pages wholly BEHIND every row's window
    # (counts are nondecreasing, so row 0's window start is the minimum)
    # unless they hold sink tokens. Python-static composition, one pl.when.
    live = page0 < cnt_ref[b, n_rows - 1]
    if sliding_window:
        ahead = page0 + page_size > cnt_ref[b, 0] - sliding_window
        if attn_sinks:
            ahead |= page0 < attn_sinks
        live &= ahead

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # (H, R, C)
        k = k_ref[:, 0]  # (H, page_size, C)
        if quantized:
            # Dequantize in VMEM: the page's f32 scales broadcast over C
            # (exact — int8 * f32, ops/quant.py), then the same dots as
            # the bf16 path in f32.
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0][:, :, None]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # (H, R, page_size) f32
        col = page0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        # ops/attention.visible_mask spelled as straight-line selects
        # (no lax.cond — graftcheck GC001): causal/length bound, then the
        # window [count - W, count) widened by the sink prefix [0, sinks).
        keep = col < counts[None, :, None]
        if sliding_window:
            w = col >= counts[None, :, None] - sliding_window
            if attn_sinks:
                w |= col < attn_sinks
            keep &= w
        s = jnp.where(keep, s, MASK)

        m_new, alpha, prob, l_new = online_block(m_sc[:, :, 0], l_sc[:, :, 0], s)
        if quantized:
            v = v_ref[:, 0].astype(jnp.float32) * vs_ref[0][:, :, None]
        else:
            v = v_ref[:, 0]
        pv = jax.lax.dot_general(
            prob.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (H, R, C)
        acc_sc[:] = acc_sc[:] * alpha[:, :, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, :, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, :, None], l_sc.shape)

    @pl.when(p == pages_per_split - 1)
    def _emit():
        if split_k > 1:
            # Raw partials out; merge_partials + finalize run outside.
            o_ref[0] = acc_sc[:]
            m_ref[0] = m_sc[:]
            l_ref[0] = l_sc[:]
        else:
            out, _ = finalize(m_sc[:, :, 0], l_sc[:, :, 0], acc_sc[:])
            o_ref[0] = out.astype(o_ref.dtype)


def paged_attention_template(
    q: Array,  # (B, H_q, R, C) — head-major query rows (H_q >= pool heads)
    k_pages: Array,  # (H_kv, num_pages, page_size, C) — ONE layer's pool
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, R) int32 — keys visible to row r of slot b
    k_scale: tp.Optional[Array] = None,  # (num_pages, H_kv, page_size) f32
    v_scale: tp.Optional[Array] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Instantiate the template for one (n_rows, quantized, split_k,
    kv_groups, window) spec.

    Returns (B, H_q, R, C) in q.dtype. int8 pools require both scale side
    buffers; bf16/f32 pools take none. split_k is normalized to a pow2
    divisor of the table width; split_k == 1 is the classic in-kernel
    finalize, split_k > 1 emits per-partition partials and merges them
    here (f32, ops/online_softmax) before the final dtype cast.

    GQA/MQA is inferred from the shapes: when q carries groups = H_q/H_kv
    query heads per pool head, the group axis folds into the row axis
    (module docstring) and unfolds on the way out — the kernel body and
    every BlockSpec see plain H_kv-head geometry. sliding_window/attn_sinks
    are static mask/sweep parameters (0 = full causal, bit-identical to
    the windowless template)."""
    B, HQ, R, C = q.shape
    H, _, page_size, _ = k_pages.shape
    groups = HQ // H
    if groups > 1:
        # Fold: head h = kv*groups + g, so (B, HQ, R, C) is contiguously
        # (B, H, groups, R, C); folded row g*R + r keeps row r's count.
        q = q.reshape(B, H, groups * R, C)
        counts = jnp.tile(counts, (1, groups))
    R_full, R = R, groups * R
    max_pages = page_table.shape[1]
    split_k = normalize_split_k(split_k, max_pages)
    pps = max_pages // split_k
    scale = 1.0 / math.sqrt(C)
    quantized = k_scale is not None

    page_spec = pl.BlockSpec(
        (H, 1, page_size, C),
        lambda b, si, p, pt, cnt: (0, pt[b, si * pps + p], 0, 0),
    )
    in_specs = [
        pl.BlockSpec((1, H, R, C), lambda b, si, p, pt, cnt: (b, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # One page's scales per grid step, translated through the same
        # scalar-prefetched table as its page. Trailing dims (H, page_size)
        # span the full array dims -> Mosaic-tileable as-is.
        scale_spec = pl.BlockSpec(
            (1, H, page_size),
            lambda b, si, p, pt, cnt: (pt[b, si * pps + p], 0, 0),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    if split_k > 1:
        # Partition axis folded into the slot axis: 4-D partial buffers
        # whose trailing block dims span the full array dims (Mosaic rule).
        part_idx = lambda b, si, p, pt, cnt: (b * split_k + si, 0, 0, 0)
        out_specs = [
            pl.BlockSpec((1, H, R, C), part_idx),
            pl.BlockSpec((1, H, R, _STATS_LANES), part_idx),
            pl.BlockSpec((1, H, R, _STATS_LANES), part_idx),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((B * split_k, H, R, C), jnp.float32),
            jax.ShapeDtypeStruct((B * split_k, H, R, _STATS_LANES), jnp.float32),
            jax.ShapeDtypeStruct((B * split_k, H, R, _STATS_LANES), jnp.float32),
        ]
    else:
        out_specs = pl.BlockSpec(
            (1, H, R, C), lambda b, si, p, pt, cnt: (b, 0, 0, 0)
        )
        out_shape = jax.ShapeDtypeStruct((B, H, R, C), q.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, split_k, pps),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((H, R, C), jnp.float32),
            pltpu.VMEM((H, R, _STATS_LANES), jnp.float32),
            pltpu.VMEM((H, R, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _tpl_kernel, scale=scale, page_size=page_size, n_rows=R,
            split_k=split_k, pages_per_split=pps, quantized=quantized,
            sliding_window=sliding_window, attn_sinks=attn_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            # slots and partitions are independent; the page sweep is the
            # sequential reduction (scratch carries across it)
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), counts.astype(jnp.int32), *operands)
    if split_k == 1:
        return out.reshape(B, HQ, R_full, C) if groups > 1 else out
    o, m, l = out
    o = o.reshape(B, split_k, H, R, C)
    m = m.reshape(B, split_k, H, R, _STATS_LANES)[..., 0]
    l = l.reshape(B, split_k, H, R, _STATS_LANES)[..., 0]
    m, l, acc = merge_partials(m, l, o, axis=1)
    merged, _ = finalize(m, l, acc)
    merged = merged.astype(q.dtype)
    return merged.reshape(B, HQ, R_full, C) if groups > 1 else merged
