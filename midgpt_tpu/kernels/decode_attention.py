"""Pallas TPU paged decode/verify attention for the continuous-batching
engine, in bf16 and int8-quantized cache modes.

Decode-time attention reads K/V through a per-slot PAGE TABLE instead of a
contiguous (B, S, ...) cache: physical pages of `page_size` tokens live in a
shared (H, num_pages, page_size, C) pool (models/gpt.py PagedKVCache), and
slot b's logical page j is pool page `page_table[b, j]`. Each slot masks to
its own true length, so one compiled program serves any mix of request
lengths — the two levers the serving layer needs (vLLM-style paged memory +
FlashAttention-style work partitioning, PAPERS.md) under XLA's static-shape
constraint.

Kernel structure: grid (B, max_pages), pages innermost/sequential. The page
table and per-slot lengths ride `PrefetchScalarGridSpec` scalar prefetch, so
the K/V BlockSpec index maps translate (slot, logical page) -> physical page
BEFORE the DMA is issued: each grid step pulls exactly one (page_size, C)
page per head into VMEM — never the whole pool. Online-softmax running
statistics live in VMEM scratch across the page sweep (same scheme as
kernels/flash_attention.py, whose finite MASK/M_INIT constants this reuses).
Pages at or past a slot's length are predicated off with `pl.when` (compute
skipped; the block DMA still runs — it reads the reserved sink page or a
stale page, both masked).

**Int8 mode** (PagedKVCache int8 storage): pages arrive int8 with f32
absmax scales in (num_pages, H, page_size) side buffers (one scale per K/V
vector per head, ops/quant.py). The scale BlockSpec (1, H, page_size)
fetches exactly one page's scales alongside its int8 page — the trailing
block dims span the full (H, page_size) array dims, so the layout is
Mosaic-tileable with no in-kernel transpose — and dequantization happens in
VMEM before QK^T/PV: HBM only ever moves int8 pages plus the tiny scale
rows, which is the whole point (decode is HBM-bandwidth-bound; halving
cache bytes ~halves decode-attention traffic).

There are TWO kernels:

  * `paged_attention_kernel` — one query row per slot (plain decode).
  * `paged_verify_attention_kernel` — T = k+1 query rows per slot with a
    per-row visible-key count (speculative verification,
    GPT.verify_step_paged): the multi-row sibling with (H, T, page_size)
    score tiles and per-(head, row) online-softmax stats. This replaces
    the gather lowering as the compiled verify path on TPU (it was the
    named upgrade path of the speculative-decoding PR).

Blocks obey the Mosaic tiling rule (CLAUDE.md): every block's last two
dims are (8, 128)-divisible or span the full array dim.

Off-TPU the dispatchers use the XLA gather fallbacks below, which mirror
the contiguous `GPT.decode_step` attention op-for-op (same einsum shapes,
same mask-then-scale-then-f32-softmax order, dequantizing right after the
page gather in int8 mode) so paged decode stays token-exact with the
single-request engine on the CPU test mesh; the kernels themselves run in
interpret mode only under their parity tests (tests/test_decode_attention.py
and tests/test_quant_cache.py — interpret is too slow for the serving
tests' inner loop).
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.kernels.flash_attention import M_INIT, MASK, _interpret
from midgpt_tpu.ops.quant import dequantize_q8
from midgpt_tpu.utils.compat import shard_map

Array = jax.Array

# lane width of the m/l statistics scratch (see flash_attention._STATS_LANES)
_STATS_LANES = 8


def _decode_kernel(
    pt_ref,  # (B, max_pages) int32 scalar-prefetch: page table
    len_ref,  # (B,) int32 scalar-prefetch: per-slot valid lengths
    q_ref,  # (1, H, C)
    k_ref,  # (H, 1, page_size, C)
    v_ref,  # (H, 1, page_size, C)
    *rest,  # int8 mode: ks_ref, vs_ref (1, H, page_size) f32; then
    # o_ref (1, H, C), acc_sc (H, C) f32, m_sc/l_sc (H, _STATS_LANES) f32
    scale: float,
    page_size: int,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_sc, m_sc, l_sc = rest
    else:
        o_ref, acc_sc, m_sc, l_sc = rest
    b, p = pl.program_id(0), pl.program_id(1)
    n_p = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, M_INIT)
        l_sc[:] = jnp.zeros_like(l_sc)

    length = len_ref[b]

    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0]  # (H, C)
        k = k_ref[:, 0]  # (H, page_size, C)
        if quantized:
            # Dequantize in VMEM: the page's f32 scales broadcast over C
            # (exact — int8 * f32, ops/quant.py), then the same dots as
            # the bf16 path in f32.
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0][:, :, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # (H, page_size) f32
        col = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < length, s, MASK)

        m_prev = m_sc[:, 0]  # (H,)
        l_prev = l_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        prob = jnp.exp(s - m_new[:, None])  # masked entries underflow to 0
        if quantized:
            v = v_ref[:, 0].astype(jnp.float32) * vs_ref[0][:, :, None]
        else:
            v = v_ref[:, 0]
        l_new = l_prev * alpha + jnp.sum(prob, axis=-1)
        pv = jax.lax.dot_general(
            prob.astype(v.dtype), v,
            (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (H, C)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = l_sc[:, 0]
        safe_l = jnp.maximum(l, 1e-30)  # length-0 slots emit 0, not NaN
        o_ref[0] = (acc_sc[:] / safe_l[:, None]).astype(o_ref.dtype)


def paged_attention_kernel(
    q: Array,  # (B, H, C) — one query token per slot
    k_pages: Array,  # (H, num_pages, page_size, C) — ONE layer's pool
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32 — valid tokens per slot (0 = inactive)
    k_scale: tp.Optional[Array] = None,  # (num_pages, H, page_size) f32
    v_scale: tp.Optional[Array] = None,
) -> Array:
    """Paged decode attention via the Pallas kernel. Returns (B, H, C).
    int8 pools require both scale side buffers; bf16 pools take none."""
    B, H, C = q.shape
    _, _, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    scale = 1.0 / math.sqrt(C)
    quantized = k_scale is not None

    page_spec = pl.BlockSpec(
        (H, 1, page_size, C), lambda b, p, pt, ln: (0, pt[b, p], 0, 0)
    )
    in_specs = [
        pl.BlockSpec((1, H, C), lambda b, p, pt, ln: (b, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # One page's scales per grid step, translated through the same
        # scalar-prefetched table as its page. Trailing dims (H, page_size)
        # span the full array dims -> Mosaic-tileable as-is.
        scale_spec = pl.BlockSpec(
            (1, H, page_size), lambda b, p, pt, ln: (pt[b, p], 0, 0)
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, C), lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C), jnp.float32),
            pltpu.VMEM((H, _STATS_LANES), jnp.float32),
            pltpu.VMEM((H, _STATS_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, page_size=page_size,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), *operands)


def _gather_pages(
    pages: Array,  # (H, num_pages, page_size, C)
    scales: tp.Optional[Array],  # (num_pages, H, page_size) f32 | None
    page_table: Array,  # (B, max_pages) int32
    out_dtype,
) -> Array:
    """Gather every slot's pages contiguous -> (B, H, S, C), dequantizing
    right after the gather in int8 mode (the CPU sibling of the kernels'
    in-VMEM dequant; ops/quant.py — exact, so gather and kernel read
    identical values from the same pool)."""
    H, _, page_size, C = pages.shape
    B, max_pages = page_table.shape
    S = max_pages * page_size
    flat = page_table.reshape(-1)
    g = jnp.take(pages, flat, axis=1)  # (H, B*max_pages, page_size, C)
    g = g.reshape(H, B, S, C).transpose(1, 0, 2, 3)  # (B, H, S, C)
    if scales is None:
        return g
    sg = jnp.take(scales, flat, axis=0)  # (B*max_pages, H, page_size)
    sg = sg.reshape(B, max_pages, H, page_size).transpose(0, 2, 1, 3)
    return dequantize_q8(g, sg.reshape(B, H, S)).astype(out_dtype)


def paged_attention_gather(
    q: Array,  # (B, H, C)
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
) -> Array:
    """XLA fallback: gather each slot's pages contiguous (dequantized in
    int8 mode), then run the exact attention ops of the contiguous
    `GPT.decode_step` (same einsum shapes, -inf mask BEFORE the
    1/sqrt(C)-scaled f32 softmax) so paged and contiguous decode agree
    token-for-token on CPU. O(B * max_pages) page reads per call — the
    kernel above is the O(used-length) path on TPU."""
    B, H, C = q.shape
    S = page_table.shape[1] * k_pages.shape[2]
    kg = _gather_pages(k_pages, k_scale, page_table, q.dtype)
    vg = _gather_pages(v_pages, v_scale, page_table, q.dtype)
    scores = jnp.einsum("bhqc,bhkc->bhqk", q[:, :, None], kg)  # (B, H, 1, S)
    valid = jnp.arange(S)[None, None, None, :] < lengths[:, None, None, None]
    scores = jnp.where(valid, scores, float("-inf"))
    probs = jax.nn.softmax(
        scores.astype(jnp.float32) / math.sqrt(C), axis=-1
    ).astype(q.dtype)
    return jnp.einsum("bhqk,bhkc->bhqc", probs, vg)[:, :, 0]


def _tp_shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """Full-MANUAL shard_map over the serving mesh: every named axis is
    manual (only 'tp' exceeds size 1 on a serve mesh, parallel/serve_tp.py),
    so the body is a plain per-shard trace — exactly what a Pallas kernel
    needs, and the one shard_map form the 0.4.37 CPU backend lowers (the
    partial-manual form aborts there; utils/compat.shard_map docstring).
    check_vma off: paged attention is pointwise in heads, there is no
    replication to certify."""
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names=frozenset(mesh.axis_names),
        check_vma=False,
    )


def paged_attention(
    q: Array,
    k_pages: Array,
    v_pages: Array,
    page_table: Array,
    lengths: Array,
    impl: str = "auto",
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    mesh: tp.Optional[Mesh] = None,
) -> Array:
    """Dispatch: Pallas kernel on TPU, XLA gather elsewhere (interpret mode
    is orders of magnitude too slow for the serving loop — same policy as
    ops/attention.py for the flash kernel).

    With a tp>1 serving mesh the kernel is invoked PER SHARD through a
    full-manual shard_map: each tp shard holds H/tp heads of q and of the
    page pool (+ int8 scale rows), the page table and lengths ride in
    replicated, and the per-head online-softmax sweep needs no collective at
    all — the head axis is embarrassingly parallel. The gather lowering
    ignores `mesh`: it is plain jnp, and GSPMD partitions it from the
    operand shardings alone."""
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "kernel":
        if mesh is not None and mesh.shape["tp"] > 1:
            quantized = k_scale is not None
            pool = P("tp", None, None, None)  # (H, pages, page_size, C)
            in_specs = [P(None, "tp", None), pool, pool, P(), P()]
            args = [q, k_pages, v_pages, page_table, lengths]
            if quantized:
                in_specs += [P(None, "tp", None)] * 2  # (pages, H, page_size)
                args += [k_scale, v_scale]
            fn = _tp_shard_map(
                lambda *a: paged_attention_kernel(*a),
                mesh, tuple(in_specs), P(None, "tp", None),
            )
            return fn(*args)
        return paged_attention_kernel(
            q, k_pages, v_pages, page_table, lengths, k_scale, v_scale
        )
    if impl == "gather":
        return paged_attention_gather(
            q, k_pages, v_pages, page_table, lengths, k_scale, v_scale
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")


# ----------------------------------------------------------------------
# Multi-row paged verify attention (speculative decoding)
# ----------------------------------------------------------------------


def _verify_kernel(
    pt_ref,  # (B, max_pages) int32 scalar-prefetch: page table
    cnt_ref,  # (B, T) int32 scalar-prefetch: visible keys per row
    q_ref,  # (1, H, T, C) — head-major (transposed once outside)
    k_ref,  # (H, 1, page_size, C)
    v_ref,  # (H, 1, page_size, C)
    *rest,  # int8 mode: ks_ref, vs_ref (1, H, page_size) f32; then
    # o_ref (1, H, T, C), acc_sc (H, T, C) f32,
    # m_sc/l_sc (H, T, _STATS_LANES) f32
    scale: float,
    page_size: int,
    n_rows: int,
    quantized: bool,
):
    """The decode kernel's online-softmax page sweep, widened to T = k+1
    query rows per slot: score tiles are (H, T, page_size), the running
    m/l statistics carry a row axis, and each row t masks to its OWN
    visible-key count cnt_ref[b, t] (the caller passes lengths + t + 1,
    which is what makes the speculative chunk causal through the page
    table — GPT.verify_step_paged). Counts are nondecreasing in t, so the
    page sweep runs to the LAST row's count and earlier rows simply mask."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_sc, m_sc, l_sc = rest
    else:
        o_ref, acc_sc, m_sc, l_sc = rest
    b, p = pl.program_id(0), pl.program_id(1)
    n_p = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, M_INIT)
        l_sc[:] = jnp.zeros_like(l_sc)

    # Per-row counts from SMEM, assembled by a static unroll over the
    # (small, static) row count; the sweep bound is the last row's count.
    counts = jnp.stack([cnt_ref[b, t] for t in range(n_rows)])  # (T,)

    @pl.when(p * page_size < cnt_ref[b, n_rows - 1])
    def _compute():
        q = q_ref[0]  # (H, T, C)
        k = k_ref[:, 0]  # (H, page_size, C)
        if quantized:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0][:, :, None]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # (H, T, page_size) f32
        col = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < counts[None, :, None], s, MASK)

        m_prev = m_sc[:, :, 0]  # (H, T)
        l_prev = l_sc[:, :, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        prob = jnp.exp(s - m_new[:, :, None])  # masked entries underflow to 0
        if quantized:
            v = v_ref[:, 0].astype(jnp.float32) * vs_ref[0][:, :, None]
        else:
            v = v_ref[:, 0]
        l_new = l_prev * alpha + jnp.sum(prob, axis=-1)
        pv = jax.lax.dot_general(
            prob.astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (H, T, C)
        acc_sc[:] = acc_sc[:] * alpha[:, :, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, :, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, :, None], l_sc.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = l_sc[:, :, 0]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_sc[:] / safe_l[:, :, None]).astype(o_ref.dtype)


def paged_verify_attention_kernel(
    q: Array,  # (B, T, H, C)
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, T) int32 — keys visible to row t of slot b
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
) -> Array:
    """Multi-row paged attention via the Pallas verify kernel. Returns
    (B, T, H, C). q is transposed head-major ONCE outside the kernel (a
    single small XLA transpose per verify forward) so the kernel works in
    the pool's native (H, ...) layout with no in-kernel transposes."""
    B, T, H, C = q.shape
    _, _, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    scale = 1.0 / math.sqrt(C)
    quantized = k_scale is not None
    q_hm = q.transpose(0, 2, 1, 3)  # (B, H, T, C)

    page_spec = pl.BlockSpec(
        (H, 1, page_size, C), lambda b, p, pt, cnt: (0, pt[b, p], 0, 0)
    )
    in_specs = [
        pl.BlockSpec((1, H, T, C), lambda b, p, pt, cnt: (b, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q_hm, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, H, page_size), lambda b, p, pt, cnt: (pt[b, p], 0, 0)
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, H, T, C), lambda b, p, pt, cnt: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((H, T, C), jnp.float32),
            pltpu.VMEM((H, T, _STATS_LANES), jnp.float32),
            pltpu.VMEM((H, T, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _verify_kernel, scale=scale, page_size=page_size, n_rows=T,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, C), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), counts.astype(jnp.int32), *operands)
    return out.transpose(0, 2, 1, 3)  # (B, T, H, C)


def paged_verify_attention_gather(
    q: Array,  # (B, T, H, C)
    k_pages: Array,
    v_pages: Array,
    page_table: Array,
    counts: Array,  # (B, T) int32
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
) -> Array:
    """XLA gather lowering of the multi-row verify attention: pages
    gathered contiguous once (dequantized in int8 mode, like
    prefill_paged_chunk), then per-row count masks over the shared buffer.
    Same mask-then-scale-then-f32-softmax order as
    `paged_attention_gather`, so speculative greedy verify stays
    token-exact with plain paged decode (pinned by tests/test_spec.py)."""
    B, T, H, C = q.shape
    S = page_table.shape[1] * k_pages.shape[2]
    kg = _gather_pages(k_pages, k_scale, page_table, q.dtype)
    vg = _gather_pages(v_pages, v_scale, page_table, q.dtype)
    scores = jnp.einsum("bthc,bhkc->bhtk", q.astype(kg.dtype), kg)
    valid = jnp.arange(S)[None, None, None, :] < counts[:, None, :, None]
    scores = jnp.where(valid, scores, float("-inf"))
    probs = jax.nn.softmax(
        scores.astype(jnp.float32) / math.sqrt(C), axis=-1
    ).astype(q.dtype)
    return jnp.einsum("bhtk,bhkc->bthc", probs, vg)  # (B, T, H, C)


def paged_verify_attention(
    q: Array,  # (B, T, H, C) — T = k+1 speculative positions per slot
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, T) int32 — keys visible to row t of slot b
    impl: str = "auto",
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    mesh: tp.Optional[Mesh] = None,
) -> Array:
    """Batched multi-row paged attention for speculative verification
    (GPT.verify_step_paged): every slot scores its k+1 candidate positions
    against its own pages in ONE call. Row t of slot b attends to
    counts[b, t] keys — the caller passes lengths[b] + t + 1, which makes
    the chunk causal through the cache: all rows' K/V are written before
    the read, and the per-row count hides the later rows.

    Dispatch mirrors `paged_attention`: the Pallas multi-row kernel on TPU
    (the compiled verify path, bf16 and int8 — interpret-mode parity in
    tests/test_quant_cache.py), the XLA gather lowering elsewhere; on a
    tp>1 mesh the kernel runs per shard over H/tp heads via the same
    full-manual shard_map, collective-free."""
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "kernel":
        if mesh is not None and mesh.shape["tp"] > 1:
            quantized = k_scale is not None
            pool = P("tp", None, None, None)
            row_spec = P(None, None, "tp", None)  # q/out (B, T, H, C)
            in_specs = [row_spec, pool, pool, P(), P()]
            args = [q, k_pages, v_pages, page_table, counts]
            if quantized:
                in_specs += [P(None, "tp", None)] * 2
                args += [k_scale, v_scale]
            fn = _tp_shard_map(
                lambda *a: paged_verify_attention_kernel(*a),
                mesh, tuple(in_specs), row_spec,
            )
            return fn(*args)
        return paged_verify_attention_kernel(
            q, k_pages, v_pages, page_table, counts, k_scale, v_scale
        )
    if impl == "gather":
        return paged_verify_attention_gather(
            q, k_pages, v_pages, page_table, counts, k_scale, v_scale
        )
    raise ValueError(f"unknown paged verify attention impl {impl!r}")
