"""Pallas TPU paged decode attention for the continuous-batching engine.

Decode-time attention reads K/V through a per-slot PAGE TABLE instead of a
contiguous (B, S, ...) cache: physical pages of `page_size` tokens live in a
shared (H, num_pages, page_size, C) pool (models/gpt.py PagedKVCache), and
slot b's logical page j is pool page `page_table[b, j]`. Each slot masks to
its own true length, so one compiled program serves any mix of request
lengths — the two levers the serving layer needs (vLLM-style paged memory +
FlashAttention-style work partitioning, PAPERS.md) under XLA's static-shape
constraint.

Kernel structure: grid (B, max_pages), pages innermost/sequential. The page
table and per-slot lengths ride `PrefetchScalarGridSpec` scalar prefetch, so
the K/V BlockSpec index maps translate (slot, logical page) -> physical page
BEFORE the DMA is issued: each grid step pulls exactly one (page_size, C)
page per head into VMEM — never the whole pool. Online-softmax running
statistics live in VMEM scratch across the page sweep (same scheme as
kernels/flash_attention.py, whose finite MASK/M_INIT constants this reuses).
Pages at or past a slot's length are predicated off with `pl.when` (compute
skipped; the block DMA still runs — it reads the reserved sink page or a
stale page, both masked).

Blocks obey the Mosaic tiling rule (CLAUDE.md): the K/V block's last two
dims are (page_size, C) with page_size 8-divisible and C spanning the full
head dim; the q/o blocks span (H, C) fully.

Off-TPU the dispatcher uses the XLA gather fallback below, which mirrors the
contiguous `GPT.decode_step` attention op-for-op (same einsum shapes, same
mask-then-scale-then-f32-softmax order) so paged decode stays token-exact
with the single-request engine on the CPU test mesh; the kernel itself runs
in interpret mode only under its parity test (tests/test_decode_attention.py
— interpret is too slow for the serving tests' inner loop).
"""

from __future__ import annotations

import functools
import math
import typing as tp

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from midgpt_tpu.kernels.flash_attention import M_INIT, MASK, _interpret

Array = jax.Array

# lane width of the m/l statistics scratch (see flash_attention._STATS_LANES)
_STATS_LANES = 8


def _decode_kernel(
    pt_ref,  # (B, max_pages) int32 scalar-prefetch: page table
    len_ref,  # (B,) int32 scalar-prefetch: per-slot valid lengths
    q_ref,  # (1, H, C)
    k_ref,  # (H, 1, page_size, C)
    v_ref,  # (H, 1, page_size, C)
    o_ref,  # (1, H, C)
    acc_sc,  # (H, C) f32
    m_sc,  # (H, _STATS_LANES) f32
    l_sc,  # (H, _STATS_LANES) f32
    *,
    scale: float,
    page_size: int,
):
    b, p = pl.program_id(0), pl.program_id(1)
    n_p = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, M_INIT)
        l_sc[:] = jnp.zeros_like(l_sc)

    length = len_ref[b]

    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0]  # (H, C)
        k = k_ref[:, 0]  # (H, page_size, C)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # (H, page_size) f32
        col = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < length, s, MASK)

        m_prev = m_sc[:, 0]  # (H,)
        l_prev = l_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        prob = jnp.exp(s - m_new[:, None])  # masked entries underflow to 0
        l_new = l_prev * alpha + jnp.sum(prob, axis=-1)
        pv = jax.lax.dot_general(
            prob.astype(v_ref.dtype), v_ref[:, 0],
            (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (H, C)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = l_sc[:, 0]
        safe_l = jnp.maximum(l, 1e-30)  # length-0 slots emit 0, not NaN
        o_ref[0] = (acc_sc[:] / safe_l[:, None]).astype(o_ref.dtype)


def paged_attention_kernel(
    q: Array,  # (B, H, C) — one query token per slot
    k_pages: Array,  # (H, num_pages, page_size, C) — ONE layer's pool
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32 — valid tokens per slot (0 = inactive)
) -> Array:
    """Paged decode attention via the Pallas kernel. Returns (B, H, C)."""
    B, H, C = q.shape
    _, _, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    scale = 1.0 / math.sqrt(C)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, C), lambda b, p, pt, ln: (b, 0, 0)),
            pl.BlockSpec(
                (H, 1, page_size, C), lambda b, p, pt, ln: (0, pt[b, p], 0, 0)
            ),
            pl.BlockSpec(
                (H, 1, page_size, C), lambda b, p, pt, ln: (0, pt[b, p], 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, H, C), lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C), jnp.float32),
            pltpu.VMEM((H, _STATS_LANES), jnp.float32),
            pltpu.VMEM((H, _STATS_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page_size=page_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), q, k_pages, v_pages)


def paged_attention_gather(
    q: Array,  # (B, H, C)
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32
) -> Array:
    """XLA fallback: gather each slot's pages contiguous, then run the exact
    attention ops of the contiguous `GPT.decode_step` (same einsum shapes,
    -inf mask BEFORE the 1/sqrt(C)-scaled f32 softmax) so paged and
    contiguous decode agree token-for-token on CPU. O(B * max_pages) page
    reads per call — the kernel above is the O(used-length) path on TPU."""
    B, H, C = q.shape
    _, _, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    S = max_pages * page_size
    flat = page_table.reshape(-1)
    kg = jnp.take(k_pages, flat, axis=1)  # (H, B*max_pages, page_size, C)
    kg = kg.reshape(H, B, S, C).transpose(1, 0, 2, 3)  # (B, H, S, C)
    vg = jnp.take(v_pages, flat, axis=1).reshape(H, B, S, C).transpose(1, 0, 2, 3)
    scores = jnp.einsum("bhqc,bhkc->bhqk", q[:, :, None], kg)  # (B, H, 1, S)
    valid = jnp.arange(S)[None, None, None, :] < lengths[:, None, None, None]
    scores = jnp.where(valid, scores, float("-inf"))
    probs = jax.nn.softmax(
        scores.astype(jnp.float32) / math.sqrt(C), axis=-1
    ).astype(q.dtype)
    return jnp.einsum("bhqk,bhkc->bhqc", probs, vg)[:, :, 0]


def paged_attention(
    q: Array,
    k_pages: Array,
    v_pages: Array,
    page_table: Array,
    lengths: Array,
    impl: str = "auto",
) -> Array:
    """Dispatch: Pallas kernel on TPU, XLA gather elsewhere (interpret mode
    is orders of magnitude too slow for the serving loop — same policy as
    ops/attention.py for the flash kernel)."""
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "kernel":
        return paged_attention_kernel(q, k_pages, v_pages, page_table, lengths)
    if impl == "gather":
        return paged_attention_gather(q, k_pages, v_pages, page_table, lengths)
    raise ValueError(f"unknown paged attention impl {impl!r}")


def paged_verify_attention(
    q: Array,  # (B, T, H, C) — T = k+1 speculative positions per slot
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, T) int32 — keys visible to row t of slot b
    impl: str = "auto",
) -> Array:
    """Batched multi-row paged attention for speculative verification
    (GPT.verify_step_paged): every slot scores its k+1 candidate positions
    against its own pages in ONE call. Row t of slot b attends to
    counts[b, t] keys — the caller passes lengths[b] + t + 1, which makes
    the chunk causal through the cache: all rows' K/V are written before
    the gather, and the per-row count hides the later rows.

    Gather lowering only for now (pages gathered contiguous once, like
    prefill_paged_chunk): the one-query-row online-softmax shape of the
    Pallas decode kernel above does not fit a (B, T) query block, so a
    multi-row verify kernel is the TPU upgrade path (docs/SERVING.md) —
    'auto'/'gather' both take this path, 'kernel' fails loudly instead of
    silently falling back. Same mask-then-scale-then-f32-softmax order as
    `paged_attention_gather`, so speculative greedy verify stays
    token-exact with plain paged decode (pinned by tests/test_spec.py)."""
    if impl == "kernel":
        raise NotImplementedError(
            "no Pallas verify kernel yet — multi-row paged attention runs "
            "the gather lowering (docs/SERVING.md upgrade path)"
        )
    B, T, H, C = q.shape
    _, _, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    S = max_pages * page_size
    flat = page_table.reshape(-1)
    kg = jnp.take(k_pages, flat, axis=1)  # (H, B*max_pages, page_size, C)
    kg = kg.reshape(H, B, S, C).transpose(1, 0, 2, 3)  # (B, H, S, C)
    vg = jnp.take(v_pages, flat, axis=1).reshape(H, B, S, C).transpose(1, 0, 2, 3)
    scores = jnp.einsum("bthc,bhkc->bhtk", q.astype(kg.dtype), kg)
    valid = jnp.arange(S)[None, None, None, :] < counts[:, None, :, None]
    scores = jnp.where(valid, scores, float("-inf"))
    probs = jax.nn.softmax(
        scores.astype(jnp.float32) / math.sqrt(C), axis=-1
    ).astype(q.dtype)
    return jnp.einsum("bhtk,bhkc->bthc", probs, vg)  # (B, T, H, C)
