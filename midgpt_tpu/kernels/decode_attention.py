"""Paged decode/verify attention for the continuous-batching engine, in
bf16 and int8-quantized cache modes, with optional split-K sequence
partitioning.

Decode-time attention reads K/V through a per-slot PAGE TABLE instead of a
contiguous (B, S, ...) cache: physical pages of `page_size` tokens live in a
shared (H, num_pages, page_size, C) pool (models/gpt.py PagedKVCache), and
slot b's logical page j is pool page `page_table[b, j]`. Each slot masks to
its own true length, so one compiled program serves any mix of request
lengths — the two levers the serving layer needs (vLLM-style paged memory +
FlashAttention-style work partitioning, PAPERS.md) under XLA's static-shape
constraint.

Both compiled variants — plain decode (one query row per slot) and
multi-row speculative verify (T = k+1 rows with per-row visible-key
counts, GPT.verify_step_paged) — are instantiations of ONE parameterized
kernel (kernels/attention_template.py): shared scalar-prefetched page
translation, shared online-softmax sweep (ops/online_softmax.py), shared
int8 fused-dequant read path. `split_k > 1` additionally partitions each
slot's visible key sequence over a parallel grid dimension — per-partition
raw (m, l, acc) partials merged outside the kernel — which is what keeps
the chip busy when a single long request is the whole batch (the T>=4k
single-slot regime; docs/SERVING.md "Split-K decode").

Off-TPU the dispatchers use the XLA gather fallbacks below, which mirror
the contiguous `GPT.decode_step` attention op-for-op (same einsum shapes,
same mask-then-scale-then-f32-softmax order, dequantizing right after the
page gather in int8 mode) so paged decode stays token-exact with the
single-request engine on the CPU test mesh. The split-K gather sibling
keeps the unsplit pass's fat q.K score matmul and partitions only the
softmax STATISTICS: scores reshape into split_k independent partitions,
one online-softmax block sweeps each, and partials merge with the SAME
ops/online_softmax.merge_partials math as the kernel path. Deliberately so:
a host core executes partitions sequentially either way, so the gather
split lowering aims for structure-neutrality (measured within noise of the
unsplit pass, RESULTS.md §5) while the kernel's parallel grid dimension
carries the actual long-T win on hardware (tools/bench_serve.py
--long-ctx). The kernels themselves run in interpret mode only under
their parity tests (tests/test_decode_attention.py, tests/test_split_k.py
and tests/test_quant_cache.py — interpret is too slow for the serving
tests' inner loop).
"""

from __future__ import annotations

import math
import typing as tp

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, PartitionSpec as P

from midgpt_tpu.kernels.attention_template import (
    normalize_split_k,
    paged_attention_template,
)
from midgpt_tpu.kernels.flash_attention import M_INIT, MASK
from midgpt_tpu.ops.attention import visible_mask
from midgpt_tpu.ops.online_softmax import finalize, merge_partials, online_block
from midgpt_tpu.ops.quant import dequantize_q8
from midgpt_tpu.utils.compat import shard_map

Array = jax.Array


def _repeat_kv_heads(a: Array, groups: int, axis: int) -> Array:
    """Broadcast K/V heads to the query head count (GQA gather lowerings).
    Query head h reads K/V head h // groups — same consecutive-grouping
    convention as the template's reshape spec (attention_template.py)."""
    return a if groups == 1 else jnp.repeat(a, groups, axis=axis)


def paged_attention_kernel(
    q: Array,  # (B, H_q, C) — one query token per slot
    k_pages: Array,  # (H_kv, num_pages, page_size, C) — ONE layer's pool
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32 — valid tokens per slot (0 = inactive)
    k_scale: tp.Optional[Array] = None,  # (num_pages, H_kv, page_size) f32
    v_scale: tp.Optional[Array] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Paged decode attention via the kernel template. Returns (B, H_q, C).
    int8 pools require both scale side buffers; bf16 pools take none.
    Plain decode is the template's n_rows == 1 spec: the per-row count IS
    the slot length. GQA (H_q > H_kv) and the sliding-window/sink mask are
    template specs too — the query-group fold and the windowed column mask
    live in attention_template.py, shared with the verify variant."""
    out = paged_attention_template(
        q[:, :, None, :],  # (B, H_q, 1, C)
        k_pages, v_pages, page_table,
        lengths[:, None],  # (B, 1) counts
        k_scale, v_scale, split_k=split_k,
        sliding_window=sliding_window, attn_sinks=attn_sinks,
    )
    return out[:, :, 0, :]


def _gather_pages(
    pages: Array,  # (H, num_pages, page_size, C)
    scales: tp.Optional[Array],  # (num_pages, H, page_size) f32 | None
    page_table: Array,  # (B, max_pages) int32
    out_dtype,
) -> Array:
    """Gather every slot's pages contiguous -> (B, H, S, C), dequantizing
    right after the gather in int8 mode (the CPU sibling of the kernels'
    in-VMEM dequant; ops/quant.py — exact, so gather and kernel read
    identical values from the same pool)."""
    H, _, page_size, C = pages.shape
    B, max_pages = page_table.shape
    S = max_pages * page_size
    flat = page_table.reshape(-1)
    g = jnp.take(pages, flat, axis=1)  # (H, B*max_pages, page_size, C)
    g = g.reshape(H, B, S, C).transpose(1, 0, 2, 3)  # (B, H, S, C)
    if scales is None:
        return g
    sg = jnp.take(scales, flat, axis=0)  # (B*max_pages, H, page_size)
    sg = sg.reshape(B, max_pages, H, page_size).transpose(0, 2, 1, 3)
    return dequantize_q8(g, sg.reshape(B, H, S)).astype(out_dtype)


def paged_attention_gather(
    q: Array,  # (B, H_q, C)
    k_pages: Array,  # (H_kv, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    lengths: Array,  # (B,) int32
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """XLA fallback: gather each slot's pages contiguous (dequantized in
    int8 mode), then run the exact attention ops of the contiguous
    `GPT.decode_step` (same einsum shapes, -inf mask BEFORE the
    1/sqrt(C)-scaled f32 softmax) so paged and contiguous decode agree
    token-for-token on CPU.

    split_k == 1 is that classic single pass, byte-for-byte unchanged.
    split_k > 1 keeps the SAME fat q.K score matmul and partitions only
    the softmax statistics: the masked f32 scores reshape into split_k
    independent partitions, one online-softmax block sweeps each, and
    partials merge with the same ops/online_softmax.merge_partials the
    kernel path uses — gather and kernel split lowerings share their
    merge math exactly. No scan, and no partitioned score matmul either:
    on a single host core a sequential partition loop only adds loop
    overhead and a partition-shaped dot defeats XLA's fusion of the long
    masked-softmax axis (both measured, RESULTS.md §5 — the parallel win
    belongs to the kernel's grid dimension on real hardware), while the
    stats-only split is within noise of the unsplit pass; greedy decode
    streams stay token-identical to it (tests/test_split_k.py)."""
    B, H, C = q.shape
    page_size = k_pages.shape[2]
    groups = H // k_pages.shape[0]  # GQA: query heads per K/V head
    max_pages = page_table.shape[1]
    S = max_pages * page_size
    split_k = normalize_split_k(split_k, max_pages)
    if split_k == 1:
        kg = _repeat_kv_heads(
            _gather_pages(k_pages, k_scale, page_table, q.dtype), groups, 1
        )
        vg = _repeat_kv_heads(
            _gather_pages(v_pages, v_scale, page_table, q.dtype), groups, 1
        )
        scores = jnp.einsum("bhqc,bhkc->bhqk", q[:, :, None], kg)  # (B, H, 1, S)
        valid = visible_mask(
            jnp.arange(S)[None, None, None, :],
            lengths[:, None, None, None],
            sliding_window,
            attn_sinks,
        )
        scores = jnp.where(valid, scores, float("-inf"))
        probs = jax.nn.softmax(
            scores.astype(jnp.float32) / math.sqrt(C), axis=-1
        ).astype(q.dtype)
        return jnp.einsum("bhqk,bhkc->bhqc", probs, vg)[:, :, 0]

    part_len = (max_pages // split_k) * page_size
    scale = 1.0 / math.sqrt(C)
    kg = _repeat_kv_heads(
        _gather_pages(k_pages, k_scale, page_table, q.dtype), groups, 1
    )
    vg = _repeat_kv_heads(
        _gather_pages(v_pages, v_scale, page_table, q.dtype), groups, 1
    )
    s = jnp.einsum("bhc,bhkc->bhk", q, kg).astype(jnp.float32) * scale
    s = jnp.where(
        visible_mask(
            jnp.arange(S)[None, None], lengths[:, None, None],
            sliding_window, attn_sinks,
        ),
        s,
        MASK,
    )
    # Fat dot above, partitioned statistics below: scores reshape into
    # split_k independent partitions, each swept by one online block from
    # the init stats — exactly the kernel's single-block partition sweep.
    s = s.reshape(B, H, split_k, part_len)
    m = jnp.full((B, H, split_k), M_INIT, jnp.float32)
    l = jnp.zeros((B, H, split_k), jnp.float32)
    m, _, p, l = online_block(m, l, s)
    acc = jnp.einsum(
        "bhsk,bhskc->bhsc", p.astype(vg.dtype),
        vg.reshape(B, H, split_k, part_len, C),
    ).astype(jnp.float32)
    m, l, acc = merge_partials(m, l, acc, axis=2)
    out, _ = finalize(m, l, acc, dtype=q.dtype)
    return out


def _tp_shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """Full-MANUAL shard_map over the serving mesh: every named axis is
    manual (only 'tp' exceeds size 1 on a serve mesh, parallel/serve_tp.py),
    so the body is a plain per-shard trace — exactly what a Pallas kernel
    needs, and the one shard_map form the 0.4.37 CPU backend lowers (the
    partial-manual form aborts there; utils/compat.shard_map docstring).
    check_vma off: paged attention is pointwise in heads, there is no
    replication to certify."""
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names=frozenset(mesh.axis_names),
        check_vma=False,
    )


def paged_attention(
    q: Array,
    k_pages: Array,
    v_pages: Array,
    page_table: Array,
    lengths: Array,
    impl: str = "auto",
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    mesh: tp.Optional[Mesh] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Dispatch: Pallas kernel on TPU, XLA gather elsewhere (interpret mode
    is orders of magnitude too slow for the serving loop — same policy as
    ops/attention.py for the flash kernel).

    With a tp>1 serving mesh the kernel is invoked PER SHARD through a
    full-manual shard_map: each tp shard holds H_q/tp query heads and
    H_kv/tp heads of the page pool (+ int8 scale rows) — under GQA the
    shard boundary lands between whole K/V-head GROUPS, since H_q/tp =
    groups * (H_kv/tp), so each shard's query heads read exactly its own
    pool heads (requires n_kv_heads % tp == 0, validated by the engine) —
    the page table and lengths ride in replicated, and the per-head
    online-softmax sweep needs no collective at all: the head axis is
    embarrassingly parallel, and the tp all-reduce PAYLOAD the pool feeds
    shrinks with the pool while the COUNT stays two per layer. split_k
    rides the grid (kernel) or the batched partition axis (gather) INSIDE
    each head shard, so tensor parallelism, GQA, the window mask and
    split-K all compose with zero new collectives. The gather lowering
    ignores `mesh`: it is plain jnp, and GSPMD partitions it from the
    operand shardings alone."""
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "kernel":
        if mesh is not None and mesh.shape["tp"] > 1:
            quantized = k_scale is not None
            pool = P("tp", None, None, None)  # (H_kv, pages, page_size, C)
            in_specs = [P(None, "tp", None), pool, pool, P(), P()]
            args = [q, k_pages, v_pages, page_table, lengths]
            if quantized:
                in_specs += [P(None, "tp", None)] * 2  # (pages, H_kv, ps)
                args += [k_scale, v_scale]
            fn = _tp_shard_map(
                lambda *a: paged_attention_kernel(
                    *a, split_k=split_k,
                    sliding_window=sliding_window, attn_sinks=attn_sinks,
                ),
                mesh, tuple(in_specs), P(None, "tp", None),
            )
            return fn(*args)
        return paged_attention_kernel(
            q, k_pages, v_pages, page_table, lengths, k_scale, v_scale,
            split_k=split_k, sliding_window=sliding_window,
            attn_sinks=attn_sinks,
        )
    if impl == "gather":
        return paged_attention_gather(
            q, k_pages, v_pages, page_table, lengths, k_scale, v_scale,
            split_k=split_k, sliding_window=sliding_window,
            attn_sinks=attn_sinks,
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")


# ----------------------------------------------------------------------
# Multi-row paged verify attention (speculative decoding)
# ----------------------------------------------------------------------


def paged_verify_attention_kernel(
    q: Array,  # (B, T, H_q, C)
    k_pages: Array,  # (H_kv, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, T) int32 — keys visible to row t of slot b
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Multi-row paged attention via the kernel template (n_rows == T).
    Returns (B, T, H, C). q is transposed head-major ONCE outside the
    kernel (a single small XLA transpose per verify forward) so the kernel
    works in the pool's native (H, ...) layout with no in-kernel
    transposes. Each row t masks to its OWN visible-key count cnt[b, t]
    (the caller passes lengths + t + 1, which is what makes the
    speculative chunk causal through the page table —
    GPT.verify_step_paged)."""
    out = paged_attention_template(
        q.transpose(0, 2, 1, 3),  # (B, H_q, T, C)
        k_pages, v_pages, page_table, counts,
        k_scale, v_scale, split_k=split_k,
        sliding_window=sliding_window, attn_sinks=attn_sinks,
    )
    return out.transpose(0, 2, 1, 3)  # (B, T, H_q, C)


def paged_verify_attention_gather(
    q: Array,  # (B, T, H_q, C)
    k_pages: Array,
    v_pages: Array,
    page_table: Array,
    counts: Array,  # (B, T) int32
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """XLA gather lowering of the multi-row verify attention: pages
    gathered contiguous once (dequantized in int8 mode, like
    prefill_paged_chunk), then per-row count masks over the shared buffer.
    Same mask-then-scale-then-f32-softmax order as
    `paged_attention_gather`, so speculative greedy verify stays
    token-exact with plain paged decode (pinned by tests/test_spec.py).
    split_k > 1 is the same stats-only split as the decode gather (fat
    score matmul kept, one online block per scores partition,
    merge_partials outside), applied per row after the per-row count
    mask."""
    B, T, H, C = q.shape
    page_size = k_pages.shape[2]
    groups = H // k_pages.shape[0]  # GQA: query heads per K/V head
    max_pages = page_table.shape[1]
    S = max_pages * page_size
    split_k = normalize_split_k(split_k, max_pages)
    if split_k == 1:
        kg = _repeat_kv_heads(
            _gather_pages(k_pages, k_scale, page_table, q.dtype), groups, 1
        )
        vg = _repeat_kv_heads(
            _gather_pages(v_pages, v_scale, page_table, q.dtype), groups, 1
        )
        scores = jnp.einsum("bthc,bhkc->bhtk", q.astype(kg.dtype), kg)
        valid = visible_mask(
            jnp.arange(S)[None, None, None, :],
            counts[:, None, :, None],
            sliding_window,
            attn_sinks,
        )
        scores = jnp.where(valid, scores, float("-inf"))
        probs = jax.nn.softmax(
            scores.astype(jnp.float32) / math.sqrt(C), axis=-1
        ).astype(q.dtype)
        return jnp.einsum("bhtk,bhkc->bthc", probs, vg)  # (B, T, H, C)

    part_len = (max_pages // split_k) * page_size
    scale = 1.0 / math.sqrt(C)
    kg = _repeat_kv_heads(
        _gather_pages(k_pages, k_scale, page_table, q.dtype), groups, 1
    )
    vg = _repeat_kv_heads(
        _gather_pages(v_pages, v_scale, page_table, q.dtype), groups, 1
    )
    s = jnp.einsum("bthc,bhkc->bhtk", q.astype(kg.dtype), kg).astype(
        jnp.float32
    ) * scale  # (B, H, T, S) — the unsplit fat dot
    s = jnp.where(
        visible_mask(
            jnp.arange(S)[None, None, None],
            counts[:, None, :, None],
            sliding_window,
            attn_sinks,
        ),
        s,
        MASK,
    )
    s = s.reshape(B, H, T, split_k, part_len)
    m = jnp.full((B, H, T, split_k), M_INIT, jnp.float32)
    l = jnp.zeros((B, H, T, split_k), jnp.float32)
    m, _, p, l = online_block(m, l, s)
    acc = jnp.einsum(
        "bhtsk,bhskc->bhtsc", p.astype(vg.dtype),
        vg.reshape(B, H, split_k, part_len, C),
    ).astype(jnp.float32)
    m, l, acc = merge_partials(m, l, acc, axis=3)
    out, _ = finalize(m, l, acc, dtype=q.dtype)  # (B, H, T, C)
    return out.transpose(0, 2, 1, 3)  # (B, T, H, C)


def paged_verify_attention(
    q: Array,  # (B, T, H, C) — T = k+1 speculative positions per slot
    k_pages: Array,  # (H, num_pages, page_size, C)
    v_pages: Array,
    page_table: Array,  # (B, max_pages) int32
    counts: Array,  # (B, T) int32 — keys visible to row t of slot b
    impl: str = "auto",
    k_scale: tp.Optional[Array] = None,
    v_scale: tp.Optional[Array] = None,
    mesh: tp.Optional[Mesh] = None,
    split_k: int = 1,
    sliding_window: int = 0,
    attn_sinks: int = 0,
) -> Array:
    """Batched multi-row paged attention for speculative verification
    (GPT.verify_step_paged): every slot scores its k+1 candidate positions
    against its own pages in ONE call. Row t of slot b attends to
    counts[b, t] keys — the caller passes lengths[b] + t + 1, which makes
    the chunk causal through the cache: all rows' K/V are written before
    the read, and the per-row count hides the later rows. Under a sliding
    window each row additionally masks to the last `sliding_window` of its
    own visible keys (+ the `attn_sinks` prefix) — the window slides per
    ROW, so the speculative chunk stays causal-consistent with plain
    windowed decode.

    Dispatch mirrors `paged_attention`: the template-instantiated multi-row
    kernel on TPU (bf16 and int8 — interpret-mode parity in
    tests/test_quant_cache.py and tests/test_split_k.py), the XLA gather
    lowering elsewhere; on a tp>1 mesh the kernel runs per shard over
    H_q/tp query heads and H_kv/tp pool heads via the same full-manual
    shard_map, collective-free, with split_k riding inside each shard."""
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "kernel":
        if mesh is not None and mesh.shape["tp"] > 1:
            quantized = k_scale is not None
            pool = P("tp", None, None, None)
            row_spec = P(None, None, "tp", None)  # q/out (B, T, H_q, C)
            in_specs = [row_spec, pool, pool, P(), P()]
            args = [q, k_pages, v_pages, page_table, counts]
            if quantized:
                in_specs += [P(None, "tp", None)] * 2
                args += [k_scale, v_scale]
            fn = _tp_shard_map(
                lambda *a: paged_verify_attention_kernel(
                    *a, split_k=split_k,
                    sliding_window=sliding_window, attn_sinks=attn_sinks,
                ),
                mesh, tuple(in_specs), row_spec,
            )
            return fn(*args)
        return paged_verify_attention_kernel(
            q, k_pages, v_pages, page_table, counts, k_scale, v_scale,
            split_k=split_k, sliding_window=sliding_window,
            attn_sinks=attn_sinks,
        )
    if impl == "gather":
        return paged_verify_attention_gather(
            q, k_pages, v_pages, page_table, counts, k_scale, v_scale,
            split_k=split_k, sliding_window=sliding_window,
            attn_sinks=attn_sinks,
        )
    raise ValueError(f"unknown paged verify attention impl {impl!r}")
