"""Observability: metrics logging, throughput/MFU accounting, profiler hooks.

Mirrors the reference's surface (wandb + tqdm postfix + jax.profiler,
reference train.py:191-220, launch.py:38-68) but degrades gracefully: wandb
is optional (proc-0 only when present), and every metric always lands in
`rundir/metrics.jsonl` + stdout so headless TPU runs are inspectable.
"""

from __future__ import annotations

import json
import os
import time
import typing as tp

import jax

from midgpt_tpu.config import ExperimentConfig
from midgpt_tpu.models.gpt import GPTConfig

try:  # wandb is an optional dependency
    import wandb as _wandb
except Exception:  # pragma: no cover - depends on environment
    _wandb = None


def flops_per_token(cfg: GPTConfig, seq_len: tp.Optional[int] = None) -> float:
    """Training FLOPs/token: 6N for the matmuls (fwd 2N + bwd 4N) plus the
    12*L*D*T attention-scores term (PaLM appendix B accounting)."""
    T = seq_len or cfg.block_size
    D, L, V = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    if cfg.n_experts > 0:
        # ACTIVE-expert accounting (the MoE convention): top_k expert MLPs
        # + the router per token. The masked-dense lowering EXECUTES all E
        # experts, so reported MFU under-counts by E/top_k there — honest
        # for the useful-FLOPs metric.
        mlp = min(cfg.moe_top_k, cfg.n_experts) * 8 * D * D + cfg.n_experts * D
    else:
        mlp = 8 * D * D
    n_params = V * D + L * (4 * D * D + mlp + 2 * cfg.head_dim) + V * D
    # Count the tied embedding once, like reference count_params (model.py:161).
    n_params -= V * D
    return 6.0 * n_params + 12.0 * L * D * T


# Peak bf16 TFLOP/s per chip by TPU generation (public figures).
_PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}


def device_peak_flops(device: tp.Optional[jax.Device] = None) -> tp.Optional[float]:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for name, flops in _PEAK_FLOPS.items():
        if name in kind:
            return flops
    return None


def mfu(tokens_per_sec: float, cfg: GPTConfig, n_devices: int) -> tp.Optional[float]:
    peak = device_peak_flops()
    if peak is None:
        return None
    return tokens_per_sec * flops_per_token(cfg) / (peak * n_devices)


class MetricLogger:
    """jsonl + stdout always; wandb when available (proc 0 only)."""

    def __init__(self, config: ExperimentConfig, *, use_wandb: bool = True, resume_id: tp.Optional[str] = None):
        self.is_main = jax.process_index() == 0
        self.rundir = config.rundir
        self._file = None
        self._wandb = None
        if self.is_main and self.rundir and not self.rundir.startswith("gs://"):
            os.makedirs(self.rundir, exist_ok=True)
            self._file = open(os.path.join(self.rundir, "metrics.jsonl"), "a")
        if self.is_main and use_wandb and _wandb is not None and not config.debug:
            import dataclasses

            if resume_id is None:
                resume_id = self._persistent_run_id()
            self._wandb = _wandb.init(
                project="midgpt-tpu",
                id=resume_id,
                resume="allow",
                config=dataclasses.asdict(config),
            )

    def _persistent_run_id(self) -> tp.Optional[str]:
        """Read or create `rundir/wandb_id.txt` so a relaunched run continues
        the same wandb run (reference launch.py:59-68)."""
        if not self.rundir:
            return None
        path = os.path.join(self.rundir, "wandb_id.txt")
        try:
            if self.rundir.startswith("gs://"):
                import gcsfs

                fs = gcsfs.GCSFileSystem()
                if fs.exists(path):
                    with fs.open(path, "r") as f:
                        return f.read().strip()
                run_id = _wandb.util.generate_id()
                with fs.open(path, "w") as f:
                    f.write(run_id)
                return run_id
            if os.path.exists(path):
                with open(path) as f:
                    return f.read().strip()
            run_id = _wandb.util.generate_id()
            os.makedirs(self.rundir, exist_ok=True)
            with open(path, "w") as f:
                f.write(run_id)
            return run_id
        except Exception:
            return None  # id persistence is best-effort; never block training

    def log(self, step: int, metrics: tp.Dict[str, float]) -> None:
        if not self.is_main:
            return
        record = {"step": step, "time": time.time(), **metrics}
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
        if self._wandb is not None:
            self._wandb.finish()


class Progress:
    """Live single-line progress bar with a loss/lr/throughput postfix
    (reference train.py:190-220 drives tqdm the same way). Process 0 only,
    and only when stderr is a terminal — headless/nohup runs keep clean
    line-per-interval logs from MetricLogger instead. Degrades to a no-op
    when tqdm is unavailable."""

    def __init__(self, total: int, first_step: int = 0, enabled: bool = True):
        self._bar = None
        if not enabled or jax.process_index() != 0:
            return
        try:
            import sys

            from tqdm import tqdm

            if sys.stderr.isatty():
                self._bar = tqdm(
                    total=total, initial=first_step, dynamic_ncols=True,
                    desc="train", unit="step",
                )
        except Exception:  # pragma: no cover - tqdm is optional
            self._bar = None

    @property
    def active(self) -> bool:
        return self._bar is not None

    def update(self, n: int = 1, **postfix: tp.Any) -> None:
        if self._bar is None:
            return
        if postfix:
            self._bar.set_postfix(postfix, refresh=False)
        self._bar.update(n)

    def close(self) -> None:
        if self._bar is not None:
            self._bar.close()


class Profiler:
    """One-shot trace of the first post-warmup step (reference train.py:205-211)."""

    def __init__(self, rundir: str, enabled: bool):
        self.rundir, self.enabled, self._active = rundir, enabled, False

    def maybe_start(self, step: int, at_step: int = 0) -> None:
        if self.enabled and step == at_step:
            jax.profiler.start_trace(self.rundir or "/tmp/midgpt_trace")
            self._active = True

    def maybe_stop(self, wait_for: tp.Any = None) -> None:
        if self._active:
            if wait_for is not None:
                jax.block_until_ready(wait_for)
            jax.profiler.stop_trace()
            self._active = False
