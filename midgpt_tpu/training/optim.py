"""Optimizer: AdamW with *independent* (LR-decoupled) weight decay.

Exact reference chain (reference train.py:147-159): global-norm clip 1.0 →
adam moments (b1=0.9, b2 from config) → add params * (weight_decay /
learning_rate) → scale by warmup-cosine schedule → negate. Dividing the decay
by the peak LR before the schedule multiplies makes the *effective* decay
independent of the learning rate (the small-scale-proxies recipe) while still
following the schedule. Decay applies to ALL params, including norm scales
and embeddings, as in the reference.
"""

from __future__ import annotations

import typing as tp

import optax

from midgpt_tpu.config import ExperimentConfig


def make_schedule(config: ExperimentConfig) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=config.lr_decay_steps,
        end_value=config.min_lr,
    )


def make_optimizer(
    config: ExperimentConfig,
) -> tp.Tuple[optax.GradientTransformation, optax.Schedule]:
    schedule = make_schedule(config)
    optimizer = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.scale_by_adam(b2=config.beta2),
        optax.add_decayed_weights(config.weight_decay / config.learning_rate),
        optax.scale_by_schedule(schedule),
        optax.scale(-1.0),
    )
    return optimizer, schedule


def opt_step_count(opt_state: tp.Any) -> tp.Any:
    """The schedule step from a chain state (reference train.py:150-152 peeks
    opt_state[3].count; here we match the schedule state by type to survive
    chain reorders)."""
    for sub in opt_state:
        if isinstance(sub, optax.ScaleByScheduleState):
            return sub.count
    raise ValueError("no ScaleByScheduleState found in the optimizer chain")
