from midgpt_tpu.training.optim import make_optimizer
from midgpt_tpu.training.train import train, make_train_step

__all__ = ["make_optimizer", "train", "make_train_step"]
