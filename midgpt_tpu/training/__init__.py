from midgpt_tpu.training.optim import make_optimizer
from midgpt_tpu.training.train import make_runtime, make_train_step, train

__all__ = ["make_optimizer", "make_runtime", "train", "make_train_step"]
