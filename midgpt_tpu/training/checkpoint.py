"""Async Orbax checkpointing with a *named* state tree + integrity manifests.

Upgrades over the reference, which saves bare `tree_leaves` tuples
(reference train.py:215) so restore requires rebuilding the exact tree
structure in code (reference sample.py:111-137 reconstructs the whole
optimizer chain just to get a skeleton):

  * state is a named dict {"params": ..., "opt_state": ...} serialized by
    key path — robust to incidental structure changes and readable by tools;
  * restore is sharding-aware: each host reads only its shards, directly
    into the live arrays' shardings (same property as reference
    train.py:179-187);
  * saves are async (training continues during the TensorStore write), with
    a final barrier on close (reference train.py:224-225).

Fault tolerance (docs/ROBUSTNESS.md):

  * **Write retry.** The synchronous part of a save (queueing the
    TensorStore write) retries `write_retries` times with exponential
    backoff before raising CheckpointWriteError — a transient filesystem
    hiccup must not kill a run that has hours of state in memory. Disk
    exhaustion (the `ckpt_enospc` fault: ENOSPC after partial bytes land)
    rides the same schedule; the partial, un-manifested step directory is
    swept before each retry and on budget exhaustion, so it is never
    visible to `latest_verified_step` and never shadows the last good
    checkpoint.
  * **Checksum manifests.** After an async save lands, a per-file sha256
    manifest is committed (atomic rename) into the step directory. A step
    is *verified* iff every file matches its manifest. `restore` re-verifies
    and raises CheckpointCorruptError with a per-file diagnosis; resume uses
    `latest_verified_step`, so a checkpoint truncated by a mid-save kill is
    skipped, never half-restored. Manifests are local-path only; gs://
    rundirs keep the plain orbax behavior.
  * **Verified-only GC** (local paths). Orbax's own max_to_keep would delete
    the previous checkpoint the moment a new save finalizes — before anyone
    checked the new one is readable. Here GC is explicit: a step is deleted
    only once `max_to_keep` (default 2) NEWER verified steps exist, so a
    crash mid-save can never destroy the only good checkpoint.

Layout note: checkpoints are saved as named Composite items ("params",
"opt_state") plus a "format" JSON marker and a `midgpt_manifest.json`
integrity manifest; this is the framework's only supported layout — there
is no reader for other orbax layouts.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import shutil
import typing as tp

import jax
import orbax.checkpoint as ocp

from midgpt_tpu.obs import flight_recorder
from midgpt_tpu.robustness import faults
from midgpt_tpu.robustness.backoff import retry_with_backoff
from midgpt_tpu.robustness.errors import (
    CheckpointCorruptError,
    CheckpointWriteError,
    SimulatedPreemption,
)

# Format marker saved alongside the state and verified at restore. Version
# history:
#   2 — wqkv rows were flat (3D, D) head-major interleaved; a flat stacked
#       checkpoint would restore into it without any shape error but every
#       head would read other heads' projection rows, so restore REFUSES
#       checkpoints without a matching marker.
#   3 — wqkv is (3, D, D) (models/gpt.py AttentionParams): shape-distinct
#       from both flat layouts, so cross-layout restores also fail loudly at
#       the orbax level; the marker remains the explicit, diagnosable gate.
#       tools/migrate_ckpt_v2_v3.py converts v2 checkpoints in place.
FORMAT = {"version": 3, "qkv_layout": "qkv3"}

MANIFEST_NAME = "midgpt_manifest.json"


def _abstract_like(tree: tp.Any) -> tp.Any:
    def conv(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.ShapeDtypeStruct) and x.sharding is None:
            # Orbax needs a concrete sharding to deserialize into; default to
            # replicated-on-default-device (the sampler's single-chip case).
            return jax.ShapeDtypeStruct(
                x.shape,
                x.dtype,
                sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
            )
        return x

    return jax.tree.map(conv, tree)


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(step_dir: str, step: int) -> None:
    """Commit a per-file sha256 manifest for a finalized step directory.

    The manifest is written to a temp file and os.replace'd into place, so a
    crash mid-write leaves the step *unverified* (no manifest), never
    half-verified. Exposed module-level so tools (migrate_ckpt_v2_v3) can
    stamp the checkpoints they produce."""
    files: tp.Dict[str, tp.Dict[str, tp.Any]] = {}
    for root, dirnames, names in os.walk(step_dir):
        dirnames.sort()
        for name in sorted(names):
            if name == MANIFEST_NAME:
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            files[rel] = {"size": os.path.getsize(path), "sha256": _hash_file(path)}
    manifest = {"step": step, "format": FORMAT, "files": files}
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))


def verify_manifest(step_dir: str) -> tp.List[str]:
    """Re-checksum a step directory against its manifest. Returns a list of
    human-readable problems — empty means verified."""
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return [f"no {MANIFEST_NAME} in {step_dir} (save never completed?)"]
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        return [f"unreadable manifest {mpath}: {e}"]
    problems: tp.List[str] = []
    for rel, rec in manifest.get("files", {}).items():
        path = os.path.join(step_dir, rel)
        if not os.path.exists(path):
            problems.append(f"missing item file: {rel}")
            continue
        size = os.path.getsize(path)
        if size != rec["size"]:
            problems.append(
                f"truncated item file: {rel} ({size} bytes, manifest says "
                f"{rec['size']})"
            )
            continue
        if _hash_file(path) != rec["sha256"]:
            problems.append(f"checksum mismatch: {rel}")
    return problems


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 2,
        save_interval_steps: int = 1000,
        write_retries: int = 3,
        retry_backoff_sec: float = 0.5,
    ):
        self._local = not directory.startswith("gs://")
        if self._local:
            directory = os.path.abspath(directory)  # TensorStore requires absolute
        self._dir = directory
        options = ocp.CheckpointManagerOptions(
            # Local paths: GC is ours (verified-only, module docstring); on
            # gs:// there are no manifests, so keep orbax's rolling delete.
            max_to_keep=None if self._local else max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)
        self.max_to_keep = max_to_keep
        self.write_retries = max(1, write_retries)
        self.retry_backoff_sec = retry_backoff_sec
        # Step whose async save has been queued but whose manifest is not
        # yet committed; finalized at the next save/wait/restore/close.
        self._pending: tp.Optional[int] = None

    # -- step inventory -------------------------------------------------

    def all_steps(self) -> tp.List[int]:
        return sorted(self._mngr.all_steps())

    def latest_step(self) -> tp.Optional[int]:
        return self._mngr.latest_step()

    def _step_dir(self, step: int) -> tp.Optional[str]:
        if not self._local:
            return None
        direct = os.path.join(self._dir, str(step))
        if os.path.isdir(direct):
            return direct
        if os.path.isdir(self._dir):
            # Tolerate prefixed step names (orbax step_name_format variants).
            for name in os.listdir(self._dir):
                tail = name.rsplit("_", 1)[-1]
                if tail.isdigit() and int(tail) == step:
                    return os.path.join(self._dir, name)
        return None

    def _has_manifest(self, step: int) -> bool:
        d = self._step_dir(step)
        return d is not None and os.path.exists(os.path.join(d, MANIFEST_NAME))

    def verify(self, step: int) -> tp.List[str]:
        """Problems with the step's integrity; [] means verified."""
        d = self._step_dir(step)
        if d is None:
            return [f"step {step} has no directory under {self._dir}"]
        return verify_manifest(d)

    def is_verified(self, step: int) -> bool:
        return self._local and not self.verify(step)

    def verified_steps(self) -> tp.List[int]:
        return [s for s in self.all_steps() if self.is_verified(s)]

    def weights_version(self, step: int) -> tp.Optional[str]:
        """'<step>:<sha12>' identity of a step's committed manifest — the
        value serving surfaces as `weights_version` on stats()/loadgen
        lines so every round is attributable to exactly one verified
        checkpoint (sampling/ops.py hot-swap; "inline" means params were
        passed directly). Hashing the manifest FILE (which already records
        per-item sha256s) gives a stable content identity without
        re-hashing tensor bytes. None when the step has no manifest."""
        d = self._step_dir(step)
        if d is None:
            return None
        path = os.path.join(d, MANIFEST_NAME)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as fh:
            digest = hashlib.sha256(fh.read()).hexdigest()
        return f"{step}:{digest[:12]}"

    def latest_verified_step(self) -> tp.Optional[int]:
        """Newest step whose manifest verifies — the only safe resume point.

        Directories with no manifests at all (pre-manifest runs, gs://) fall
        back to orbax's latest step; a MIXED directory trusts only verified
        steps, so a save truncated by a mid-save kill is skipped rather than
        resumed into."""
        self.wait()
        steps = self.all_steps()
        verified = [s for s in steps if self.is_verified(s)]
        if verified:
            return verified[-1]
        if steps and not any(self._has_manifest(s) for s in steps):
            return steps[-1]
        return None

    # -- save -----------------------------------------------------------

    def should_save(self, step: int) -> bool:
        """Would a non-forced save at `step` actually persist? Lets the train
        loop pay its pre-save health sync only on real save steps."""
        return bool(self._mngr.should_save(step))

    def save(self, step: int, state: tp.Dict[str, tp.Any], *, force: bool = False) -> bool:
        """Queue an async save of named items (e.g. {"params": ..., "opt_state": ...});
        the manager filters by save_interval_steps unless `force` (used for the
        final step of a run and emergency preemption saves).

        The synchronous part (queueing the write) retries with exponential
        backoff; the async part is verified and manifest-stamped at the next
        barrier (`wait`/next `save`/`close`)."""
        if not force and not self._mngr.should_save(step):
            return False
        self._finalize_pending()
        if step in self._mngr.all_steps() and not self.is_verified(step):
            # A leftover from a crashed/killed earlier attempt at this step
            # (e.g. after a rollback): it is garbage — clear it so the fresh
            # save does not collide with StepAlreadyExists.
            self._mngr.delete(step)
        args = ocp.args.Composite(
            format=ocp.args.JsonSave(FORMAT),
            **{name: ocp.args.StandardSave(item) for name, item in state.items()},
        )
        def _queue_write() -> bool:
            if faults.should_fire("ckpt_io_error"):
                raise IOError(
                    "injected transient checkpoint-write failure "
                    "(faults: ckpt_io_error)"
                )
            if faults.should_fire("ckpt_enospc"):
                # Disk exhaustion mid-write: partial bytes land in the step
                # directory (no manifest — the atomic commit never ran),
                # then the write dies with ENOSPC. The retry below must
                # first sweep the partial so a recovered attempt starts
                # from a clean step dir.
                if self._local:
                    d = os.path.join(self._dir, str(step))
                    os.makedirs(d, exist_ok=True)
                    with open(os.path.join(d, "partial_item.bin"), "wb") as fh:
                        fh.write(b"\x00" * 1024)
                raise OSError(
                    errno.ENOSPC,
                    "injected ENOSPC mid checkpoint write (faults: ckpt_enospc)",
                )
            self._clear_partial(step)
            return self._mngr.save(step, args=args, force=True)

        try:
            # Shared retry discipline (robustness/backoff.py) — the same
            # schedule the serving front door applies to BackpressureError.
            # The span holds only the SYNCHRONOUS queue (+ retries); the
            # TensorStore write itself is async and lands under the
            # ckpt.finalize span at the next barrier.
            with flight_recorder().tracer.span(
                "ckpt.save_queue", "ckpt", "train"
            ):
                queued = retry_with_backoff(
                    _queue_write,
                    retries=self.write_retries,
                    base_s=self.retry_backoff_sec,
                    retry_on=(OSError,),  # includes IOError; TensorStore failures
                )
        except OSError as e:
            # Budget exhausted: sweep any partial bytes a failed attempt
            # left (ENOSPC), so the step never shows up in all_steps() —
            # an un-manifested partial must not shadow the last verified
            # checkpoint nor trip a later save's StepAlreadyExists.
            self._clear_partial(step)
            raise CheckpointWriteError(
                f"checkpoint save at step {step} under {self._dir} failed "
                f"{self.write_retries} attempt(s); last error: {e}",
                step=step,
                attempts=self.write_retries,
                directory=str(self._dir),
            ) from e
        if faults.should_fire("kill_mid_save", step=step):
            # Model SIGKILL between the TensorStore write and the manifest
            # commit: bytes on disk, one item truncated, no manifest —
            # `latest_verified_step` must skip this step on resume.
            self._mngr.wait_until_finished()
            self._corrupt_one_item(step)
            raise SimulatedPreemption(f"simulated kill mid-save at step {step}")
        self._pending = step
        return bool(queued)

    def _clear_partial(self, step: int) -> None:
        """Remove an un-manifested partial step directory (the ENOSPC
        leftovers). A dir WITH a manifest is a real checkpoint — never
        touched here; verified-only GC owns its lifecycle."""
        if not self._local:
            return
        d = self._step_dir(step)
        if d is not None and not os.path.exists(os.path.join(d, MANIFEST_NAME)):
            shutil.rmtree(d, ignore_errors=True)

    def _corrupt_one_item(self, step: int) -> None:
        d = self._step_dir(step)
        if d is None:
            return
        # Truncate the largest non-manifest file (a tensor shard, in
        # practice) to half — realistic partial-write damage.
        candidates = []
        for root, _, names in os.walk(d):
            for name in names:
                if name == MANIFEST_NAME:
                    continue
                p = os.path.join(root, name)
                candidates.append((os.path.getsize(p), p))
        if not candidates:
            return
        size, path = max(candidates)
        with open(path, "rb+") as fh:
            fh.truncate(max(1, size // 2))

    def _finalize_pending(self) -> None:
        """Barrier on the in-flight async save, then commit its manifest,
        verify it, and (only on success) garbage-collect older steps."""
        step, self._pending = self._pending, None
        if step is None:
            return
        tr = flight_recorder().tracer
        with tr.span("ckpt.finalize", "ckpt", "train"):
            self._mngr.wait_until_finished()
            self._mngr.check_for_errors()
            if not self._local:
                return
            d = self._step_dir(step)
            if d is None:
                return
            write_manifest(d, step)
            if faults.should_fire("truncate_ckpt_item", step=step):
                # Corruption AFTER the manifest committed (bit rot / bad
                # copy): the recorded hashes no longer match the bytes.
                self._corrupt_one_item(step)
            with tr.span("ckpt.verify", "ckpt", "train"):
                problems = self.verify(step)
            if problems:
                tr.instant(
                    "ckpt.verify_failed", "ckpt", "train",
                    args={"step": step, "n_problems": len(problems)},
                )
                if jax.process_index() == 0:
                    print(
                        f"WARNING: checkpoint step {step} failed post-save "
                        "verification and will not be resumed from:\n  "
                        + "\n  ".join(problems)
                    )
                return  # keep older verified steps; no GC off an unverified save
            tr.instant("ckpt.verified", "ckpt", "train", args={"step": step})
            self._gc()

    def _gc(self) -> None:
        """Delete steps older than the `max_to_keep`-newest verified steps.

        Runs only after a fresh save verified, so the previous checkpoint
        outlives the new one's verification — a crash at any point leaves at
        least one verified step on disk."""
        verified = self.verified_steps()
        if len(verified) <= self.max_to_keep:
            return
        cutoff = verified[-self.max_to_keep]
        for s in self.all_steps():
            if s < cutoff:
                self._mngr.delete(s)

    # -- restore --------------------------------------------------------

    def restore(self, step: int, like: tp.Dict[str, tp.Any]) -> tp.Dict[str, tp.Any]:
        """Restore named items into the structure/shardings of `like` (live or
        abstract trees). Restoring a SUBSET of the saved items is supported —
        the sampler restores only {"params": ...} without touching the
        optimizer state."""
        self._finalize_pending()
        available = self.all_steps()
        if step not in available:
            raise ValueError(
                f"no checkpoint for step {step} under {self._dir}; available "
                f"steps: {available or 'none'} (verified: "
                f"{self.verified_steps() or 'none'})"
            )
        if self._has_manifest(step):
            problems = self.verify(step)
            if problems:
                raise CheckpointCorruptError(
                    f"checkpoint step {step} under {self._dir} fails integrity "
                    "verification — refusing to restore corrupt state:\n  "
                    + "\n  ".join(problems)
                    + f"\nVerified steps available: {self.verified_steps() or 'none'}",
                    step=step,
                    problems=problems,
                )
        # Validate the format marker FIRST, on its own, so a marker problem
        # (pre-v2 checkpoint, foreign layout) is diagnosed as such and a
        # genuine state-restore failure (e.g. shape mismatch) isn't.
        try:
            fmt = self._mngr.restore(
                step, args=ocp.args.Composite(format=ocp.args.JsonRestore())
            )["format"]
        except (FileNotFoundError, KeyError, ValueError) as e:
            raise ValueError(
                f"checkpoint step {step} has no readable 'format' marker — it "
                f"predates checkpoint format v{FORMAT['version']} (or is not "
                "this framework's layout) and would restore silently wrong "
                f"(see training/checkpoint.py FORMAT). Available steps: "
                f"{available}. Underlying error: {e}"
            ) from e
        if fmt != FORMAT:
            hint = (
                " If this is a v2 checkpoint (flat head-major wqkv), convert "
                "it with tools/migrate_ckpt_v2_v3.py."
                if isinstance(fmt, dict) and fmt.get("version") == 2
                else ""
            )
            raise ValueError(
                f"checkpoint format mismatch at step {step}: saved marker "
                f"{fmt}, this build reads {FORMAT} — refusing a silently-"
                f"wrong restore. Available steps under {self._dir}: "
                f"{available}.{hint}"
            )
        args = ocp.args.Composite(
            **{
                name: ocp.args.StandardRestore(_abstract_like(item))
                for name, item in like.items()
            }
        )
        restored = self._mngr.restore(step, args=args)
        return {name: restored[name] for name in like}

    # -- lifecycle ------------------------------------------------------

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._finalize_pending()

    def close(self) -> None:
        self.wait()
        self._mngr.close()
