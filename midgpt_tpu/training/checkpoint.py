"""Async Orbax checkpointing with a *named* state tree.

Upgrades over the reference, which saves bare `tree_leaves` tuples
(reference train.py:215) so restore requires rebuilding the exact tree
structure in code (reference sample.py:111-137 reconstructs the whole
optimizer chain just to get a skeleton):

  * state is a named dict {"params": ..., "opt_state": ...} serialized by
    key path — robust to incidental structure changes and readable by tools;
  * restore is sharding-aware: each host reads only its shards, directly
    into the live arrays' shardings (same property as reference
    train.py:179-187);
  * saves are async (training continues during the TensorStore write), with
    a final barrier on close (reference train.py:224-225).

Works on local paths and gs:// rundirs alike (TensorStore handles both).

Layout note: checkpoints are saved as named Composite items ("params",
"opt_state"); this is the framework's only supported layout — there is no
reader for other orbax layouts.
"""

from __future__ import annotations

import typing as tp

import jax
import orbax.checkpoint as ocp


def _abstract_like(tree: tp.Any) -> tp.Any:
    def conv(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.ShapeDtypeStruct) and x.sharding is None:
            # Orbax needs a concrete sharding to deserialize into; default to
            # replicated-on-default-device (the sampler's single-chip case).
            return jax.ShapeDtypeStruct(
                x.shape,
                x.dtype,
                sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
            )
        return x

    return jax.tree.map(conv, tree)


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 1,
        save_interval_steps: int = 1000,
    ):
        if not directory.startswith("gs://"):
            import os

            directory = os.path.abspath(directory)  # TensorStore requires absolute
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)

    def latest_step(self) -> tp.Optional[int]:
        return self._mngr.latest_step()

    def save(self, step: int, state: tp.Dict[str, tp.Any], *, force: bool = False) -> bool:
        """Queue an async save of named items (e.g. {"params": ..., "opt_state": ...});
        the manager filters by save_interval_steps unless `force` (used for the
        final step of a run)."""
        args = ocp.args.Composite(
            **{name: ocp.args.StandardSave(item) for name, item in state.items()}
        )
        return self._mngr.save(step, args=args, force=force)

    def restore(self, step: int, like: tp.Dict[str, tp.Any]) -> tp.Dict[str, tp.Any]:
        """Restore named items into the structure/shardings of `like` (live or
        abstract trees). Restoring a SUBSET of the saved items is supported —
        the sampler restores only {"params": ...} without touching the
        optimizer state."""
        args = ocp.args.Composite(
            **{
                name: ocp.args.StandardRestore(_abstract_like(item))
                for name, item in like.items()
            }
        )
        restored = self._mngr.restore(step, args=args)
        return {name: restored[name] for name in like}

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
