"""Async Orbax checkpointing with a *named* state tree.

Upgrades over the reference, which saves bare `tree_leaves` tuples
(reference train.py:215) so restore requires rebuilding the exact tree
structure in code (reference sample.py:111-137 reconstructs the whole
optimizer chain just to get a skeleton):

  * state is a named dict {"params": ..., "opt_state": ...} serialized by
    key path — robust to incidental structure changes and readable by tools;
  * restore is sharding-aware: each host reads only its shards, directly
    into the live arrays' shardings (same property as reference
    train.py:179-187);
  * saves are async (training continues during the TensorStore write), with
    a final barrier on close (reference train.py:224-225).

Works on local paths and gs:// rundirs alike (TensorStore handles both).

Layout note: checkpoints are saved as named Composite items ("params",
"opt_state") plus a "format" JSON marker; this is the framework's only
supported layout — there is no reader for other orbax layouts.
"""

from __future__ import annotations

import typing as tp

import jax
import orbax.checkpoint as ocp

# Format marker saved alongside the state and verified at restore. Version
# history:
#   2 — wqkv rows were flat (3D, D) head-major interleaved; a flat stacked
#       checkpoint would restore into it without any shape error but every
#       head would read other heads' projection rows, so restore REFUSES
#       checkpoints without a matching marker.
#   3 — wqkv is (3, D, D) (models/gpt.py AttentionParams): shape-distinct
#       from both flat layouts, so cross-layout restores also fail loudly at
#       the orbax level; the marker remains the explicit, diagnosable gate.
#       tools/migrate_ckpt_v2_v3.py converts v2 checkpoints in place.
FORMAT = {"version": 3, "qkv_layout": "qkv3"}


def _abstract_like(tree: tp.Any) -> tp.Any:
    def conv(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.ShapeDtypeStruct) and x.sharding is None:
            # Orbax needs a concrete sharding to deserialize into; default to
            # replicated-on-default-device (the sampler's single-chip case).
            return jax.ShapeDtypeStruct(
                x.shape,
                x.dtype,
                sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
            )
        return x

    return jax.tree.map(conv, tree)


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 1,
        save_interval_steps: int = 1000,
    ):
        if not directory.startswith("gs://"):
            import os

            directory = os.path.abspath(directory)  # TensorStore requires absolute
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)

    def latest_step(self) -> tp.Optional[int]:
        return self._mngr.latest_step()

    def should_save(self, step: int) -> bool:
        """Would a non-forced save at `step` actually persist? Lets the train
        loop pay its pre-save health sync only on real save steps."""
        return bool(self._mngr.should_save(step))

    def save(self, step: int, state: tp.Dict[str, tp.Any], *, force: bool = False) -> bool:
        """Queue an async save of named items (e.g. {"params": ..., "opt_state": ...});
        the manager filters by save_interval_steps unless `force` (used for the
        final step of a run)."""
        args = ocp.args.Composite(
            format=ocp.args.JsonSave(FORMAT),
            **{name: ocp.args.StandardSave(item) for name, item in state.items()},
        )
        return self._mngr.save(step, args=args, force=force)

    def restore(self, step: int, like: tp.Dict[str, tp.Any]) -> tp.Dict[str, tp.Any]:
        """Restore named items into the structure/shardings of `like` (live or
        abstract trees). Restoring a SUBSET of the saved items is supported —
        the sampler restores only {"params": ...} without touching the
        optimizer state."""
        # Validate the format marker FIRST, on its own, so a marker problem
        # (pre-v2 checkpoint, foreign layout) is diagnosed as such and a
        # genuine state-restore failure (e.g. shape mismatch) isn't.
        try:
            fmt = self._mngr.restore(
                step, args=ocp.args.Composite(format=ocp.args.JsonRestore())
            )["format"]
        except (FileNotFoundError, KeyError, ValueError) as e:
            raise ValueError(
                f"checkpoint step {step} has no readable 'format' marker — it "
                f"predates checkpoint format v{FORMAT['version']} (or is not "
                "this framework's layout) and would restore silently wrong "
                f"(see training/checkpoint.py FORMAT). Underlying error: {e}"
            ) from e
        if fmt != FORMAT:
            raise ValueError(
                f"checkpoint format mismatch: saved {fmt}, this build reads "
                f"{FORMAT} — refusing a silently-wrong restore"
            )
        args = ocp.args.Composite(
            **{
                name: ocp.args.StandardRestore(_abstract_like(item))
                for name, item in like.items()
            }
        )
        restored = self._mngr.restore(step, args=args)
        return {name: restored[name] for name in like}

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
