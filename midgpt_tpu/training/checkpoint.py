"""Async Orbax checkpointing with a *named* state tree.

Upgrades over the reference, which saves bare `tree_leaves` tuples
(reference train.py:215) so restore requires rebuilding the exact tree
structure in code (reference sample.py:111-137 reconstructs the whole
optimizer chain just to get a skeleton):

  * state is a named dict {"params": ..., "opt_state": ...} serialized by
    key path — robust to incidental structure changes and readable by tools;
  * restore is sharding-aware: each host reads only its shards, directly
    into the live arrays' shardings (same property as reference
    train.py:179-187);
  * saves are async (training continues during the TensorStore write), with
    a final barrier on close (reference train.py:224-225).

Works on local paths and gs:// rundirs alike (TensorStore handles both).
"""

from __future__ import annotations

import typing as tp

import jax
import orbax.checkpoint as ocp


def _abstract_like(tree: tp.Any) -> tp.Any:
    def conv(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(conv, tree)


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 1,
        save_interval_steps: int = 1000,
    ):
        if not directory.startswith("gs://"):
            import os

            directory = os.path.abspath(directory)  # TensorStore requires absolute
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)

    def latest_step(self) -> tp.Optional[int]:
        return self._mngr.latest_step()

    def save(self, step: int, state: tp.Any, *, force: bool = False) -> bool:
        """Queue an async save; the manager filters by save_interval_steps
        unless `force` (used for the final step of a run)."""
        return self._mngr.save(step, args=ocp.args.StandardSave(state), force=force)

    def restore(self, step: int, like: tp.Any) -> tp.Any:
        """Restore into the structure/shardings of `like` (live or abstract)."""
        abstract = _abstract_like(like)
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
