"""Training runtime: one compiled SPMD step + the host-side experiment loop.

Step semantics match the reference hot path (reference train.py:69-97) for
val-loss parity:
  * fp32 master params, cast to the compute dtype (bf16) once per step;
  * `lax.scan` over `g_accum_iters` microbatches, each microgradient
    re-constrained to the FSDP layout (so accumulation happens *sharded* —
    GSPMD reduce-scatters each microstep, reference train.py:87) and
    accumulated in fp32 pre-scaled by 1/G (no epilogue divide); losses
    averaged on the scalar;
  * optax update + apply, params re-constrained, buffers donated.

The whole step — microbatching, collectives, optimizer — is ONE XLA program
(jit with donate_argnums), executing identically on every device of every
host. Eval runs `eval_steps` fresh seeded batches at compute dtype with
dropout off (reference train.py:99-117).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np
import optax

from midgpt_tpu.config import ExperimentConfig
from midgpt_tpu.data.dataset import TokenDataset
from midgpt_tpu.models.gpt import GPT, GPTParams
from midgpt_tpu.obs import dump_flight_recorder, flight_recorder
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain, named_shardings
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.robustness import faults, preempt
from midgpt_tpu.robustness.errors import DivergenceError
from midgpt_tpu.robustness.watchdog import StepWatchdog
from midgpt_tpu.training.checkpoint import CheckpointManager, _abstract_like
from midgpt_tpu.training.metrics import MetricLogger, Profiler, Progress, mfu
from midgpt_tpu.training.optim import make_optimizer, make_schedule

Array = jax.Array


def health_flag(grad, loss: Array, prev_loss: Array) -> Array:
    """Sticky post-update health, folded into the reported loss.

    Returns the loss to report: `loss` when this step AND every earlier step
    were healthy, else NaN. Three properties (each pinned by
    tests/test_train.py):

    * **Leaf-wise finiteness, not global-norm finiteness.** isfinite of
      `optax.global_norm(grad)` squares in fp32, so large-but-finite grads
      (|g| ~ 1e20) overflow the squared sum to inf and would flag a step
      that clip_by_global_norm(1.0) handles fine (scale -> ~0, training
      recovers) — a spurious hard stop (ADVICE r4). The per-leaf
      `all(isfinite)` reductions read the same grad leaves the optimizer's
      clip reads; measured free on the v5e G=1 124M bench (48.5/48.9% MFU
      vs the 48.8% r3/r4 baseline, within the ±0.3 noise band — unlike the
      non-CSE'd global_norm(updates) variant, which cost −1.4 MFU).
    * **Sticky via the reported loss.** A non-finite step at an iteration
      that is neither a log nor a save step could otherwise leave NaN only
      in optimizer state (e.g. Adam mu of a rare embedding row whose later
      grads are 0) while every later loss/grad is finite — and a later save
      would persist it (ADVICE r4). Threading the previous REPORTED loss in
      and NaN-poisoning on `~isfinite(prev_loss)` makes badness sticky by
      induction, with no extra carry in the step signature: every later
      log raise / pre-save gate / final force-save sees NaN.
    * **Soundness by induction** (unchanged): state_t finite ∧ grad_t finite
      ⇒ clip/adam/wd/schedule all finite ⇒ state_{t+1} finite; so a NaN/Inf
      anywhere first shows in some step's grad leaves or loss. The base case
      for restored checkpoints is the resume-time sweep below. The induction
      is a property of THIS chain (training/optim.py: clip(1.0) is
      0-norm-safe, adam bias correction needs beta2<1 — enforced by config
      validation, eps>0); revisit if the chain changes."""
    grads_ok = jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grad)])
    )
    healthy = grads_ok & jnp.isfinite(loss) & jnp.isfinite(prev_loss)
    return jnp.where(healthy, loss, jnp.nan)


def make_train_step(
    config: ExperimentConfig,
    optimizer: optax.GradientTransformation,
    mesh,
    param_specs,
) -> tp.Tuple[tp.Callable, tp.Callable, tp.Callable]:
    """Build (step, eval_loss, eval_loss_many) jitted functions."""
    model_cfg = config.model_config
    if mesh.shape["tp"] > 1 and model_cfg.qkv_proj == "fused":
        # The fused lowering reshapes the tp-sharded feature axis into the
        # merged 3D axis (a reshard); the batched per-third form keeps each
        # of q/k/v independently column-sharded (models/gpt.py _project_qkv).
        import dataclasses

        model_cfg = dataclasses.replace(model_cfg, qkv_proj="split3")
    compute_dtype = jnp.dtype(config.compute_dtype)
    G = config.g_accum_iters

    # Sequence parallelism: ring attention is bound to the mesh here (the
    # model is mesh-agnostic; attention is its only cross-token op). The
    # GSPMD-sharded wrapper serves the implicit-FSDP train loss and all
    # eval paths; the explicit shard_map path calls the ring directly
    # inside its own body (no nesting — see make_shard_map_loss).
    attn_fn = None
    if model_cfg.attn_impl == "ring":
        from midgpt_tpu.parallel.ring_attention import ring_attention_sharded

        attn_fn = functools.partial(
            ring_attention_sharded,
            mesh=mesh,
            block_size=model_cfg.attn_block_size,
            # tp x sp composition: the ring is head-independent, so with a
            # real 'tp' axis each device runs the ring over its head shard.
            head_axis="tp" if mesh.shape["tp"] > 1 else None,
        )
    elif model_cfg.attn_impl == "ulysses":
        from midgpt_tpu.parallel.ulysses import ulysses_attention_sharded

        attn_fn = functools.partial(
            ulysses_attention_sharded,
            mesh=mesh,
            block_size=model_cfg.attn_block_size,
            head_axis="tp" if mesh.shape["tp"] > 1 else None,
        )

    loss_and_grad_fn = None  # set only by the 1F1B pipeline schedule
    if mesh.shape["pp"] > 1:
        from midgpt_tpu.parallel.pipeline import (
            make_pipeline_loss,
            make_pipeline_loss_and_grad,
        )

        # The GPipe loss serves eval under BOTH schedules (same math,
        # dropout-free); 1F1B replaces only the value_and_grad of training.
        _pp_loss = make_pipeline_loss(
            model_cfg, mesh, param_specs, config.loss_chunk_tokens,
            config.loss_remat_chunks,
            microbatches=config.pipeline_microbatches,
        )
        if config.pipeline_schedule == "1f1b":
            loss_and_grad_fn = make_pipeline_loss_and_grad(
                model_cfg, mesh, param_specs, config.loss_chunk_tokens,
                config.loss_remat_chunks,
                microbatches=config.pipeline_microbatches,
            )

        def loss_fn(params_c: GPTParams, x: Array, y: Array, key) -> Array:
            return _pp_loss(params_c, x, y, key)

    elif config.fsdp_mode == "shard_map":
        from midgpt_tpu.parallel.shard_map_fsdp import make_shard_map_loss

        _sm_loss = make_shard_map_loss(
            model_cfg, mesh, param_specs, config.loss_chunk_tokens,
            config.loss_remat_chunks,
            sequence_parallel=(
                model_cfg.attn_impl
                if model_cfg.attn_impl in ("ring", "ulysses")
                else None
            ),
        )

        def loss_fn(params_c: GPTParams, x: Array, y: Array, key) -> Array:
            return _sm_loss(params_c, x, y, key)

    else:
        # Router load-balance pressure (config.moe_aux_coef): CE +
        # coef * aux. Gated at trace time — with the default coef of 0.0
        # the aux term is never even requested, so this path's compiled
        # program is byte-identical to the pre-knob loss (zero-impact pin
        # in tests/test_moe.py).
        use_moe_aux = (
            config.moe_aux_coef != 0.0 and model_cfg.n_experts > 0
        )

        def loss_fn(params_c: GPTParams, x: Array, y: Array, key) -> Array:
            h = GPT.hidden(
                model_cfg, params_c, x, key=key, inference=False, attn_fn=attn_fn,
                return_moe_aux=use_moe_aux,
            )
            if use_moe_aux:
                h, aux = h
            ce = fused_linear_cross_entropy(
                h, params_c.lm_head, y, config.loss_chunk_tokens,
                config.loss_remat_chunks,
            )
            return ce + config.moe_aux_coef * aux if use_moe_aux else ce

    def cast_compute(params: GPTParams) -> GPTParams:
        return jax.tree.map(
            lambda p: p.astype(compute_dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params,
        )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params: GPTParams, opt_state, x_GBT: Array, y_GBT: Array, key,
             prev_loss=0.0):
        params_c = cast_compute(params)
        keys = jax.random.split(key, G)

        value_and_grad = (
            loss_and_grad_fn
            if loss_and_grad_fn is not None
            else jax.value_and_grad(loss_fn)
        )
        if G == 1:
            # No accumulation machinery: skip the zeros-init + add + divide
            # passes over a full parameter-sized buffer (~3 HBM sweeps).
            loss, grad = value_and_grad(params_c, x_GBT[0], y_GBT[0], keys[0])
            grad = constrain(grad, param_specs, mesh)
            grad = jax.tree.map(lambda g, p: g.astype(p.dtype), grad, params)
        else:

            # The /G rides each accumulate as a fused elementwise scale, so
            # the epilogue divide's parameter-sized read+write sweep
            # disappears. (Measured: the whole accumulation machinery is
            # ~3 ms of a 2.2 s G=16 step at 124M — RESULTS.md §1 — so no
            # first-microstep peel: it would double the compiled graph for
            # a win within noise.) Math is the reference's sharded-fp32
            # accumulation (reference train.py:85-94) up to f32
            # reassociation of the mean.
            inv_G = 1.0 / G

            def microstep(grad_acc, xyk):
                x, y, k = xyk
                loss, grad = value_and_grad(params_c, x, y, k)
                grad = constrain(grad, param_specs, mesh)
                grad_acc = jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype) * inv_G, grad_acc, grad
                )
                return grad_acc, loss

            grad_init = jax.tree.map(jnp.zeros_like, params)
            grad, losses = jax.lax.scan(microstep, grad_init, (x_GBT, y_GBT, keys))
            loss = jnp.mean(losses)
        updates, opt_state = optimizer.update(grad, opt_state, params)
        params = optax.apply_updates(params, updates)
        params = constrain(params, param_specs, mesh)
        # Post-UPDATE health, folded into the reported loss: the scalar loss
        # is computed from the PRE-update params, so on its own it shows
        # divergence one step after the poisoned state could already have
        # been checkpointed. Semantics + cost rationale: health_flag above.
        # Callers that thread the previous reported loss back in (the train
        # loop) get sticky poisoning; one-shot callers (benches, parity
        # tests) pass nothing and get the per-step check.
        loss = health_flag(grad, loss, prev_loss)
        return params, opt_state, loss

    def _eval_loss_one(params_c: GPTParams, x: Array, y: Array) -> Array:
        if mesh.shape["pp"] > 1:
            # GSPMD cannot shard a scan over its length axis, so the dense
            # backbone would all-gather the stage-sharded blocks; evaluate
            # through the same GPipe schedule instead (dropout-free, so the
            # train-mode loss IS the eval loss).
            return loss_fn(params_c, x, y, None)
        h = GPT.hidden(model_cfg, params_c, x, inference=True, attn_fn=attn_fn)
        return fused_linear_cross_entropy(
            h, params_c.lm_head, y, config.loss_chunk_tokens,
            config.loss_remat_chunks,
        )

    @jax.jit
    def eval_loss(params: GPTParams, x: Array, y: Array) -> Array:
        return _eval_loss_one(cast_compute(params), x, y)

    @jax.jit
    def eval_loss_many(params: GPTParams, x_NBT: Array, y_NBT: Array) -> Array:
        """SUMMED loss over a stacked (N, B, T) eval set in one device-side
        scan. Returning the sum (not the mean) lets `evaluate` chunk the
        eval set to a fixed host-memory budget over the same windows, with
        one division at the end (equal to the monolithic mean up to f32
        re-association of the chunk subtotals). Still asynchronous — the
        caller syncs once per eval, vs the reference's 200 sequential jit
        calls + float() round-trips (reference train.py:107-117)."""
        params_c = cast_compute(params)

        def body(total, xy):
            x, y = xy
            return total + _eval_loss_one(params_c, x, y), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (x_NBT, y_NBT))
        return total

    return step, eval_loss, eval_loss_many


def init_state(config: ExperimentConfig, mesh) -> tp.Tuple[GPTParams, tp.Any, tp.Any, tp.Any]:
    """Sharded-at-birth params + optimizer state (never materialized dense).

    Returns (params, opt_state, param_specs, optimizer)."""
    optimizer, _ = make_optimizer(config)
    abstract_params = jax.eval_shape(
        lambda k: GPT.init(config.model_config, k), jax.random.PRNGKey(0)
    )
    # Spec rule: GPipe layer-axis sharding when the mesh has a real 'pp'
    # axis (parallel/pipeline.py), else Megatron tp x fsdp (parallel/tp.py)
    # — which with mesh tp=1 reduces to the plain FSDP rule exactly (pinned
    # by test_tp.py).
    if mesh.shape["pp"] > 1:
        # Same (tree, mesh, shard_model, min_size) signature as the tp rule:
        # layer axis over 'pp', large leaves additionally over 'fsdp'.
        from midgpt_tpu.parallel.pipeline import pipeline_param_specs as spec_rule

    else:
        from midgpt_tpu.parallel.tp import tp_param_specs

        spec_rule = functools.partial(tp_param_specs, vocab_parallel=config.tp_vocab)
    param_specs = spec_rule(
        abstract_params, mesh, config.shard_model, config.fsdp_min_size
    )

    def init_fn(key):
        params = GPT.init(config.model_config, key)
        params = jax.tree.map(lambda p: p.astype(jnp.dtype(config.param_dtype)), params)
        return constrain(params, param_specs, mesh)

    params = jax.jit(init_fn)(jax.random.PRNGKey(config.seed))

    abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
    opt_specs = spec_rule(
        abstract_opt, mesh, config.shard_model, config.fsdp_min_size
    )
    opt_state = jax.jit(
        optimizer.init, out_shardings=named_shardings(opt_specs, mesh)
    )(params)
    return params, opt_state, param_specs, optimizer


def evaluate(
    config: ExperimentConfig,
    eval_loss_many: tp.Callable,
    params: GPTParams,
    dataset: TokenDataset,
    split: str,
    mesh,
    step_idx: int,
) -> float:
    """Stream the eval set through fixed-size device programs, one sync.

    Host memory is bounded to `eval_host_chunk` batches at a time (at
    openwebtext_mh scale the whole 200-batch eval set is ~1.7 GB of int32
    per host — an avoidable cliff). Each chunk is dispatched asynchronously
    and only the final total is pulled to host, so the single-sync property
    of the batched eval is preserved; the chunked result sums the same
    windows (accum_slice) and differs from the monolithic one only by f32
    re-association of chunk subtotals."""
    # leading N axis ~ the accum axis; sequence shards over 'sp' when on
    spec = batch_spec(with_accum=True, shard_seq=mesh.shape["sp"] > 1)
    n = 1 if config.debug else config.eval_steps
    chunk = max(1, min(n, config.eval_host_chunk))
    total = None
    for lo in range(0, n, chunk):
        m = min(chunk, n - lo)
        x, y = dataset.batch(
            split,
            # decorrelate eval batches from train batches and across evals
            1_000_000_000 + step_idx,
            config.model_config.block_size,
            config.batch_size // jax.process_count(),
            g_accum_iters=n,
            accum_slice=(lo, m),
        )
        xg = make_global_batch(x, mesh, spec)
        yg = make_global_batch(y, mesh, spec)
        part = eval_loss_many(params, xg, yg)  # async device scalar (sum)
        total = part if total is None else total + part
    return float(total) / n


def _all_finite(tree) -> Array:
    """Device-side finiteness sweep over every floating leaf of `tree`."""
    return jnp.all(
        jnp.array(
            [
                jnp.all(jnp.isfinite(l))
                for l in jax.tree.leaves(tree)
                if jnp.issubdtype(l.dtype, jnp.floating)
            ]
        )
    )


@dataclasses.dataclass
class TrainRuntime:
    """Everything about a run that survives a restart attempt.

    The supervisor's rollback (robustness/supervisor.py) re-enters `train`
    after restoring a checkpoint; rebuilding the jitted step there would
    recompile the entire program per attempt (minutes at scale — and pinned
    against by tests/test_robustness.py with the test_recompile_pins.py
    methodology). A TrainRuntime carries the mesh, dataset, and every jitted
    callable across attempts; only host-side config fields (e.g.
    `data_step_offset`) may differ between the attempts that share one.
    """

    mesh: tp.Any
    dataset: TokenDataset
    optimizer: tp.Any
    schedule: tp.Callable
    param_specs: tp.Any
    step: tp.Callable
    eval_loss: tp.Callable
    eval_loss_many: tp.Callable
    # Abstract {"params", "opt_state"} with shardings — the restore template,
    # so a rollback attempt never needs live donated buffers from a previous
    # attempt.
    abstract_state: tp.Dict[str, tp.Any]
    finite_check: tp.Callable
    n_params: int
    _initial: tp.Optional[tp.Tuple[tp.Any, tp.Any]] = None

    def take_initial(self, config: ExperimentConfig) -> tp.Tuple[tp.Any, tp.Any]:
        """Hand out the freshly initialized state (once); re-init if a later
        attempt starts from scratch (the first attempt donated the buffers)."""
        if self._initial is None:
            params, opt_state, _, _ = init_state(config, self.mesh)
            return params, opt_state
        state, self._initial = self._initial, None
        return state

    def rebuild(
        self,
        config: ExperimentConfig,
        *,
        devices: tp.Optional[tp.Sequence[tp.Any]] = None,
    ) -> "TrainRuntime":
        """A fresh runtime on a DIFFERENT topology (elastic resume).

        `devices` is the new slice (default: every visible device); the
        mesh's data axis is re-derived for the new count and fsdp clamped
        by make_mesh's divisor rule, so the same config resumes on whatever
        the scheduler gives back. The dataset is shared — the positional
        sampler is device-count-independent, which is what keeps the global
        batch order (and so the loss trajectory) continuous across the
        move. The step program necessarily recompiles ONCE for the new
        mesh; the warm-then-count pin in tests/test_robustness.py holds it
        to exactly one."""
        return make_runtime(config, devices=devices, dataset=self.dataset)


def make_runtime(
    config: ExperimentConfig,
    *,
    devices: tp.Optional[tp.Sequence[tp.Any]] = None,
    dataset: tp.Optional[TokenDataset] = None,
) -> TrainRuntime:
    """Build the mesh/dataset/compiled-step bundle `train` runs on.

    `devices` pins the mesh to an explicit slice (elastic resume,
    TrainRuntime.rebuild): the data axis is re-derived for the new count
    (the `data=-1` inference in parallel/mesh.py, with fsdp clamped by its
    divisor rule), so ONE config builds a valid mesh on whatever topology
    the run lands on. `dataset` reuses an already-open TokenDataset — the
    positional sampler is device-count-independent, which is the property
    that keeps the global batch order continuous across a mesh change."""
    mesh_cfg = config.mesh
    if devices is not None:
        mesh_cfg = dataclasses.replace(mesh_cfg, data=-1)
    mesh = make_mesh(mesh_cfg, devices=devices)
    n_proc = jax.process_count()
    assert config.batch_size % n_proc == 0, "global batch must divide process count"
    if dataset is None:
        dataset = TokenDataset(
            config.data_dir, seed=config.data_seed, shard_by_process=n_proc > 1
        )
    params, opt_state, param_specs, optimizer = init_state(config, mesh)
    schedule = make_schedule(config)
    step, eval_loss, eval_loss_many = make_train_step(
        config, optimizer, mesh, param_specs
    )
    return TrainRuntime(
        mesh=mesh,
        dataset=dataset,
        optimizer=optimizer,
        schedule=schedule,
        param_specs=param_specs,
        step=step,
        eval_loss=eval_loss,
        eval_loss_many=eval_loss_many,
        abstract_state={
            "params": _abstract_like(params),
            "opt_state": _abstract_like(opt_state),
        },
        finite_check=jax.jit(_all_finite),
        n_params=GPT.count_params(params),
        _initial=(params, opt_state),
    )


def train(
    config: ExperimentConfig, *, runtime: tp.Optional[TrainRuntime] = None
) -> dict:
    """Run the experiment; returns final metrics (for tests/benches).

    `runtime` lets a supervisor re-enter after a rollback without
    recompiling anything (TrainRuntime docstring). Resume picks the newest
    *verified* checkpoint (training/checkpoint.py manifests), so a save
    truncated by a preemption is skipped, not restored."""
    rt = runtime if runtime is not None else make_runtime(config)
    mesh, dataset, schedule = rt.mesh, rt.dataset, rt.schedule
    step, eval_loss_many = rt.step, rt.eval_loss_many
    local_bs = config.batch_size // jax.process_count()
    if jax.process_index() == 0:
        print(f"Model has {rt.n_params:,} parameters.")

    mngr = None
    first_step = 0
    params = opt_state = None
    if not config.debug and config.rundir:
        mngr = CheckpointManager(
            config.rundir,
            max_to_keep=config.ckpt_max_to_keep,
            save_interval_steps=config.eval_interval,
            write_retries=config.ckpt_write_retries,
            retry_backoff_sec=config.ckpt_retry_backoff_sec,
        )
        resume_step = mngr.latest_verified_step()
        if resume_step is not None:
            state = mngr.restore(resume_step, rt.abstract_state)
            params, opt_state = state["params"], state["opt_state"]
            first_step = resume_step + 1
            # Base case of the per-step health induction (the in-step check
            # watches grads, which cannot see a corrupted RESTORED state):
            # one device-side finiteness sweep of params + opt_state at
            # resume, one sync, never again. The manifest guards the bytes;
            # this guards the VALUES (a v2->v3 migration bug, a save of
            # NaN state by older code).
            if not bool(rt.finite_check((params, opt_state))):
                raise FloatingPointError(
                    f"checkpoint step {resume_step} in {config.rundir} "
                    "restored non-finite values — it is corrupt; do not "
                    "resume from it."
                )
    if params is None:
        params, opt_state = rt.take_initial(config)

    logger = MetricLogger(config)
    profiler = Profiler(config.rundir, enabled=config.debug)
    progress = Progress(config.max_steps, first_step, enabled=not config.debug)
    if os.environ.get("MIDGPT_VIZ_SHARDING") and jax.process_index() == 0:
        # Startup sharding diagnostic (reference sample.py:181-182): how the
        # largest weight and one batch land on the mesh.
        try:
            jax.debug.visualize_array_sharding(params.blocks.attn.wqkv[0])
        except Exception as e:  # diagnostic only — never block training
            print(f"visualize_array_sharding unavailable: {e}")
    data_sp = batch_spec(with_accum=True, shard_seq=mesh.shape["sp"] > 1)
    # Positional key stream: fold the DATA step index into the base key so
    # resumed runs continue the exact dropout-key sequence (the data sampler
    # is already positional; this makes the whole step a function of the
    # data index). `data_step_offset` shifts both streams together: after a
    # divergence rollback the supervisor advances it so the replayed steps
    # sample PAST the poisoned window — deterministically, since the offset
    # is plain config.
    base_key = jax.random.PRNGKey(config.seed)
    T = config.model_config.block_size
    metrics: tp.Dict[str, float] = {}
    import time as _time

    t_last, tokens_since = _time.time(), 0
    # Sticky health carrier (health_flag): the previous reported loss feeds
    # the next step; once NaN, always NaN, so no later save can persist a
    # state poisoned at an un-inspected step. Committed mesh-replicated
    # placement, matching the step's own loss output: an uncommitted
    # jnp.zeros here gives iteration 1 a different input-sharding aval than
    # every later iteration, silently compiling the whole step TWICE (found
    # by the pass-2 compile counter; pinned in tests/test_recompile_pins.py).
    replicated = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    loss = jax.device_put(jnp.zeros((), jnp.float32), replicated)
    from midgpt_tpu.analysis.hlo_audit import jit_cache_size

    step_cache_size = functools.partial(jit_cache_size, step)
    warned_recompile = False
    preempted = False
    # Training-side flight recorder (midgpt_tpu/obs/): per-step spans and
    # lifecycle instants land in the process-global ring; crash paths
    # (DivergenceError in the supervisor, the preempt branch below) dump it
    # to the rundir as a Chrome trace for postmortems. Host-side only —
    # spans never cross the jit boundary, so the step program is untouched.
    _tr = flight_recorder().tracer
    # Hung-step watchdog (robustness/watchdog.py): the loop's host<->device
    # sync points go through `_sync` so a wedged dispatch (tunnel down,
    # device hung) is bounded by `watchdog_deadline_s` instead of blocking
    # the process forever. Off by default: `_sync` is then a plain float()
    # — no thread, no event, zero machinery (pinned by the watchdog-off
    # zero-extra-programs test in tests/test_robustness.py).
    wd = (
        StepWatchdog(
            config.watchdog_deadline_s,
            escalate=config.watchdog_escalate,
            rundir=config.rundir,
        )
        if config.watchdog_deadline_s > 0
        else None
    )

    def _sync(arr, itr: int, data_itr: int) -> float:
        # The `hang_step` fault wedges the force ITSELF (a never-set
        # event), modeling the failure where float() never returns — so
        # only the watchdog's worker-thread inversion can end the wait.
        hang = faults.should_fire("hang_step", step=data_itr)

        def force() -> float:
            if hang:
                threading.Event().wait()
            return float(arr)

        if wd is None:
            return force()
        return wd.sync(force, step=itr, label="train.loss_sync")

    try:
        for itr in range(first_step, config.max_steps):
            if itr % config.eval_interval == 0:
                with _tr.span("train.eval", "train", "train"):
                    metrics["loss/train"] = evaluate(
                        config, eval_loss_many, params, dataset, "train", mesh, itr
                    )
                    metrics["loss/val"] = evaluate(
                        config, eval_loss_many, params, dataset, "val", mesh, itr
                    )
                logger.log(itr, {k: metrics[k] for k in ("loss/train", "loss/val")})
                t_last, tokens_since = _time.time(), 0  # eval pauses don't count

            data_itr = itr + config.data_step_offset
            x, y = dataset.batch("train", data_itr, T, local_bs, config.g_accum_iters)
            xg = make_global_batch(x, mesh, data_sp)
            yg = make_global_batch(y, mesh, data_sp)
            step_key = jax.random.fold_in(base_key, data_itr)
            profiler.maybe_start(itr, at_step=first_step + 1)
            # Span covers host-side batch feed + async ENQUEUE of the one
            # step program — device time shows up at the log-interval float
            # sync, not here (the tunnel-safe measurement discipline;
            # tools/profile_summary.py --correlate lines host spans up
            # against xplane device time).
            with _tr.span("train.step", "train", "train"):
                params, opt_state, loss = step(params, opt_state, xg, yg, step_key, loss)
            profiler.maybe_stop(wait_for=loss)

            if faults.should_fire("nan_grad", step=data_itr):
                # Poison the sticky carrier exactly as a NaN gradient would
                # (health_flag folds grad badness into the reported loss).
                # Same committed replicated aval as the real carrier, so the
                # injection cannot recompile the step.
                loss = jax.device_put(jnp.full((), jnp.nan, jnp.float32), replicated)
            if faults.should_fire("preempt", step=data_itr):
                preempt.request()
            if faults.should_fire("resume_reshard", step=data_itr):
                # Same exit mechanics as a preemption; the DRIVER
                # (tools/chaos_run.py) restarts on a different device count,
                # exercising the cross-mesh resharding resume path
                # (TrainRuntime.rebuild + on_resume_mesh in the supervisor).
                preempt.request()

            tokens_since += config.batch_size * config.g_accum_iters * T
            if itr % config.log_interval == 0:
                loss_f = _sync(loss, itr, data_itr)
                if not np.isfinite(loss_f):
                    # Divergence guard (no reference counterpart — its NaN
                    # runs burn wall-clock until someone looks at wandb):
                    # stop loudly at the already-paid log sync, WITHOUT
                    # saving the poisoned params over the rolling
                    # checkpoint, and say where the last good state is. The
                    # supervisor catches this, rolls back, and skips the
                    # window (robustness/supervisor.py).
                    last_good = (
                        mngr.latest_verified_step() if mngr is not None else None
                    )
                    _tr.instant(
                        "train.divergence", "train", "train",
                        args={"step": itr, "last_good": last_good},
                    )
                    raise DivergenceError(
                        f"non-finite loss ({loss_f}) at step {itr} — training "
                        "has diverged. Last good checkpoint: "
                        + (f"step {last_good} in {config.rundir}"
                           if last_good is not None else "none was saved")
                        + ". Lower learning_rate or raise warmup_steps and "
                        "resume.",
                        step=itr,
                        last_good_step=last_good,
                        rundir=config.rundir,
                    )
                dt = _time.time() - t_last
                tok_s = tokens_since / dt if dt > 0 else 0.0
                t_last, tokens_since = _time.time(), 0
                # Recompile watch (graftcheck pass-2 hook): the whole step is
                # ONE XLA program, so its jit cache must stay at exactly one
                # entry. Growth means some input's shape/dtype is unstable
                # across steps — the silent per-step-recompile failure mode
                # CLAUDE.md warns about, easily >10x wall-clock, invisible in
                # the loss. Warn at the already-paid log sync; pinned in
                # tests/test_recompile_pins.py.
                n_programs = step_cache_size()
                if n_programs is not None and n_programs > 1 and not warned_recompile:
                    warned_recompile = True
                    if jax.process_index() == 0:
                        print(
                            f"WARNING: train step has compiled {n_programs} distinct "
                            "programs — input shapes/dtypes are unstable across "
                            "steps and every recompile stalls the device "
                            "(run graftcheck --audit / check batch shapes)"
                        )
                metrics.update(
                    {
                        "loss/optimized": loss_f,
                        "lr": float(schedule(itr)),
                        "throughput/tokens_per_sec": tok_s,
                    }
                )
                m = mfu(tok_s, config.model_config, jax.device_count())
                if m is not None:
                    metrics["throughput/mfu"] = m
                logger.log(itr, dict(metrics))
                if progress.active:
                    progress.update(
                        0, loss=f"{loss_f:.4f}", lr=f"{metrics['lr']:.2e}",
                        tok_s=f"{tok_s:,.0f}",
                    )
                elif jax.process_index() == 0:
                    print(
                        f"step {itr}: loss {loss_f:.4f} lr {metrics['lr']:.2e} "
                        f"tok/s {tok_s:,.0f}"
                    )
            progress.update(1)
            if mngr is not None and mngr.should_save(itr):
                # One device sync per SAVE interval (not per step): never let
                # a poisoned state overwrite the rolling checkpoints.
                if not np.isfinite(_sync(loss, itr, data_itr)):
                    last_good = mngr.latest_verified_step()
                    _tr.instant(
                        "train.divergence", "train", "train",
                        args={"step": itr, "last_good": last_good},
                    )
                    raise DivergenceError(
                        f"non-finite training state at step {itr} — refusing "
                        "to overwrite the rolling checkpoint. Last good "
                        f"checkpoint: step {last_good} in {config.rundir}. "
                        "Lower learning_rate or raise warmup_steps and resume.",
                        step=itr,
                        last_good_step=last_good,
                        rundir=config.rundir,
                    )
                mngr.save(itr, {"params": params, "opt_state": opt_state})
            if itr % config.preempt_check_interval == 0 and preempt.any_host_requested():
                # Preemption (SIGTERM/SIGINT or the `preempt` fault): one
                # SYNCHRONOUS emergency save at this step boundary, then a
                # clean exit. The flag is replicated across hosts
                # (robustness/preempt.py), so every host takes this branch
                # at the same itr — no host-divergent control flow around
                # the collectives inside `step`.
                grace = config.preempt_grace_s
                req_at = preempt.requested_at()
                save_late = bool(
                    grace > 0
                    and req_at is not None
                    and _time.monotonic() - req_at > grace
                )
                if save_late:
                    # The grace budget was spent before the save could even
                    # START (a long step or eval sat between the signal and
                    # this boundary): beginning a multi-second checkpoint
                    # write now risks a SIGKILL mid-write. Skip it LOUDLY —
                    # ledger note + flight-recorder dump below — and let
                    # resume fall back to the last verified checkpoint.
                    _tr.instant(
                        "train.preempt_save_skipped", "train", "train",
                        args={"step": itr, "grace_s": grace},
                    )
                    if config.rundir and not config.rundir.startswith("gs://"):
                        from midgpt_tpu.robustness import supervisor as _sup

                        _sup.append_note(
                            config.rundir,
                            {"event": "preempt_save_skipped", "step": itr,
                             "grace_s": grace},
                        )
                    if jax.process_index() == 0:
                        print(
                            f"preemption: grace budget ({grace:g}s) already "
                            f"spent at step {itr} — skipping the emergency "
                            "save; resume falls back to the last verified "
                            "checkpoint"
                        )
                elif (
                    mngr is not None
                    and mngr.latest_step() != itr  # interval save just landed?
                    and np.isfinite(_sync(loss, itr, data_itr))  # not poisoned
                ):
                    mngr.save(itr, {"params": params, "opt_state": opt_state},
                              force=True)
                    mngr.wait()  # barrier + manifest: verified before we exit
                metrics["preempted"] = True
                preempted = True
                _tr.instant(
                    "train.preempt", "train", "train", args={"step": itr}
                )
                if config.rundir and jax.process_index() == 0:
                    # SIGTERM postmortem artifact: the flight recorder's
                    # crash-adjacent tail as a loadable Chrome trace
                    # (docs/OBSERVABILITY.md "Crash dumps").
                    dump_flight_recorder(config.rundir)
                if jax.process_index() == 0 and not save_late:
                    print(
                        f"preemption: emergency checkpoint at step {itr} in "
                        f"{config.rundir or '(no rundir)'}; exiting"
                    )
                break

        if not preempted:
            metrics["loss/final"] = float(
                evaluate(
                    config, eval_loss_many, params, dataset, "val", mesh,
                    config.max_steps,
                )
            )
            logger.log(config.max_steps, {"loss/val_final": metrics["loss/final"]})
            if mngr is not None:
                # Force-persist the final state unless the in-loop save
                # already did (orbax raises StepAlreadyExists on a forced
                # duplicate).
                mngr.wait()
                # Gate on the sticky loss too: a transient mid-run poisoning
                # that left NaN only in optimizer state would pass the
                # val-loss check.
                if mngr.latest_step() != config.max_steps - 1 and np.isfinite(
                    metrics["loss/final"]
                ) and np.isfinite(float(loss)):
                    mngr.save(
                        config.max_steps - 1,
                        {"params": params, "opt_state": opt_state},
                        force=True,
                    )
    finally:
        # Never abandon an in-flight async save: a raised divergence guard
        # (or any other exception) must not leave a half-written TensorStore
        # step behind — close() barriers, manifests, and GCs.
        progress.close()
        logger.close()
        if mngr is not None:
            mngr.close()
    return {"params": params, "opt_state": opt_state, "metrics": metrics}
