/* Native host-side batcher: random-window gather over a uint16 token stream.
 *
 * The training hot loop's only host-side work is assembling (x, y=x+1)
 * int32 windows from the memmapped token stream (midgpt_tpu/data/dataset.py
 * sample_batch). numpy does this as two fancy-indexing gathers, each
 * materializing a (B*G, T) index matrix and walking the stream twice with
 * per-element index arithmetic. This C kernel does one contiguous pass per
 * window — read T+1 tokens once, widen to int32, write x and y together —
 * parallelized across windows with pthreads. 7-9.5x on pod-scale host
 * batches (tools/bench_batcher.py; RESULTS.md), which keeps TPUs fed at
 * openwebtext_mh batch sizes without host-side double-buffering tricks.
 *
 * Contract (ctypes, see midgpt_tpu/native/__init__.py):
 *   sample_windows(data, n_windows, T, starts, x_out, y_out, n_threads)
 *     data:    const uint16_t*  token stream (memmap or RAM)
 *     starts:  const int64_t*   window start offsets, n_windows of them
 *     x_out:   int32_t*         (n_windows, T) row-major
 *     y_out:   int32_t*         (n_windows, T) row-major
 *
 * Bounds are the caller's responsibility (starts[i] + T < len(data)), as
 * with the numpy path it replaces. Python owns the RNG: the same seeded
 * numpy Generator produces `starts`, so native and numpy paths are
 * bit-identical (asserted in tests/test_native_batcher.py).
 */

#include <pthread.h>
#include <stdint.h>
#include <stddef.h>

typedef struct {
    const uint16_t *data;
    const int64_t *starts;
    int32_t *x_out;
    int32_t *y_out;
    int64_t t;        /* window length */
    int64_t begin;    /* first window index (inclusive) */
    int64_t end;      /* last window index (exclusive) */
} job_t;

static void *worker(void *arg)
{
    job_t *j = (job_t *)arg;
    const int64_t t = j->t;
    for (int64_t w = j->begin; w < j->end; ++w) {
        const uint16_t *src = j->data + j->starts[w];
        int32_t *x = j->x_out + w * t;
        int32_t *y = j->y_out + w * t;
        /* one pass: src[0..t] read once, x gets src[i], y gets src[i+1] */
        int32_t prev = (int32_t)src[0];
        for (int64_t i = 0; i < t; ++i) {
            int32_t next = (int32_t)src[i + 1];
            x[i] = prev;
            y[i] = next;
            prev = next;
        }
    }
    return NULL;
}

void sample_windows(const uint16_t *data, int64_t n_windows, int64_t t,
                    const int64_t *starts, int32_t *x_out, int32_t *y_out,
                    int64_t n_threads)
{
    if (n_threads < 1)
        n_threads = 1;
    if (n_threads > n_windows)
        n_threads = n_windows > 0 ? n_windows : 1;

    enum { MAX_THREADS = 64 };
    if (n_threads > MAX_THREADS)
        n_threads = MAX_THREADS;

    pthread_t tids[MAX_THREADS];
    job_t jobs[MAX_THREADS];
    int64_t per = (n_windows + n_threads - 1) / n_threads;

    int64_t spawned = 0;
    for (int64_t i = 0; i < n_threads; ++i) {
        int64_t begin = i * per;
        int64_t end = begin + per > n_windows ? n_windows : begin + per;
        if (begin >= end)
            break;
        jobs[i] = (job_t){data, starts, x_out, y_out, t, begin, end};
        if (i == n_threads - 1 || begin + per >= n_windows) {
            /* run the last slice inline — saves one thread spawn */
            worker(&jobs[i]);
            spawned = i;
            break;
        }
        pthread_create(&tids[i], NULL, worker, &jobs[i]);
        spawned = i + 1;
    }
    for (int64_t i = 0; i < spawned; ++i)
        pthread_join(tids[i], NULL);
}
