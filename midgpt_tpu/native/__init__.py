"""Native host-runtime components (C, loaded via ctypes).

The TPU compute path is JAX/XLA/Pallas; the host runtime around it is where
native code earns its keep. Currently: the data batcher (batcher.c) — the
only host-side work on the training hot loop.

The shared library is built on demand with the system C compiler into this
package directory (`_batcher.so`), once, at first use. No pybind11 and no
build-system hook: ctypes + cc keeps the extension working from a plain
checkout (and cross-compiles trivially on TPU-VM hosts via setup_hosts.sh).
Every entry point falls back to the numpy implementation when the toolchain
or the build is unavailable — the native path is an accelerator, never a
requirement. Parity is asserted bit-for-bit in tests/test_native_batcher.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
import typing as tp

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "batcher.c")
_LIB = os.path.join(_DIR, "_batcher.so")

_lock = threading.Lock()
_lib: tp.Optional[ctypes.CDLL] = None
_build_failed = False


def _compiler() -> str:
    return os.environ.get("CC") or sysconfig.get_config_var("CC") or "cc"


def _load() -> tp.Optional[ctypes.CDLL]:
    """Build (once) and load the shared library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                cc = _compiler().split()[0]
                # Build to a per-process temp name, then publish atomically:
                # concurrent importers (pytest -n, parallel launches) must
                # never dlopen a half-written library.
                tmp = f"{_LIB}.{os.getpid()}.tmp"
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _LIB)
            lib = ctypes.CDLL(_LIB)
            lib.sample_windows.argtypes = [
                ctypes.c_void_p,  # data (uint16*)
                ctypes.c_int64,  # n_windows
                ctypes.c_int64,  # t
                ctypes.c_void_p,  # starts (int64*)
                ctypes.c_void_p,  # x_out (int32*)
                ctypes.c_void_p,  # y_out (int32*)
                ctypes.c_int64,  # n_threads
            ]
            lib.sample_windows.restype = None
            _lib = lib
        except Exception:
            _build_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def sample_windows(
    data: np.ndarray,  # uint16 token stream (memmap or RAM)
    starts: np.ndarray,  # int64 window starts, shape (n_windows,)
    block_size: int,
    n_threads: tp.Optional[int] = None,
) -> tp.Optional[tp.Tuple[np.ndarray, np.ndarray]]:
    """(x, y) int32 windows via the C kernel; None if the library is
    unavailable or inputs don't qualify (caller falls back to numpy)."""
    lib = _load()
    if lib is None or data.dtype != np.uint16:
        return None
    data = np.ascontiguousarray(data) if not data.flags.c_contiguous else data
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    n = int(starts.shape[0])
    if n and (starts.min() < 0 or int(starts.max()) + block_size >= len(data)):
        # same failure mode as the numpy fancy-indexing path it replaces —
        # the C kernel itself does not bounds-check
        raise IndexError(
            f"window out of bounds: starts in [{starts.min()}, {starts.max()}] "
            f"+ {block_size} vs stream of {len(data)} tokens"
        )
    x = np.empty((n, block_size), np.int32)
    y = np.empty((n, block_size), np.int32)
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    lib.sample_windows(
        data.ctypes.data_as(ctypes.c_void_p),
        n,
        block_size,
        starts.ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        y.ctypes.data_as(ctypes.c_void_p),
        int(n_threads),
    )
    return x, y
