"""Token-stream dataset: uint16 memmap bins + seeded random-window sampling.

Format-compatible with the reference/nanoGPT pipeline: `train.bin`/`val.bin`
flat uint16 token streams, plus optional `meta.pkl` char codec (reference
train.py:56-66,132-137; data/*/prepare.py).

Two deliberate upgrades over the reference:
  * **Seeded, resumable sampling.** The reference draws from the unseeded
    global numpy RNG (reference train.py:60), so resumed runs replay nothing.
    Here every batch is drawn from `np.random.default_rng([seed, split, step])`
    — stateless, deterministic, and exactly replayable after restore with no
    sampler state to checkpoint.
  * **Optional RAM copy.** The reference always copies the full 17GB stream
    into host RAM (train.py:132-133). `in_ram=False` keeps the memmap and
    lets the page cache do its job.
"""

from __future__ import annotations

import os
import pickle
import typing as tp

import numpy as np

_SPLIT_IDS = {"train": 0, "val": 1}


def sample_batch(
    data: np.ndarray,
    block_size: int,
    batch_size: int,
    g_accum_iters: tp.Optional[int] = None,
    *,
    rng: tp.Optional[np.random.Generator] = None,
    accum_slice: tp.Optional[tp.Tuple[int, int]] = None,
) -> tp.Tuple[np.ndarray, np.ndarray]:
    """Random (x, y=x shifted by one) windows, int32.

    Shapes: (B, T) or (G, B, T) when g_accum_iters is given (reference
    train.py:56-66).

    accum_slice=(lo, m) materializes only accumulation steps [lo, lo+m) of
    the full (g_accum_iters, B, T) draw: ALL window starts are generated (a
    cheap rng.integers pass) and then sliced, so chunked consumers (the
    memory-bounded evaluate loop) see bit-identical windows to a monolithic
    caller."""
    rng = rng or np.random.default_rng()
    bs = batch_size * (g_accum_iters or 1)
    starts = rng.integers(0, len(data) - block_size, size=(bs,))
    if accum_slice is not None:
        assert g_accum_iters is not None
        lo, m = accum_slice
        starts = starts[lo * batch_size : (lo + m) * batch_size]
        g_accum_iters = m
    # One-pass native gather when the C batcher is available (built on
    # demand, midgpt_tpu/native); numpy double-gather otherwise. The RNG
    # stays in numpy either way, so both paths are bit-identical.
    from midgpt_tpu import native

    xy = native.sample_windows(data, starts, block_size)
    if xy is not None:
        x, y = xy
    else:
        offsets = np.arange(block_size)
        x = data[starts[:, None] + offsets].astype(np.int32)
        y = data[starts[:, None] + offsets + 1].astype(np.int32)
    if g_accum_iters is not None:
        x = x.reshape(g_accum_iters, batch_size, block_size)
        y = y.reshape(g_accum_iters, batch_size, block_size)
    return x, y


class TokenDataset:
    """train/val uint16 streams from `data_dir`, sliced per host."""

    def __init__(
        self,
        data_dir: str,
        *,
        in_ram: bool = True,
        seed: int = 1337,
        shard_by_process: bool = False,
    ):
        """shard_by_process: give this host a contiguous 1/n_proc slice of
        EACH split (sized per split — reference train.py:122-136)."""
        self.data_dir = data_dir
        self.seed = seed
        self.splits: tp.Dict[str, np.ndarray] = {}
        # Prep pipelines that retrain their tokenizer (data/local_text)
        # fingerprint the bins in meta.pkl; bins left behind from an older
        # prepare run would otherwise train silently on re-interpreted ids.
        expected = (self.meta() or {}).get("split_tokens", {})
        for split in ("train", "val"):
            path = os.path.join(data_dir, f"{split}.bin")
            arr = np.memmap(path, dtype=np.uint16, mode="r")
            if expected.get(split, len(arr)) != len(arr):
                raise ValueError(
                    f"{path} has {len(arr):,} tokens but meta.pkl records "
                    f"{expected[split]:,} — the bins predate the committed "
                    "tokenizer/meta. Re-run the dataset's prepare.py."
                )
            if shard_by_process:
                import jax

                n_proc, idx = jax.process_count(), jax.process_index()
                # Equal-length contiguous slices (remainder tokens dropped) so
                # every process samples from the same-sized pool.
                per = len(arr) // n_proc
                arr = arr[idx * per : (idx + 1) * per]
            if in_ram:
                arr = np.ascontiguousarray(arr)
            self.splits[split] = arr

    def __getitem__(self, split: str) -> np.ndarray:
        return self.splits[split]

    def batch(
        self,
        split: str,
        step: int,
        block_size: int,
        batch_size: int,
        g_accum_iters: tp.Optional[int] = None,
        accum_slice: tp.Optional[tp.Tuple[int, int]] = None,
    ) -> tp.Tuple[np.ndarray, np.ndarray]:
        """Deterministic batch for (split, step): resumable by construction."""
        rng = np.random.default_rng([self.seed, _SPLIT_IDS[split], step])
        return sample_batch(
            self.splits[split], block_size, batch_size, g_accum_iters, rng=rng,
            accum_slice=accum_slice,
        )

    def meta(self) -> tp.Optional[dict]:
        """Char-codec metadata if present (shakespeare_char)."""
        path = os.path.join(self.data_dir, "meta.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return pickle.load(f)
