from midgpt_tpu.data.dataset import TokenDataset, sample_batch

__all__ = ["TokenDataset", "sample_batch"]
