"""Continuous-batching serving engine over the paged KV cache.

`engine.generate` serves ONE fixed batch: every request starts together,
pads to the longest prompt, and the whole batch runs until the last request
finishes — a tail of dead slots, and a (B, S)-sized cache however short the
requests are. This module serves a STREAM: requests are admitted into decode
slots the moment one frees (or a new one arrives), long prompts prefill in
bounded chunks interleaved with the running batch's decode steps, and K/V
live in a shared paged pool sized to the expected working set instead of
`n_slots * block_size` (models/gpt.py PagedKVCache).

Scheduling is host-side and runs every round (`ServeEngine.step`):

  1. **Admit** — waiting requests claim free slots (FCFS). Admission needs
     only enough free pages for the FIRST prefill chunk; later pages are
     allocated lazily as the request grows.
  2. **Prefill** — ONE waiting slot advances its prompt by at most
     `prefill_chunk` tokens (GPT.prefill_paged_chunk), so a 30k-token
     prompt costs each running generation at most one chunk of extra
     latency per round instead of stalling the batch for the whole prompt
     (the chunked-prefill lever, Sarathi/vLLM-style, adapted to XLA static
     shapes: the chunk is padded to a fixed width, so ONE compiled program
     serves every chunk of every prompt).
  3. **Decode** — all generating slots step together as one device program:
     a power-of-two-sized chain of `GPT.decode_step_paged` calls
     (`_serve_decode_chunk`, same dispatch-amortization scheme as
     engine.generate's DECODE_CHUNK, bounded compile set
     {decode_chunk, decode_chunk/2, ..., 1}). Page tables and lengths are
     plain jit inputs — admitting/finishing requests never recompiles.

With a draft model configured, step 3 becomes a SPECULATIVE round instead:
the draft proposes k tokens per slot against the paged cache (one scanned
program), the target scores all k+1 positions in one batched paged verify
forward, and a rejection sampler commits the longest valid prefix + one
corrected/bonus token — exactly the target's distribution, any acceptance
rate (sampling/spec.py; `_spec_round`; docs/SERVING.md "Speculative
decoding"). k adapts per slot from the recent acceptance EMA over the pow2
buckets [spec_k_min, spec_k_max]; rejected tail positions roll back
page-aligned (length counters reset, tail pages freed, device pool never
rewritten).

Round-overlap dispatch (docs/SERVING.md "Round-overlap dispatch") hides
the per-dispatch tunnel latency behind two composable levers, both off by
default and both compiled from the SAME `_serve_decode_group` program:
`overlap="group"` fuses `round_group` decode rounds into one dispatched
`lax.scan` (EOS / budget / page-boundary handling masks on device, so a
slot that finishes mid-group settles at the group edge exactly where a
sequence of classic rounds would), and `overlap="double"` additionally
dispatches round N+1 BEFORE round N's host post-processing runs
(`_step_overlapped`), chaining device-side token/length state between the
two in-flight programs. Scheduler decisions are one round late by
construction under "double" — an admission or eviction during round N's
host phase first appears in round N+2's dispatch — and greedy streams
stay bit-exact across every mode (tests/test_overlap.py).

When the pool runs dry, the scheduler EVICTS a younger running slot
(frees its pages, pushes the request back to the queue front with its
generated tokens folded into the prompt — recompute-style preemption), so
the oldest requests always make progress and the engine never deadlocks.
WHICH younger slot — like the admission order and the shed decision — is a
pluggable policy (`sampling/scheduler.py`): `FCFSScheduler` (the default:
queue-head admission, youngest-first eviction, budget-only shedding) or
`SLOScheduler` (earliest-deadline-first admission, most-slack-first
eviction, infeasible-deadline shedding). Policies are pure host code; the
compiled program set is policy-independent (tests/test_scheduler.py).

Robustness levers (each round starts with an expiry pass):

  * **Per-request deadline/TTL** — `submit(..., ttl_s=...)`: a request that
    is still queued or generating past its deadline is finished with
    `status="timeout"` (partial tokens returned) and its pages freed, so a
    stalled client cannot occupy pool pages forever. All deadline math runs
    on the injectable `clock=` callable (default `time.perf_counter`), so
    TTL behavior is testable with a fake clock instead of sleeps.
  * **Backpressure** — `max_backlog_pages` bounds the worst-case page
    demand of all live requests; `submit` raises BackpressureError beyond
    it instead of growing the queue (and the eviction churn) without bound.
    The exception carries `retry_after_pages` / `backlog_pages` /
    `retryable` so callers back off programmatically (sampling/server.py)
    instead of string-parsing the message.
  * **Cancellation** — `cancel(uid)` finishes a queued or running request
    immediately (status "cancelled", pages freed) without perturbing
    co-resident slots; the async front door maps client disconnects onto
    it (tests/test_serving.py pins page conservation and neighbor-token
    stability).
  * **Fault hooks** — `step()` consults the robustness/faults.py registry
    for the serving fault kinds (`kill_mid_decode`: the round's decode
    dispatch dies and every decode-ready slot is recompute-preempted;
    `poisoned_page`: one live page is corrupted in place, modeling HBM
    damage — page isolation keeps every other slot's stream intact).
    With an empty registry (always, in production) each hook is a scan
    over an empty list. Chaos scenarios: robustness/chaos_serve.py.

With `prefix_cache=True`, admissions walk a host-side radix trie over the
pool (sampling/prefix_cache.py): fully-matched prompt pages map into the
new slot's page table with a refcount taken and their prefill SKIPPED —
the slot starts at `length = matched` and chunk-prefills only the
unmatched tail (chunked prefill's traced `start` makes that free of new
programs). Departing slots release their pages through the trie, which
keeps complete committed pages for future matches — so a preemption victim
re-matches its own history on readmission instead of re-prefilling from
token 0. When the allocator runs dry, refcount-0 trie pages are reclaimed
(LRU) BEFORE any slot is preempted; a referenced trie page is never
reclaimed. Sharing is page-table indirection only: the compiled program
set is identical with the cache on or off (tests/test_recompile_pins.py),
greedy streams are bit-identical (tests/test_prefix_cache.py), and all
three cache modes work unchanged — int8 scales are indexed by physical
page so they are shared with their page, and speculative drafts attend
through the same shared tables (docs/SERVING.md "Prefix cache").

Streaming hooks: `on_token(uid, token, t)` fires per generated token and
`on_finish(FinishedRequest)` on every terminal transition (finish, EOS,
timeout, cancel) — the async server's per-token streaming rides these.

Greedy (temperature=0) serving is token-for-token identical to
`engine.generate` on the same prompt (parity pin in tests/test_sampling.py);
stochastic sampling draws from a different key stream (per-chunk splits per
slot batch) and is only distributionally equivalent.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams, PagedKVCache
from midgpt_tpu.obs import DISABLED_SNAPSHOT, Observability
from midgpt_tpu.obs.trace import NULL_TRACER
from midgpt_tpu.robustness import faults
from midgpt_tpu.sampling.engine import sample_logits, warp_logits
from midgpt_tpu.sampling.prefix_cache import PrefixCache
from midgpt_tpu.sampling.scheduler import FCFSScheduler, Scheduler
from midgpt_tpu.sampling.spec import speculative_accept

Array = jax.Array


def _maybe_constrain(cache, mesh):
    """Pin a tp-sharded pool's out-sharding to its in-sharding inside the
    serving jits (no-op unsharded). Without the constraint GSPMD may pick a
    different output layout for the donated pool and the round-to-round
    donation degrades to a copy+reshard (parallel/serve_tp.constrain_cache)."""
    if mesh is None:
        return cache
    from midgpt_tpu.parallel.serve_tp import constrain_cache

    return constrain_cache(cache, mesh)


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=(5,))
def _serve_prefill_chunk(
    config, params, tokens, start, n_valid, cache, page_table_row, mesh=None
):
    logits, cache = GPT.prefill_paged_chunk(
        config, params, tokens, start, n_valid, cache, page_table_row
    )
    return logits, _maybe_constrain(cache, mesh)


@functools.partial(
    jax.jit, static_argnums=(0, 7, 8, 9, 10, 11, 13, 14), donate_argnums=(3,)
)
def _serve_decode_chunk(
    config,
    params,
    token,  # (B,) int32
    cache,  # PagedKVCache (donated)
    page_table,  # (B, max_pages) int32
    lengths,  # (B,) int32
    active,  # (B,) bool
    n_steps: int,
    temperature: float,
    top_k,
    top_p,
    attn_impl: str,
    key=None,
    mesh=None,  # static (Mesh hashes) — tp serving mesh, None = single chip
    split_k: int = 1,  # static — key partitions per slot (docs/SERVING.md)
):
    """n_steps decode+sample steps for the whole slot batch as ONE device
    program. Inactive slots hold their token and length (their writes land
    on the sink page). Returns (cache, tokens (n_steps, B))."""

    def body(carry, _):
        token, cache, lengths, key = carry
        if key is not None:
            key, k = jax.random.split(key)
        else:
            k = None
        logits, cache = GPT.decode_step_paged(
            config, params, token, cache, page_table, lengths, active,
            attn_impl=attn_impl, mesh=mesh, split_k=split_k,
        )
        cache = _maybe_constrain(cache, mesh)
        if temperature == 0.0:
            nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1)
        else:
            nxt = sample_logits(logits, k, temperature, top_k, top_p)
        nxt = jnp.where(active, nxt.astype(token.dtype), token)
        lengths = lengths + active.astype(lengths.dtype)
        return (nxt, cache, lengths, key), nxt

    (_, cache, _, _), toks = jax.lax.scan(
        body, (token, cache, lengths, key), None, length=n_steps
    )
    return cache, toks


# Cap on the fused multi-round group size (docs/SERVING.md "Round-overlap
# dispatch"): k rounds per dispatched program trade scheduling granularity
# (admissions/evictions only land at group edges) for dispatch amortization,
# and past ~8 the granularity cost dominates on any realistic trace.
_ROUND_GROUP_CAP = 8


def _round_group_bucket(group: int) -> int:
    """Clamp a requested multi-round group size to [1, _ROUND_GROUP_CAP]
    and floor it to a power of two — the same pow2 ladder every other
    static jit knob (decode chunk, page bucket, split_k) rides, so the
    compile set stays logarithmic and the GC011 static-domain prover can
    see the bound lexically."""
    group = max(1, min(int(group), _ROUND_GROUP_CAP))
    return 1 << (group.bit_length() - 1)


def parse_overlap(spec: str) -> tp.Tuple[str, int]:
    """Parse the `--overlap {off,double,group:k}` CLI form shared by
    tools/bench_serve.py and tools/loadgen.py into the engine's
    (overlap, round_group) kwargs. Strict: anything else raises, so a
    typo'd A/B flag fails the bench instead of silently measuring 'off'."""
    if spec in ("off", "double"):
        return spec, 1
    if spec.startswith("group:"):
        try:
            k = int(spec.split(":", 1)[1])
        except ValueError:
            k = 0
        if k >= 1:
            return "group", k
    raise ValueError(
        f"bad overlap spec {spec!r} (want 'off', 'double', or 'group:k' "
        "with k >= 1)"
    )


@functools.partial(
    jax.jit,
    static_argnums=(0, 12, 13, 14, 15, 16, 17, 19, 20),
    donate_argnums=(3,),
)
def _serve_decode_group(
    config,
    params,
    token,  # (B,) int32 — host view of each slot's pending token
    cache,  # PagedKVCache (donated)
    page_table,  # (B, max_pages) int32
    lengths,  # (B,) int32 — host view of committed lengths
    active,  # (B,) bool — batch membership at dispatch
    eos,  # (B,) int32 — per-slot EOS id, -1 when the request has none
    max_len,  # (B,) int32 — absolute settle bound per slot (see below)
    chain_mask,  # (B,) bool — slots continuing from an unsettled group
    chain_token,  # (B,) int32 — device-side pending token for chained slots
    chain_len,  # (B,) int32 — device-side lengths for chained slots
    n_steps: int,
    round_group: int,
    temperature: float,
    top_k,
    top_p,
    attn_impl: str,
    key=None,
    mesh=None,  # static (Mesh hashes) — tp serving mesh, None = single chip
    split_k: int = 1,  # static — key partitions per slot (docs/SERVING.md)
):
    """`n_steps * round_group` decode+sample steps as ONE dispatched
    program — the fused multi-round group of the round-overlap scheme
    (docs/SERVING.md "Round-overlap dispatch"). Differences from
    `_serve_decode_chunk`, all serving the settle-at-the-boundary rule:

      * **Device-side finish masking.** A slot stops stepping the moment
        its length reaches `max_len` (its generation budget or provisioned
        pages, whichever binds first) or it emits its EOS token —
        `step_active` masks the K/V write, the emit, and the length
        advance, so a finished slot can NEVER write past the pages it was
        provisioned at dispatch (an out-of-range page-table gather clamps
        to a REAL page, so an unmasked overrun would corrupt a neighbor's
        — or the trie's — committed K/V). The emitted mask is returned so
        the host commits exactly the tokens a sequence of classic rounds
        would have.
      * **Chained carry-in.** Under double-buffering the previous group is
        still in flight at dispatch: the host's token/length view of its
        slots is one round stale, so the true values ride in on
        `chain_token`/`chain_len` (the previous program's outputs, never
        forced) and are merged under `chain_mask` INSIDE this program —
        one dispatch per round, no eager merge ops through the tunnel.

    `round_group` is a pow2-bucketed static (`_round_group_bucket`), so
    the compile set stays one program per (n_steps bucket, page bucket,
    round_group) — pinned by tests/test_recompile_pins.py. Returns
    (cache, toks (T, B), emitted (T, B) bool, tok_fin (B,), len_fin (B,))
    with T = n_steps * round_group; tok_fin/len_fin seed the next group's
    chain without settling this one."""
    token = jnp.where(chain_mask, chain_token, token)
    lengths = jnp.where(chain_mask, chain_len, lengths)

    def body(carry, _):
        token, cache, lengths, active, key = carry
        if key is not None:
            key, k = jax.random.split(key)
        else:
            k = None
        # Pre-step mask: the write for this step lands at position
        # `lengths`, so it must be gated BEFORE the decode step runs.
        step_active = active & (lengths < max_len)
        logits, cache = GPT.decode_step_paged(
            config, params, token, cache, page_table, lengths, step_active,
            attn_impl=attn_impl, mesh=mesh, split_k=split_k,
        )
        cache = _maybe_constrain(cache, mesh)
        if temperature == 0.0:
            nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1)
        else:
            nxt = sample_logits(logits, k, temperature, top_k, top_p)
        nxt = jnp.where(step_active, nxt.astype(token.dtype), token)
        lengths = lengths + step_active.astype(lengths.dtype)
        hit_eos = step_active & (eos >= 0) & (nxt == eos)
        active = active & ~hit_eos
        return (nxt, cache, lengths, active, key), (nxt, step_active)

    (tok_fin, cache, len_fin, _, _), (toks, emitted) = jax.lax.scan(
        body,
        (token, cache, lengths, active, key),
        None,
        length=n_steps * round_group,
    )
    return cache, toks, emitted, tok_fin, len_fin


@functools.partial(
    jax.jit, static_argnums=(0, 7, 8, 9, 10, 11, 13, 14), donate_argnums=(3,)
)
def _spec_draft_chunk(
    config,  # the DRAFT model's GPTConfig
    params,  # the DRAFT model's params
    token,  # (B,) int32 — each slot's pending token
    cache,  # draft PagedKVCache (donated)
    page_table,  # (B, max_pages) int32 — SHARED with the target pool
    lengths,  # (B,) int32
    active,  # (B,) bool
    k_steps: int,
    temperature: float,
    top_k,
    top_p,
    attn_impl: str,
    key=None,
    mesh=None,  # static — tp serving mesh, None = single chip
    split_k: int = 1,  # static — key partitions per slot
):
    """k_steps autoregressive draft proposals for the whole slot batch as
    ONE device program: a scan of paged decode steps of the draft model
    against the draft pool. Returns (cache, drafts (k, B) int32, probs
    (k, B, V) f32) where probs[i] is the warped draft distribution proposal
    i was drawn from — the q_i the verify program's rejection sampler
    needs. Compiled once per (k bucket, page bucket), independent of
    request mix (pinned by tests/test_recompile_pins.py)."""

    def body(carry, _):
        token, cache, lengths, key = carry
        if key is not None:
            key, k = jax.random.split(key)
        logits, cache = GPT.decode_step_paged(
            config, params, token, cache, page_table, lengths, active,
            attn_impl=attn_impl, mesh=mesh, split_k=split_k,
        )
        cache = _maybe_constrain(cache, mesh)
        lf = logits.astype(jnp.float32)
        if temperature == 0.0:
            probs = jax.nn.softmax(lf, axis=-1)
            nxt = jnp.argmax(lf, axis=-1)
        else:
            warped = warp_logits(lf, temperature, top_k, top_p)
            probs = jax.nn.softmax(warped, axis=-1)
            nxt = jax.random.categorical(k, warped, axis=-1)
        nxt = jnp.where(active, nxt.astype(token.dtype), token)
        lengths = lengths + active.astype(lengths.dtype)
        return (nxt, cache, lengths, key), (nxt, probs)

    (_, cache, _, _), (toks, probs) = jax.lax.scan(
        body, (token, cache, lengths, key), None, length=k_steps
    )
    return cache, toks, probs


@functools.partial(
    jax.jit, static_argnums=(0, 9, 10, 11, 12, 14, 15), donate_argnums=(5,)
)
def _spec_verify_chunk(
    config,
    params,
    token,  # (B,) int32 — each slot's pending token
    drafts,  # (k, B) int32 — _spec_draft_chunk output, never landed on host
    draft_probs,  # (k, B, V) f32
    cache,  # target PagedKVCache (donated)
    page_table,
    lengths,
    active,
    temperature: float,
    top_k,
    top_p,
    attn_impl: str,
    key=None,
    mesh=None,  # static — tp serving mesh, None = single chip
    split_k: int = 1,  # static — key partitions per slot
):
    """One batched paged verify forward over [pending, d_1..d_k] plus the
    rejection sampler (sampling/spec.py): returns (cache, n_accept (B,),
    out (B, k+1)) — the host emits out[b, :n_accept[b] + 1] per active
    slot. k rides the drafts shape, so the program set is one per (k
    bucket, page bucket) like the draft program."""
    tokens = jnp.concatenate(
        [token[:, None], drafts.T.astype(token.dtype)], axis=1
    )  # (B, k+1)
    logits, cache = GPT.verify_step_paged(
        config, params, tokens, cache, page_table, lengths, active,
        attn_impl=attn_impl, mesh=mesh, split_k=split_k,
    )
    cache = _maybe_constrain(cache, mesh)
    n_accept, out = speculative_accept(
        logits,
        jnp.transpose(draft_probs, (1, 0, 2)),
        drafts.T.astype(jnp.int32),
        key,
        temperature,
        top_k,
        top_p,
    )
    return cache, jnp.where(active, n_accept, 0), out


# Accepted `cache_dtype` spellings. "bf16" is the TPU serving default;
# "int8" selects the quantized pool (PagedKVCache int8 storage mode —
# halves decode-attention HBM traffic and doubles pages-per-byte at the
# same pool budget, docs/SERVING.md "Quantized KV cache"); float32 exists
# for the CPU test mesh, where exact greedy parity with engine.generate's
# f32 math is what the serving pins assert.
_CACHE_DTYPES = {
    "bf16": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "f32": jnp.float32,
    "float32": jnp.float32,
}


def normalize_cache_dtype(dtype) -> jnp.dtype:
    """'bf16' | 'int8' | 'float32' | a jnp dtype -> the jnp dtype."""
    if isinstance(dtype, str):
        if dtype not in _CACHE_DTYPES:
            raise ValueError(
                f"unknown cache dtype {dtype!r} (one of {sorted(_CACHE_DTYPES)})"
            )
        return jnp.dtype(_CACHE_DTYPES[dtype])
    return jnp.dtype(dtype)


class PageAllocator:
    """Free-list allocator over the pool's pages. Page 0 is the SINK
    (absorbs inactive-slot writes, models/gpt.py PagedKVCache) and is never
    handed out."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))  # pop() yields 1, 2, ...

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> tp.Optional[tp.List[int]]:
        """n pages, or None (allocator unchanged) if the pool is short."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: tp.Iterable[int]) -> None:
        for p in pages:
            assert 0 < p < self.num_pages
            self._free.append(p)


class BackpressureError(RuntimeError):
    """Admission was refused — the caller should shed load or (when
    `retryable`) retry later, instead of the request sitting in an
    unbounded queue (or thrashing the pool with evictions) indefinitely.

    Structured fields (so callers never string-parse the message):

      needed_pages     worst-case pages the refused request would commit
      backlog_pages    worst-case pages already committed to live requests
      budget_pages     the engine's `max_backlog_pages` (None = unbounded)
      retryable        False when waiting cannot help (e.g. the
                       SLOScheduler shed an already-infeasible deadline);
                       True for capacity sheds — pages free as requests
                       finish, so a bounded retry-with-backoff is sane
                       (sampling/server.py does exactly that)
      retry_after_pages  pages that must free before a retry can admit
                       (None when any ingredient is unknown)
    """

    def __init__(
        self,
        message: str,
        *,
        needed_pages: tp.Optional[int] = None,
        backlog_pages: tp.Optional[int] = None,
        budget_pages: tp.Optional[int] = None,
        retryable: bool = True,
    ):
        super().__init__(message)
        self.needed_pages = needed_pages
        self.backlog_pages = backlog_pages
        self.budget_pages = budget_pages
        self.retryable = retryable

    @property
    def retry_after_pages(self) -> tp.Optional[int]:
        if None in (self.needed_pages, self.backlog_pages, self.budget_pages):
            return None
        return max(0, self.backlog_pages + self.needed_pages - self.budget_pages)


@dataclasses.dataclass
class Request:
    uid: int
    prompt: np.ndarray  # (T0,) int32
    max_new_tokens: int
    eos_id: tp.Optional[int] = None
    deadline: tp.Optional[float] = None  # absolute time.perf_counter() expiry


@dataclasses.dataclass
class _Slot:
    request: Request
    admit_order: int
    pages: tp.List[int] = dataclasses.field(default_factory=list)
    length: int = 0  # tokens in the paged cache
    prompt_pos: int = 0  # prompt tokens prefilled so far
    # pages[:n_shared] are prefix-cache trie entries this slot holds one
    # reference each on (prefix_cache engines only; 0 otherwise). The slot
    # never writes them: match caps at len(prompt) - 1 tokens and
    # insert_live shares only complete prompt pages, while every write
    # after admission lands at a position >= length >= the shared span.
    n_shared: int = 0
    generated: tp.List[int] = dataclasses.field(default_factory=list)
    token_times: tp.List[float] = dataclasses.field(default_factory=list)
    # speculative-decoding state (draft engines only): current per-slot
    # draft length and the acceptance EMA that adapts it. The EMA starts
    # optimistic (1.0) so the first round can never halve k before any
    # evidence exists.
    spec_k: int = 1
    accept_ema: float = 1.0

    @property
    def prefilling(self) -> bool:
        return self.prompt_pos < len(self.request.prompt)

    @property
    def remaining(self) -> int:
        return self.request.max_new_tokens - len(self.generated)


@dataclasses.dataclass
class FinishedRequest:
    uid: int
    tokens: np.ndarray  # prompt + generated
    token_times: tp.List[float]  # wall-clock completion time per new token
    status: str = "ok"  # "ok" | "timeout" (deadline expired before finish)


@dataclasses.dataclass
class _InflightRound:
    """A dispatched-but-unsettled decode group (round-overlap dispatch).

    Holds the group program's UNFORCED device outputs plus the host-side
    identity snapshot needed to settle it later: `slots` pins the exact
    _Slot objects that were in the batch, so a settle after an eviction /
    cancel / timeout skips any index whose slot object changed — the
    in-flight tokens for a departed slot are simply discarded (recompute
    preemption regenerates them bit-exactly; greedy streams are batch-
    composition-independent). `worst_len` is the worst-case post-settle
    length per slot — what the NEXT dispatch must assume for a chained
    slot whose true device-side length (`len_fin`) it merges in-program.
    """

    toks: Array  # (T, B) int32, unforced
    emitted: Array  # (T, B) bool, unforced
    tok_fin: Array  # (B,) int32, unforced — next group's chain_token
    len_fin: Array  # (B,) int32, unforced — next group's chain_len
    n_steps: int  # T = n * round_group
    active_idx: tp.List[int]
    slots: tp.List[_Slot]
    worst_len: np.ndarray  # (max_slots,) int32
    round_no: int
    t0: float
    t1: float


class ServeEngine:
    """Host-side continuous-batching scheduler (module docstring)."""

    def __init__(
        self,
        config: GPTConfig,
        params: GPTParams,
        *,
        max_slots: int = 4,
        num_pages: tp.Optional[int] = None,
        pool_hbm_bytes: tp.Optional[int] = None,
        page_size: int = 8,
        prefill_chunk: int = 16,
        decode_chunk: int = 8,
        temperature: float = 0.0,
        top_k: tp.Optional[int] = None,
        top_p: tp.Optional[float] = None,
        seed: int = 0,
        cache_dtype=jnp.bfloat16,
        attn_impl: str = "auto",
        split_k="auto",  # "auto" | int — key partitions per attention call
        overlap: str = "off",  # "off" | "double" | "group" (SERVING.md)
        round_group: int = 1,  # fused rounds per dispatch (pow2-bucketed)
        max_backlog_pages: tp.Optional[int] = None,
        prefix_cache: bool = False,
        draft_params: tp.Optional[GPTParams] = None,
        draft_config: tp.Optional[GPTConfig] = None,
        draft_shares_cache: bool = False,
        spec_k_max: int = 4,
        spec_k_min: int = 1,
        spec_adapt: bool = True,
        scheduler: tp.Optional[Scheduler] = None,
        clock: tp.Callable[[], float] = time.perf_counter,
        on_token: tp.Optional[tp.Callable[[int, int, float], None]] = None,
        on_finish: tp.Optional[tp.Callable[["FinishedRequest"], None]] = None,
        mesh=None,  # Optional[jax.sharding.Mesh] — parallel/serve_tp.py
        obs: tp.Optional[Observability] = None,
        obs_tid: str = "engine",
        weights_version: str = "inline",
        watchdog=None,  # Optional[robustness.watchdog.StepWatchdog]
    ):
        assert decode_chunk & (decode_chunk - 1) == 0, "decode_chunk: power of two"
        # ---- tp serving mesh (docs/SERVING.md "Mesh-sharded serving") ----
        # Params shard by the megatron training rules (vocab-parallel off so
        # logits stay replicated for the host-side first-token argmax), the
        # paged pools shard heads over 'tp', and EVERY scheduler-facing jit
        # input — page tables, lengths, tokens — stays a replicated host
        # array: the trie/allocator/scheduler below never learn the mesh
        # exists. The mesh rides the serving jits as a trailing static arg,
        # so a sharded and an unsharded engine in one process keep disjoint
        # compile-cache entries and mesh=None stays bit-for-bit the
        # single-chip behavior.
        self.mesh = mesh
        if mesh is not None:
            from midgpt_tpu.parallel import serve_tp as _stp

            n_tp = int(mesh.shape["tp"])
            for nm, c in (("target", config), ("draft", draft_config)):
                if c is not None and c.n_head % n_tp:
                    raise ValueError(
                        f"{nm} n_head={c.n_head} not divisible by mesh "
                        f"tp={n_tp} — the pool shards whole heads"
                    )
                if c is not None and c.kv_heads % n_tp:
                    # GQA pool shards whole KV heads; with H_q % tp == 0 the
                    # shard boundary then falls between whole query groups.
                    raise ValueError(
                        f"{nm} n_kv_heads={c.kv_heads} not divisible by "
                        f"mesh tp={n_tp} — the pool shards whole KV heads"
                    )
            if n_tp > 1:
                # Head-aligned qkv shards need the split3 einsum order over
                # the same (3, D, D) params — the identical switch training
                # makes when its mesh has tp > 1 (training/train.py).
                if config.qkv_proj != "split3":
                    config = dataclasses.replace(config, qkv_proj="split3")
                if draft_config is not None and draft_config.qkv_proj != "split3":
                    draft_config = dataclasses.replace(
                        draft_config, qkv_proj="split3"
                    )
            params = _stp.put_sharded(
                params, _stp.serve_param_specs(params, mesh), mesh
            )
            if draft_params is not None:
                draft_params = _stp.put_sharded(
                    draft_params, _stp.serve_param_specs(draft_params, mesh), mesh
                )
        self.config = config
        self.params = params
        self.scheduler = scheduler if scheduler is not None else FCFSScheduler()
        self._clock = clock
        # Observability (midgpt_tpu/obs/): spans + round decomposition +
        # metrics, all host-side. obs=None keeps NULL_TRACER in every
        # instrumentation site — zero clock reads, zero ring appends —
        # and the scheduling/token path is bit-identical either way
        # (tests/test_obs.py pins parity; tests/test_recompile_pins.py
        # pins that the toggle compiles nothing: spans never cross the
        # jit boundary, so no static, no program).
        self.obs = obs
        self._trace = obs.tracer if obs is not None else NULL_TRACER
        self._obs_tid = obs_tid
        # Hung-dispatch watchdog (robustness/watchdog.py), same injection
        # discipline as clock/obs: None (default) leaves the decode round's
        # force a plain np.asarray — no thread, no event, nothing for the
        # recompile pins to see. Set, it bounds the round's device sync so a
        # wedged tunnel ends in StepHangError instead of a hung server.
        self.watchdog = watchdog
        self.on_token = on_token
        self.on_finish = on_finish
        self.page_size = page_size
        self.max_slots = max_slots
        self.prefill_chunk = prefill_chunk
        self.decode_chunk = decode_chunk
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.attn_impl = attn_impl
        # Split-K policy (docs/SERVING.md "Split-K decode"): "auto" picks a
        # per-round pow2 split from the page bucket (_split_bucket) — short
        # traffic resolves to 1 and compiles/runs the classic unsplit
        # program; an int forces that split for every round (tests). Like
        # the page bucket and the mesh, the resolved split is a trailing
        # static jit arg: each (bucket, split) pair is its own compile-cache
        # entry, and split programs never perturb unsplit ones.
        if split_k != "auto" and (not isinstance(split_k, int) or split_k < 1):
            raise ValueError(f"split_k must be 'auto' or a positive int, got {split_k!r}")
        self.split_k = split_k
        # Round-overlap dispatch (docs/SERVING.md "Round-overlap dispatch"):
        # "off" keeps the classic settle-every-round loop byte-identical;
        # "group" fuses round_group decode rounds into one dispatched
        # program (settled at the group edge, same step order otherwise);
        # "double" additionally keeps ONE group in flight while the host
        # phases of the previous round run (_step_overlapped). Both modes
        # share _serve_decode_group, so flipping between them after warmup
        # compiles nothing (tests/test_recompile_pins.py). Speculative
        # engines ignore "double"/"group" for their spec rounds — a
        # draft-then-verify round is already two fused dispatches with a
        # host commit between, and overlapping it would re-order the
        # rollback against the next draft — and run the classic step loop.
        if overlap not in ("off", "double", "group"):
            raise ValueError(
                f"overlap must be 'off', 'double' or 'group', got {overlap!r}"
            )
        self.overlap = overlap
        self.round_group = _round_group_bucket(round_group)
        self._inflight: tp.Optional[_InflightRound] = None
        # Killed in-flight overlapped groups (kill_overlapped_round chaos).
        self.overlap_kills = 0
        # (round, (uid, ...)) per decode dispatch — the deferred-effect
        # observability hook: tests assert a request admitted/evicted
        # during round N's host phase first appears/disappears in round
        # N+2's dispatch (the one-round-late policy boundary).
        self.dispatch_log: tp.Deque[tp.Tuple[int, tp.Tuple[int, ...]]] = (
            collections.deque(maxlen=256)
        )
        self.max_pages_per_slot = -(-config.block_size // page_size)
        cache_dtype = normalize_cache_dtype(cache_dtype)
        self.cache_dtype = cache_dtype
        if pool_hbm_bytes is not None:
            # Byte-budgeted paging: the pool is sized by HBM SPEND, not page
            # count, so the page capacity follows the cache dtype — int8
            # admits 2x the pages of bf16 at the same budget (the int8 scale
            # side buffers ride on top, +4/head_dim; PagedKVCache.page_bytes
            # documents the accounting, cache_hbm_bytes() reports the true
            # total).
            if num_pages is not None:
                raise ValueError("pass num_pages OR pool_hbm_bytes, not both")
            per_page = PagedKVCache.page_bytes(config, page_size, cache_dtype)
            num_pages = max(2, pool_hbm_bytes // per_page)  # sink + >= 1
        elif num_pages is None:
            # Default: half of what dedicated full-length caches would take
            # (+ the sink) — the continuous-batching bet that Σ used-lengths
            # stays well under n_slots * block_size.
            num_pages = 1 + max_slots * self.max_pages_per_slot // 2
        # Backpressure bound: worst-case page demand (prompt + full budget)
        # summed over every live request, queued or running. None (default):
        # admission is unbounded, the pre-TTL behavior.
        self.max_backlog_pages = max_backlog_pages
        self.allocator = PageAllocator(num_pages)
        # Cross-request prefix sharing (module docstring; default OFF so a
        # plain engine's scheduling is bit-for-bit the pre-trie behavior).
        self.prefix_cache = PrefixCache(page_size) if prefix_cache else None
        # prefix-cache counters (prefix_stats): matched vs structurally
        # matchable prompt tokens per admission, COW tail re-prefills,
        # trie pages reclaimed under allocator pressure, and total prompt
        # tokens actually pushed through prefill chunks (the r10
        # self-re-prefill regression pin reads this one).
        self._prefix_matched_tokens = 0
        self._prefix_matchable_tokens = 0
        self.cow_pages = 0
        self.prefix_evictions = 0
        self.prefilled_tokens = 0
        # Host-RAM KV spill tier (sampling/fleet.py SpillTier), wired by
        # attach_spill: evicted trie pages land there instead of being
        # discarded, and _admit re-adopts resident runs past the trie
        # match. None (default): evictions discard, the pre-fleet
        # behavior.
        self.spill_tier = None
        self.spill_readopted_pages = 0
        self.spill_readopt_events = 0
        self.cache = PagedKVCache.init(
            config, num_pages=num_pages, page_size=page_size, dtype=cache_dtype
        )
        if mesh is not None:
            from midgpt_tpu.parallel import serve_tp as _stp

            self.cache = _stp.put_sharded(
                self.cache, _stp.serve_cache_specs(self.cache), mesh
            )
        # ---- speculative decoding (docs/SERVING.md) ----
        # A draft model turns every decode round into draft-k-then-verify:
        # the draft proposes spec_k tokens against its OWN paged pool, the
        # target scores them in one verify forward, and a rejection sampler
        # keeps the longest valid prefix (+1 corrected/bonus token). The
        # draft pool shares the page table and allocator with the target —
        # one logical page maps to the same physical index in both pools —
        # so the scheduler stays single-track.
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config come together")
        if draft_config is not None:
            if draft_config.block_size != config.block_size:
                raise ValueError(
                    f"draft block_size {draft_config.block_size} != target "
                    f"{config.block_size} — the shared page table assumes "
                    "equal position spaces"
                )
            for k_name, k_val in (("spec_k_max", spec_k_max),
                                  ("spec_k_min", spec_k_min)):
                if k_val < 1 or k_val & (k_val - 1):
                    raise ValueError(f"{k_name}={k_val} must be a power of two")
            if spec_k_min > spec_k_max:
                raise ValueError(
                    f"spec_k_min={spec_k_min} > spec_k_max={spec_k_max}"
                )
            if draft_shares_cache and (
                draft_config.n_head != config.n_head
                or draft_config.head_dim != config.head_dim
                or draft_config.n_layer >= config.n_layer
            ):
                raise ValueError(
                    "draft_shares_cache requires a layer-prefix draft: same "
                    "n_head/head_dim, fewer layers (sampling/spec.py "
                    "self_draft)"
                )
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.draft_shares_cache = draft_shares_cache
        self.spec_k_max = spec_k_max
        self.spec_k_min = spec_k_min
        self.spec_adapt = spec_adapt
        # A layer-prefix self-draft needs no pool of its own: draft layer i
        # IS target layer i, so the committed K/V it must attend to already
        # sit in the target pool's first n_draft layers, and its speculative
        # writes there are the same values the verify forward rewrites. The
        # draft then also skips prompt prefill entirely — the target's
        # prefill filled its layers. A separate draft model gets a dedicated
        # pool (same page table/allocator: one logical page, two pools).
        self.draft_cache = (
            None
            if draft_config is None or draft_shares_cache
            else PagedKVCache.init(
                draft_config, num_pages=num_pages, page_size=page_size,
                dtype=cache_dtype,
            )
        )
        if mesh is not None and self.draft_cache is not None:
            from midgpt_tpu.parallel import serve_tp as _stp

            self.draft_cache = _stp.put_sharded(
                self.draft_cache, _stp.serve_cache_specs(self.draft_cache), mesh
            )
        # aggregate speculative counters (spec_stats)
        self._spec_rounds = 0
        self._spec_verifies = 0  # (slot, round) pairs
        self._spec_drafted = 0
        self._spec_accepted = 0
        self.slots: tp.List[tp.Optional[_Slot]] = [None] * max_slots
        self.queue: tp.List[Request] = []
        self.finished: tp.Dict[int, FinishedRequest] = {}
        self._key = jax.random.PRNGKey(seed)
        self._uid = 0
        self._admitted = 0
        # Recompute-style preemptions since construction (one per _evict):
        # the oversubscription cost a byte budget trades against — int8
        # mode's 2x pages shows up here as strictly fewer evictions on the
        # same trace (tests/test_quant_cache.py; reported by bench_serve).
        self.preemptions = 0
        # Sliding-window page reclamation (config.sliding_window > 0,
        # cache-off, non-speculative engines): pages wholly behind every
        # future row's window (and past the sink prefix) are returned to
        # the free list mid-request, their table entries parked on the
        # sink page — the bounded-resident-set lever that makes windowed
        # decode O(window) in pool pages, not O(T).
        self.window_reclaimed_pages = 0
        # Robustness/SLO counters (reported by tools/loadgen.py and the
        # chaos serve scenarios): scheduling rounds, deadline timeouts,
        # admission sheds, client cancellations, and killed decode rounds.
        self.rounds = 0
        self.timeouts = 0
        self.shed = 0
        self.cancelled = 0
        self.decode_kills = 0
        # uids whose pool pages were corrupted by the poisoned_page fault —
        # the slots a chaos parity check must exclude (everyone else's
        # stream never reads the poisoned physical page).
        self.poisoned_uids: tp.List[int] = []
        # ---- zero-downtime model ops (sampling/ops.py) ----------------
        # weights_version identifies which weights serve each round on
        # stats() and flight-recorder dumps: "<step>:<sha12>" for verified
        # checkpoints (training/checkpoint.py weights_version) or "inline"
        # for directly-passed params. A staged blue/green swap pauses
        # admissions (so queued arrivals deterministically take the NEW
        # weights) and flips at the first slot-free round boundary.
        self.weights_version = weights_version
        self.hot_swaps = 0
        self.resizes = 0
        self.swap_history: tp.List[tp.Dict[str, tp.Any]] = []
        self.resize_history: tp.List[tp.Dict[str, tp.Any]] = []
        self._staged_swap: tp.Optional[tp.Dict[str, tp.Any]] = None
        # Uids that have been recompute-preempted at least once: a queued
        # entry with one of these uids is a stream ALREADY in flight (its
        # early tokens are committed), not a fresh arrival — the staged-
        # swap admission pause must let it resume on the old weights, and
        # the flip must wait for it (sampling/ops.py). Uids are never
        # reused, so the set is grow-only.
        self._resumed_uids: tp.Set[int] = set()
        # Chaos hooks (robustness/chaos_serve.py): hot_swap_mid_decode
        # pulls its payload from swap_source (a callable returning
        # hot_swap kwargs incl. "params"); pool_resize pops its next
        # num_pages target from resize_plan. Both None/empty in production.
        self.swap_source: tp.Optional[tp.Callable[[], tp.Dict[str, tp.Any]]] = None
        self.resize_plan: tp.List[int] = []

    # -- public surface ------------------------------------------------

    def submit(
        self,
        prompt: tp.Sequence[int],
        max_new_tokens: int,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
    ) -> int:
        """Queue a request. `ttl_s` bounds its total residence time: a
        request still unfinished `ttl_s` seconds from now is evicted with a
        `timeout` status instead of occupying queue slots / pool pages
        forever. Raises BackpressureError when the scheduler policy sheds
        the request (over the `max_backlog_pages` budget, or — SLOScheduler
        — an already-infeasible deadline)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        S = self.config.block_size
        if len(prompt) + max_new_tokens > S:
            # The paged pool is sized to the trained context; the windowed
            # overflow scheme of engine.generate has no incremental cache to
            # page. Reject instead of silently truncating.
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds block_size ({S})"
            )
        need = -(-(len(prompt) + max_new_tokens) // self.page_size)
        if need > self.allocator.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.allocator.num_pages - 1} allocatable"
            )
        now = self._clock()
        deadline = None if ttl_s is None else now + ttl_s
        shed = self.scheduler.shed_reason(need, deadline, self, now)
        if shed is not None:
            message, retryable = shed
            self.shed += 1
            self._trace.instant(
                "shed", "lifecycle", self._obs_tid,
                args={"needed_pages": need, "retryable": retryable},
            )
            raise BackpressureError(
                message,
                needed_pages=need,
                backlog_pages=self._backlog_pages(),
                budget_pages=self.max_backlog_pages,
                retryable=retryable,
            )
        uid = self._uid
        self._uid += 1
        self.queue.append(Request(uid, prompt, max_new_tokens, eos_id, deadline))
        return uid

    def _backlog_pages(self) -> int:
        """Worst-case page demand committed to live (queued + running)
        requests. Uses each request's FULL footprint — prompt plus the whole
        generation budget — because that is what the pool must eventually
        absorb if nothing times out early.

        With the prefix cache on the accounting is refcount-aware: a shared
        page is charged ONCE (the trie's referenced-entry count) instead of
        once per reader — each running slot subtracts its n_shared and each
        queued request subtracts what it would currently match (a ref-free
        `peek`). Refcount-0 trie pages are charged nothing: they are
        reclaimed on demand before any preemption, so they never stand
        between an admission and its pages. Cache off: identical to the
        pre-trie arithmetic."""

        def worst(req: Request) -> int:
            return -(-(len(req.prompt) + req.max_new_tokens) // self.page_size)

        pc = self.prefix_cache
        queued = sum(
            worst(r)
            - (0 if pc is None else pc.peek(r.prompt, max_tokens=len(r.prompt) - 1))
            for r in self.queue
        )
        running = sum(
            worst(s.request) - s.n_shared for s in self.slots if s is not None
        )
        shared = 0 if pc is None else pc.referenced_page_count()
        return queued + running + shared

    @property
    def idle(self) -> bool:
        # A staged hot-swap counts as pending work: the drive loop must
        # keep stepping until the flip lands (sampling/ops.py), or a swap
        # staged on a draining engine would never complete. Likewise an
        # unsettled in-flight decode group (overlap="double"): its tokens
        # are not committed until the next step settles it, so the drive
        # loop must take one more step even if every slot just drained.
        return (
            not self.queue
            and all(s is None for s in self.slots)
            and self._staged_swap is None
            and self._inflight is None
        )

    def run(self) -> tp.Dict[int, FinishedRequest]:
        """Drive step() until everything submitted so far has finished."""
        while not self.idle:
            self.step()
        return self.finished

    def cancel(self, uid: int, status: str = "cancelled") -> bool:
        """Finish a queued or running request NOW: its pages return to the
        pool, its partial tokens are recorded under `status`, and no other
        slot is touched — cancellation must never perturb a co-resident
        request's stream (pinned with the page-conservation invariant in
        tests/test_serving.py). A request preempted earlier returns its
        re-queued prompt (generated tokens folded in). False if `uid` is
        unknown or already finished. Call between rounds only (the engine
        is single-threaded host code; the async server serializes its
        cancellations onto the driver loop)."""
        for qi, req in enumerate(self.queue):
            if req.uid == uid:
                self.queue.pop(qi)
                self.cancelled += 1
                self._finish(
                    FinishedRequest(
                        uid=uid, tokens=req.prompt, token_times=[],
                        status=status,
                    )
                )
                return True
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.request.uid == uid:
                req = slot.request
                self.cancelled += 1
                self._finish(
                    FinishedRequest(
                        uid=uid,
                        tokens=np.concatenate(
                            [req.prompt, np.asarray(slot.generated, np.int32)]
                        ),
                        token_times=slot.token_times,
                        status=status,
                    )
                )
                self._release_slot(slot)
                self.slots[i] = None
                return True
        return False

    def hot_swap(
        self,
        params: GPTParams,
        *,
        draft_params: tp.Optional[GPTParams] = None,
        version: str = "inline",
        config: tp.Optional[GPTConfig] = None,
    ) -> tp.Dict[str, tp.Any]:
        """Stage a blue/green weight swap; flips at the first slot-free
        round boundary (immediately when idle). Same-shape swaps compile
        ZERO new programs; mismatches raise a structured HotSwapError
        before anything changes. Full protocol: sampling/ops.py,
        docs/ROBUSTNESS.md "Zero-downtime model ops"."""
        from midgpt_tpu.sampling import ops as _ops

        return _ops.stage_hot_swap(
            self, params, draft_params=draft_params, version=version,
            config=config,
        )

    def resize(
        self,
        num_pages: tp.Optional[int] = None,
        *,
        max_slots: tp.Optional[int] = None,
    ) -> tp.Dict[str, tp.Any]:
        """Live pool resize: migrate the resident working set into a fresh
        `num_pages` pool (int8 scales ride along), remap slots + trie, and
        install a new allocator. Shrinking below the resident working set
        raises a retryable PoolResizeError instead of dropping live data
        (sampling/ops.py)."""
        from midgpt_tpu.sampling import ops as _ops

        # A resize migrates the resident working set out of self.cache —
        # an unsettled in-flight group still writing into the OLD pool
        # must land (and its tokens commit) before the migration reads it.
        self._settle_inflight()
        return _ops.resize_pool(self, num_pages, max_slots=max_slots)

    def attach_spill(self, tier) -> None:
        """Wire a host-RAM spill tier (sampling/fleet.py SpillTier) under
        the prefix trie: every refcount-0 eviction — allocator pressure,
        forced flush, resize overflow, disagg adopt-side reclaim — lands
        the page's content in `tier` keyed by its full token prefix
        (PrefixCache.on_evict) instead of discarding it, stamped with the
        CURRENT weights_version so a hot swap can never resurrect
        old-weights KV. Requires the prefix cache: the trie is both the
        spill source and the re-adoption anchor."""
        if self.prefix_cache is None:
            raise ValueError("attach_spill requires prefix_cache=True")
        tier.set_page_size(self.page_size)
        self.spill_tier = tier
        self.prefix_cache.on_evict = lambda prefix, page: tier.spill(
            self.cache, prefix, page, self.weights_version
        )

    def _readopt_from_spill(self, slot: "_Slot", req: "Request") -> None:
        """Extend an admission's trie match with spilled pages: consult
        the tier for a resident run starting exactly where the match
        stopped, allocate plainly (a spill hit is an optimization, never
        a demand — it must not evict trie pages or preempt anyone),
        checksum-verify and move the run out of the tier, scatter it into
        the pool through the disagg adoption jit (pow2 dst bucket,
        oob-padded — the one page-transport funnel), and start the slot
        committed past it. The re-adopted pages are PRIVATE until prefill
        completion, when insert_live shares them like any other complete
        prompt pages. A checksum or weights_version mismatch truncates
        the run inside take_run and those tokens simply re-prefill —
        corrupt spill bytes can never reach a decode."""
        tier = self.spill_tier
        ps = self.page_size
        start = len(slot.pages)
        limit = (len(req.prompt) - 1) // ps - start
        if limit <= 0:
            return
        n = tier.peek_run(req.prompt, start, limit, self.weights_version)
        if n == 0:
            return
        n = min(n, self.allocator.free_count)  # plain alloc: take what's free
        if n == 0:
            return
        got = self.allocator.alloc(n)
        if got is None:
            return
        blocks_list = tier.take_run(req.prompt, start, n, self.weights_version)
        m = len(blocks_list)
        if m == 0:
            self.allocator.free(got)
            return
        if m < n:
            self.allocator.free(got[m:])
            got = got[:m]
        with self._trace.span("spill.readopt", "prefix", self._obs_tid):
            blocks = {
                key: np.stack(
                    [b[key] for b in blocks_list],
                    axis=1 if key.endswith("scale") else 2,
                )
                for key in blocks_list[0]
            }
            bucket = 1
            while bucket < m:
                bucket *= 2
            pad = bucket - m
            if pad:

                def _zpad(blk: np.ndarray, axis: int) -> np.ndarray:
                    shape = list(blk.shape)
                    shape[axis] = pad
                    return np.concatenate(
                        [blk, np.zeros(shape, blk.dtype)], axis=axis
                    )

                blocks = {
                    k: _zpad(b, 1 if k.endswith("scale") else 2)
                    for k, b in blocks.items()
                }
            dst = jnp.asarray(
                np.asarray(got + [self.cache.num_pages] * pad, np.int32)
            )
            from midgpt_tpu.sampling.disagg import _adopt_pages

            self.cache = _adopt_pages(
                self.mesh,
                self.cache,
                dst,
                {k: jnp.asarray(b) for k, b in blocks.items()},
            )
        slot.pages.extend(got)
        slot.prompt_pos = slot.length = (start + m) * ps
        self._prefix_matched_tokens += m * ps  # a cross-tier hit is a hit
        self.spill_readopted_pages += m
        self.spill_readopt_events += 1
        self._trace.instant(
            "spill.hit", "prefix", self._obs_tid,
            args={"uid": req.uid, "pages": m},
        )

    def _hot_swap_fault(self) -> None:
        """The `hot_swap_mid_decode` chaos fault: stage whatever weights
        the scenario registered on `swap_source` at this round boundary —
        the production swap path end to end, just triggered by the fault
        registry instead of an operator (robustness/chaos_serve.py)."""
        if self.swap_source is None:
            return
        payload = dict(self.swap_source())
        self.hot_swap(payload.pop("params"), **payload)

    def _pool_resize_fault(self) -> None:
        """The `pool_resize` chaos fault: resize to the next target on
        `resize_plan` (e.g. [43, 37] for a grow-then-shrink gate)."""
        if not self.resize_plan:
            return
        self.resize(self.resize_plan.pop(0))

    def cache_hbm_bytes(self) -> int:
        """Total device bytes of the target pool — K/V pages plus, in int8
        mode, the f32 scale side buffers (the honest spend a byte budget
        must be judged against)."""
        return sum(a.nbytes for a in jax.tree.leaves(self.cache))

    @staticmethod
    def compile_stats() -> tp.Dict[str, tp.Optional[int]]:
        """Compiled-program census of the serving jits (graftcheck pass-2
        hook). The scheduling claim in the module docstring — page tables
        and lengths are plain jit inputs, so admitting/finishing requests
        never recompiles — is only as good as these numbers staying flat:
        `decode` is bounded by |{(n_steps, page bucket)}|, `prefill` by
        |{page bucket}|, regardless of request mix. Pinned by
        tests/test_recompile_pins.py; reported by tools/bench_serve.py so
        drivers see compile-set growth as data, not as mystery latency.
        Process-global (module-level jits shared by every engine)."""
        from midgpt_tpu.analysis.hlo_audit import jit_cache_size

        return {
            "prefill": jit_cache_size(_serve_prefill_chunk),
            "decode": jit_cache_size(_serve_decode_chunk),
            "decode_group": jit_cache_size(_serve_decode_group),
            "spec_draft": jit_cache_size(_spec_draft_chunk),
            "spec_verify": jit_cache_size(_spec_verify_chunk),
        }

    def mesh_shape(self) -> tp.Optional[tp.Dict[str, int]]:
        """{'data': d, 'tp': t} when mesh-sharded, None single-chip."""
        from midgpt_tpu.parallel.serve_tp import mesh_shape

        return mesh_shape(self.mesh)

    def cache_hbm_bytes_per_shard(self) -> int:
        """Per-DEVICE bytes of the target pool. Every pool leaf (K/V pages
        and int8 scale side buffers) shards its head axis over 'tp' and
        replicates elsewhere, so a tp shard holds exactly total/tp — the
        number a per-chip HBM budget must be judged against, and the lever
        the tp bench reports: slot capacity per chip grows with the mesh
        (tools/bench_serve.py serve_tp profile)."""
        n_tp = 1 if self.mesh is None else int(self.mesh.shape["tp"])
        return self.cache_hbm_bytes() // n_tp

    def stats(self) -> tp.Dict[str, tp.Any]:
        """Deployment-shape + counter snapshot for SLO reporting: the
        `serve_slo` JSON lines (tools/loadgen.py) carry this so a sharded
        run is distinguishable from a single-chip one by its record alone."""
        return {
            "mesh": self.mesh_shape(),
            "cache_hbm_bytes": self.cache_hbm_bytes(),
            "cache_hbm_bytes_per_shard": self.cache_hbm_bytes_per_shard(),
            "rounds": self.rounds,
            "overlap_mode": self.overlap,
            "round_group": self.round_group,
            "overlap_kills": self.overlap_kills,
            "preemptions": self.preemptions,
            "timeouts": self.timeouts,
            "shed": self.shed,
            "cancelled": self.cancelled,
            "weights_version": self.weights_version,
            "hot_swaps": self.hot_swaps,
            "resizes": self.resizes,
            "spill_readopted_pages": self.spill_readopted_pages,
            "spill_readopt_events": self.spill_readopt_events,
            "window_reclaimed_pages": self.window_reclaimed_pages,
            "swap_pending": self._staged_swap is not None,
            "compile_counts": self.compile_stats(),
            # unified observability schema (docs/OBSERVABILITY.md): round
            # decomposition + metrics when an Observability is wired in,
            # {"enabled": False} otherwise — consumers key on the flag.
            "obs": (
                DISABLED_SNAPSHOT if self.obs is None else self.obs.snapshot()
            ),
        }

    # -- scheduling round ----------------------------------------------

    def step(self) -> None:
        """One round: expire -> admit -> one prefill chunk -> one decode
        chunk (or one draft-then-verify speculative round).

        The serving fault hooks fire here (robustness/faults.py; an
        empty registry — the default, always — costs a scan over nothing).
        All are keyed on the ROUND counter so chaos scenarios are
        deterministic for a seeded trace (`kill_mid_decode@7` always
        strikes round 7).

        With overlap="double" (and no draft model) the round runs the
        RESTRUCTURED order of `_step_overlapped` instead: dispatch this
        round's decode group FIRST, then settle the previous round and run
        every host phase while the new group computes behind the tunnel.
        With overlap="group" the order below is unchanged — only the
        decode call fuses `round_group` rounds into one dispatch."""
        if self.overlap == "double" and self.draft_params is None:
            self._step_overlapped()
            return
        self.rounds += 1
        tr = self._trace
        t_round = 0.0 if self.obs is None else self._clock()
        if faults.should_fire("poisoned_page", step=self.rounds):
            tr.instant("fault.poisoned_page", "fault", self._obs_tid)
            self._poison_page()
        if faults.should_fire("evict_shared_prefix", step=self.rounds):
            tr.instant("fault.evict_shared_prefix", "fault", self._obs_tid)
            self._evict_shared_prefix_fault()
        if faults.should_fire("hot_swap_mid_decode", step=self.rounds):
            tr.instant("fault.hot_swap_mid_decode", "fault", self._obs_tid)
            self._hot_swap_fault()
        if faults.should_fire("pool_resize", step=self.rounds):
            tr.instant("fault.pool_resize", "fault", self._obs_tid)
            self._pool_resize_fault()
        with tr.span("engine.expire", "phase", self._obs_tid):
            self._expire_round()
        if self._staged_swap is not None:
            # Blue/green flip point: after expiry (slots may have just
            # drained), before admission (which is paused while staged).
            from midgpt_tpu.sampling import ops as _ops

            _ops.maybe_flip_swap(self)
        with tr.span("engine.admit", "phase", self._obs_tid):
            self._admit()
        with tr.span("engine.prefill", "phase", self._obs_tid):
            self._prefill_round()
        if faults.should_fire("kill_mid_decode", step=self.rounds):
            tr.instant("fault.kill_mid_decode", "fault", self._obs_tid)
            self._kill_decode_round()
        elif self.draft_params is not None:
            self._spec_round()
        elif self.overlap == "group":
            self._decode_round_grouped()
        else:
            self._decode_round()
        if self.obs is not None:
            tr.complete(
                "engine.round", "round", self._obs_tid, t_round,
                self._clock() - t_round, args={"round": self.rounds},
            )

    def _step_overlapped(self) -> None:
        """One DOUBLE-BUFFERED round (overlap="double"): dispatch round
        k's decode group FIRST — chaining device-side token/length state
        from the still-unsettled round k-1 — then settle round k-1 and run
        every host phase (expire, swap flip, admission, prefill) while
        round k's program runs behind the tunnel. The settle's force waits
        only for round k-1, never for round k, so round k-1's host
        post-processing is HIDDEN under round k's device time — the
        `overlap_hidden_ms` measure (obs/__init__.py).

        The restructured order is what makes scheduler effects one round
        late BY CONSTRUCTION (docs/SERVING.md "Round-overlap dispatch"):
        round N's host phase runs here in step N+1, after dispatch
        D_{N+1} is already in flight, so a request admitted or evicted
        during it first appears/disappears in dispatch D_{N+2} — never
        mid-flight. Faults that mutate the pool or the engine shape
        (poisoned_page, evict_shared_prefix, hot_swap_mid_decode,
        pool_resize) assume a settled round boundary, so the in-flight
        group is drained before any of them strike."""
        self.rounds += 1
        tr = self._trace
        t_round = 0.0 if self.obs is None else self._clock()
        if self._inflight is not None and self._fault_needs_drain():
            self._settle_inflight()
        if self._inflight is not None and faults.should_fire(
            "kill_overlapped_round", step=self.rounds
        ):
            tr.instant("fault.kill_overlapped_round", "fault", self._obs_tid)
            self._kill_overlapped_round()
        if faults.should_fire("poisoned_page", step=self.rounds):
            tr.instant("fault.poisoned_page", "fault", self._obs_tid)
            self._poison_page()
        if faults.should_fire("evict_shared_prefix", step=self.rounds):
            tr.instant("fault.evict_shared_prefix", "fault", self._obs_tid)
            self._evict_shared_prefix_fault()
        if faults.should_fire("hot_swap_mid_decode", step=self.rounds):
            tr.instant("fault.hot_swap_mid_decode", "fault", self._obs_tid)
            self._hot_swap_fault()
        if faults.should_fire("pool_resize", step=self.rounds):
            tr.instant("fault.pool_resize", "fault", self._obs_tid)
            self._pool_resize_fault()
        if faults.should_fire("kill_mid_decode", step=self.rounds):
            # This round's dispatch dies: settle the previous group (its
            # tokens landed before the failure), then recompute-preempt
            # the decode-ready slots exactly like the classic path.
            tr.instant("fault.kill_mid_decode", "fault", self._obs_tid)
            self._settle_inflight()
            self._kill_decode_round()
            handle = None
        else:
            handle = self._dispatch_decode(self._inflight)
        prev, self._inflight = self._inflight, handle
        if prev is not None:
            self._settle_round(prev)
        with tr.span("engine.expire", "phase", self._obs_tid):
            self._expire_round()
        if self._staged_swap is not None:
            # The flip reads/replaces engine weights and waits for a
            # slot-free boundary — an unsettled group is pending work the
            # drain must observe, so settle before consulting it.
            self._settle_inflight()
            from midgpt_tpu.sampling import ops as _ops

            _ops.maybe_flip_swap(self)
        with tr.span("engine.admit", "phase", self._obs_tid):
            self._admit()
        with tr.span("engine.prefill", "phase", self._obs_tid):
            self._prefill_round()
        if self.obs is not None:
            tr.complete(
                "engine.round", "round", self._obs_tid, t_round,
                self._clock() - t_round, args={"round": self.rounds},
            )

    def _kill_decode_round(self) -> None:
        """The `kill_mid_decode` fault: this round's decode dispatch died
        (device restart, tunnel drop) and its tokens never landed. Recovery
        is the eviction machinery the engine already trusts: every
        decode-ready slot is recompute-preempted — pages freed, generated
        tokens folded into the prompt, re-queued oldest-first — so the
        requests re-prefill and continue with token streams identical to an
        unfaulted run (greedy recompute parity is pinned by
        tests/test_serving.py::test_serve_parity_under_eviction and
        asserted end to end by the chaos gate, tests/test_chaos_serve.py).
        Mid-prefill slots are untouched: the fault models the DECODE
        program dying, and prefill chunks already landed."""
        victims = [
            s
            for s in self.slots
            if s is not None and not s.prefilling and s.remaining > 0
        ]
        # Youngest evicts first: each _evict inserts at the queue FRONT, so
        # reverse admit order leaves the queue oldest-first for re-admission.
        for s in sorted(victims, key=lambda s: s.admit_order, reverse=True):
            self._evict(s)
        self.decode_kills += 1

    # -- round-overlap dispatch (docs/SERVING.md) ----------------------

    # Faults that mutate the pool or the engine's shape mid-round; each
    # assumes a settled round boundary, so an in-flight overlapped group
    # is drained before any of them fires (_step_overlapped).
    _DRAIN_FAULTS = (
        "poisoned_page",
        "evict_shared_prefix",
        "hot_swap_mid_decode",
        "pool_resize",
    )

    def _fault_needs_drain(self) -> bool:
        """Peek (without consuming) whether a boundary-assuming fault can
        fire this round — `faults.active()` is a copy, `should_fire` later
        in the step still performs the one consuming match."""
        for f in faults.active():
            if (
                f.kind in self._DRAIN_FAULTS
                and f.times > 0
                and (f.step is None or f.step == self.rounds)
            ):
                return True
        return False

    def _force(self, fn: tp.Callable[[], tp.Any], label: str) -> tp.Any:
        """Route a host<->device force through the watchdog when armed —
        the ONE funnel every decode-path sync takes, so a hang inside an
        overlapped in-flight dispatch escalates exactly like a classic
        round's (robustness/watchdog.py)."""
        if self.watchdog is not None:
            return self.watchdog.sync(fn, label=label)
        return fn()

    def _settle_inflight(self) -> None:
        """Settle the in-flight group now, if any (drain point for mode
        flips, pool mutations, and engine teardown paths)."""
        h, self._inflight = self._inflight, None
        if h is not None:
            self._settle_round(h)

    def _kill_overlapped_round(self) -> None:
        """The `kill_overlapped_round` fault: the in-flight group's
        dispatch died while the previous round's host work ran (device
        restart / tunnel drop with TWO rounds in the pipe). Its tokens
        never land — the handle is dropped WITHOUT forcing — and every
        slot that was in the killed batch is recompute-preempted, the
        same recovery (and the same greedy-parity guarantee) as
        kill_mid_decode — pinned end to end by tests/test_chaos_serve.py
        ::test_chaos_kill_overlapped_round_recompute_parity. Slots that
        already departed are skipped; bystanders (mid-prefill slots,
        other streams) are untouched."""
        h, self._inflight = self._inflight, None
        if h is None:
            return
        self.overlap_kills += 1
        victims = [
            s
            for idx, s in zip(h.active_idx, h.slots)
            if self.slots[idx] is s and s.remaining > 0
        ]
        for s in sorted(victims, key=lambda s: s.admit_order, reverse=True):
            self._evict(s)

    def _decode_round_grouped(self) -> None:
        """overlap="group": one fused multi-round dispatch, settled at
        the group edge within the same step (no in-flight carry-over)."""
        h = self._dispatch_decode(None)
        if h is not None:
            self._settle_round(h)

    def _dispatch_decode(
        self, prev: tp.Optional[_InflightRound]
    ) -> tp.Optional[_InflightRound]:
        """Assemble and ENQUEUE one multi-round decode group without
        forcing it; returns the in-flight handle (None when nothing can
        decode). `prev` is the still-unsettled previous group under
        double-buffering: its slots are CHAINED — their true token/length
        state rides in on the previous program's unforced outputs and is
        merged in-program under `chain_mask`, so the host's one-round-
        stale view never reaches the device. Page provisioning for a
        chained slot budgets from its WORST-CASE post-settle length
        (prev.worst_len); if the pool can't cover a full group it falls
        back to one sub-round, and failing that the slot rides along
        masked (chained — the device takes zero steps for it) or defers
        to a later round (fresh)."""
        chained: tp.Set[int] = set()
        if prev is not None:
            chained = {
                idx
                for idx, s in zip(prev.active_idx, prev.slots)
                if self.slots[idx] is s
            }
        S = self.config.block_size
        ps = self.page_size

        def _want(s: _Slot) -> int:
            # The settle bound: at length P + max_new - 1 the request has
            # committed its full generation budget (_append_token's count).
            req = s.request
            return min(len(req.prompt) + req.max_new_tokens - 1, S)

        def _base(i: int, s: _Slot) -> int:
            return int(prev.worst_len[i]) if i in chained else s.length

        cand = []
        for i, s in enumerate(self.slots):
            if s is None or s.prefilling:
                continue
            if i not in chained and s.remaining <= 0:
                continue
            if _base(i, s) < _want(s):
                cand.append((i, s))
        if not cand:
            return None
        need = min(
            self.decode_chunk, max(_want(s) - _base(i, s) for i, s in cand)
        )
        n = 1 << (need.bit_length() - 1)  # largest power of two <= need
        T = n * self.round_group
        for i, slot in list(cand):
            if self.slots[i] is not slot:
                continue  # evicted by an older slot's growth in this loop
            upto = min(_want(slot), _base(i, slot) + T)
            if not self._ensure_pages(slot, upto):
                fallback = min(_want(slot), _base(i, slot) + n)
                if not self._ensure_pages(slot, fallback) and i not in chained:
                    # Pool held by slots at least as old — defer (classic
                    # _decode_round behavior). A chained slot keeps riding:
                    # its provisioned pages already cover worst_len, so
                    # max_len clamps it to zero steps, never to an overrun.
                    cand = [(j, t) for j, t in cand if j != i]
        cand = [(i, s) for i, s in cand if self.slots[i] is s]
        if not cand:
            return None

        obs = self.obs
        t0 = 0.0 if obs is None else self._clock()
        B = self.max_slots
        token = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        eos = np.full((B,), -1, np.int32)
        max_len = np.zeros((B,), np.int32)
        chain_mask = np.zeros((B,), bool)
        worst = np.zeros((B,), np.int32)
        for i, s in cand:
            token[i] = s.generated[-1] if s.generated else s.request.prompt[-1]
            lengths[i] = s.length
            active[i] = True
            if s.request.eos_id is not None:
                eos[i] = s.request.eos_id
            max_len[i] = min(_want(s), len(s.pages) * ps)
            chain_mask[i] = i in chained
            worst[i] = min(_base(i, s) + T, max_len[i])
        if self.temperature == 0.0:
            key = None
        else:
            self._key, key = jax.random.split(self._key)
        round_span = int(worst.max())
        bucket = self._page_bucket(round_span)
        # Chain carry-in: the previous group's unforced outputs when
        # chaining, else zero fillers of the same shape/dtype — ONE
        # compiled program serves both cases, and nothing here syncs.
        if prev is not None:
            chain_token, chain_len = prev.tok_fin, prev.len_fin
        else:
            chain_token = np.zeros((B,), np.int32)
            chain_len = np.zeros((B,), np.int32)
        self.cache, toks, emitted, tok_fin, len_fin = _serve_decode_group(
            self.config,
            self.params,
            jnp.asarray(token),
            self.cache,
            jnp.asarray(self._page_table(bucket)),
            jnp.asarray(lengths),
            jnp.asarray(active),
            jnp.asarray(eos),
            jnp.asarray(max_len),
            jnp.asarray(chain_mask),
            jnp.asarray(chain_token),
            jnp.asarray(chain_len),
            n,
            self.round_group,
            self.temperature,
            self.top_k,
            self.top_p,
            self.attn_impl,
            key,
            self.mesh,
            self._split_bucket(round_span),
        )
        t1 = 0.0 if obs is None else self._clock()
        self.dispatch_log.append(
            (self.rounds, tuple(s.request.uid for _, s in cand))
        )
        return _InflightRound(
            toks=toks,
            emitted=emitted,
            tok_fin=tok_fin,
            len_fin=len_fin,
            n_steps=T,
            active_idx=[i for i, _ in cand],
            slots=[s for _, s in cand],
            worst_len=worst,
            round_no=self.rounds,
            t0=t0,
            t1=t1,
        )

    def _settle_round(self, h: _InflightRound) -> None:
        """Force a dispatched group and commit its tokens. Indices whose
        slot object changed since dispatch (finished, evicted, cancelled,
        timed out) are SKIPPED — their in-flight tokens are discarded, and
        recompute preemption regenerates them bit-exactly. The force is
        the round's one host<->device sync, watchdog-bounded; under
        double-buffering the time between dispatch-return (h.t1) and this
        force starting is host work the overlap HID, recorded as
        `overlap_hidden` in the round decomposition (obs/__init__.py)."""
        obs = self.obs
        t_force = 0.0 if obs is None else self._clock()
        toks, emitted = self._force(
            lambda: (np.asarray(h.toks), np.asarray(h.emitted)),
            "serve.overlap_sync",
        )
        t_done = self._clock()
        for idx, s in zip(h.active_idx, h.slots):
            if self.slots[idx] is not s:
                continue
            for j in range(h.n_steps):
                if not emitted[j, idx]:
                    continue
                s.length += 1
                if self._append_token(idx, s, int(toks[j, idx]), t_done):
                    break  # finished (max_new or EOS); rest discarded
        if obs is not None:
            obs.record_round(
                "decode", self._obs_tid, h.t0, h.t1, t_done, self._clock(),
                hidden_s=max(0.0, t_force - h.t1),
            )

    def _poison_page(self) -> None:
        """The `poisoned_page` fault: corrupt the first page of the
        youngest running slot in place (NaN for float pools, saturated 127
        for int8), modeling HBM damage to committed K/V. No recovery is
        attempted — the point the chaos gate asserts is ISOLATION: page
        tables never alias live pages, so every other slot's tokens are
        bit-identical to an unfaulted run, the engine keeps serving, and
        the allocator stays conserved. The victim uid lands in
        `poisoned_uids` so chaos parity checks exclude exactly it
        (tests/test_chaos_serve.py pins the isolation claim). With the
        prefix cache on, the damaged page can be SHARED — every slot whose
        table maps it is marked (a future trie match of the page is out of
        scope for this fault: the poisoned_page chaos scenario runs
        cache-off, and the trie-specific fault is evict_shared_prefix)."""
        victim = max(
            (
                s
                for s in self.slots
                if s is not None and any(p >= 0 for p in s.pages)
            ),
            key=lambda s: s.admit_order,
            default=None,
        )
        if victim is None:
            return
        page = next(p for p in victim.pages if p >= 0)
        bad = (
            float("nan")
            if jnp.issubdtype(self.cache.k.dtype, jnp.floating)
            else 127
        )
        self.cache = dataclasses.replace(
            self.cache,
            k=self.cache.k.at[:, :, page].set(bad),
            v=self.cache.v.at[:, :, page].set(bad),
        )
        for s in self.slots:
            if (
                s is not None
                and page in s.pages
                and s.request.uid not in self.poisoned_uids
            ):
                self.poisoned_uids.append(s.request.uid)

    def _evict_shared_prefix_fault(self) -> None:
        """The `evict_shared_prefix` fault: a pressure spike (or an
        operator flush) force-reclaims EVERY unreferenced trie page at
        once, hot nodes included — ignoring the LRU order that normally
        protects them. What must hold, and what the chaos gate asserts
        (tests/test_chaos_serve.py): referenced entries survive — a shared
        node is never evicted out from under a live reader — so every live
        stream stays bit-identical to an unfaulted run; later requests
        simply miss the flushed prefixes, re-prefill, and re-populate the
        trie; and pages + refcounts stay conserved through the flush."""
        if self.prefix_cache is None:
            return
        freed = self.prefix_cache.evict(0, force_all=True)
        self.allocator.free(freed)
        self.prefix_evictions += len(freed)

    def _expire_round(self) -> None:
        """Finish every deadline-expired request with a `timeout` status.

        Expired QUEUED requests stop blocking FCFS admission; expired
        RUNNING slots free their pages immediately — a stalled client
        deadline must not hold pool pages hostage while younger requests
        get evicted around it. Whatever tokens were generated before the
        deadline are returned (partial result)."""
        now = self._clock()

        def expired(req: Request) -> bool:
            return req.deadline is not None and now > req.deadline

        still_queued = []
        for req in self.queue:
            if expired(req):
                self.timeouts += 1
                self._finish(
                    FinishedRequest(
                        uid=req.uid, tokens=req.prompt, token_times=[],
                        status="timeout",
                    )
                )
            else:
                still_queued.append(req)
        self.queue[:] = still_queued
        for i, slot in enumerate(self.slots):
            if slot is not None and expired(slot.request):
                req = slot.request
                self.timeouts += 1
                self._finish(
                    FinishedRequest(
                        uid=req.uid,
                        tokens=np.concatenate(
                            [req.prompt, np.asarray(slot.generated, np.int32)]
                        ),
                        token_times=slot.token_times,
                        status="timeout",
                    )
                )
                self._release_slot(slot)
                self.slots[i] = None

    def _admit(self) -> None:
        now = self._clock()
        for i, s in enumerate(self.slots):
            if s is None and self.queue:
                if self._staged_swap is not None:
                    # A staged hot-swap pauses FRESH admissions (queued
                    # arrivals deterministically take the new weights), but
                    # a recompute-preempted stream is old-side work already
                    # in flight: it must resume on the old weights, both so
                    # its committed tokens never straddle the flip and so
                    # the drain the flip waits for can complete at all
                    # (sampling/ops.py).
                    qi = next(
                        (j for j, q in enumerate(self.queue)
                         if q.uid in self._resumed_uids),
                        None,
                    )
                else:
                    # Admission ORDER is the scheduler's call (FCFS: the
                    # queue head; SLO: earliest deadline first).
                    qi = self.scheduler.select_admit(self.queue, now)
                if qi is None:
                    break
                req = self.queue.pop(qi)
                # A preempted request restarts its k adaptation from
                # spec_k_max like a fresh one — the draft pool it re-prefills
                # is fresh too, so old acceptance evidence is stale anyway.
                slot = _Slot(req, self._admitted, spec_k=self.spec_k_max)
                if self.prefix_cache is not None:
                    # Map every fully-matched page into the slot's table and
                    # skip its prefill: the slot starts committed at the
                    # matched length and chunk-prefills only the tail. The
                    # len(prompt) - 1 cap guarantees the final prompt token
                    # is always re-prefilled, so first-token logits come
                    # from a live chunk (never from a skipped one).
                    with self._trace.span("trie.match", "prefix", self._obs_tid):
                        mr = self.prefix_cache.match(
                            req.prompt, max_tokens=len(req.prompt) - 1
                        )
                    if mr.pages:
                        slot.pages = list(mr.pages)
                        slot.n_shared = len(mr.pages)
                        slot.prompt_pos = slot.length = mr.tokens
                    ps = self.page_size
                    self._prefix_matchable_tokens += (
                        (len(req.prompt) - 1) // ps
                    ) * ps
                    self._prefix_matched_tokens += mr.tokens
                    if mr.cow_truncated:
                        self.cow_pages += 1
                    if self.spill_tier is not None:
                        self._readopt_from_spill(slot, req)
                self.slots[i] = slot
                self._admitted += 1
                self._trace.instant(
                    "admitted", "lifecycle", self._obs_tid,
                    args={"uid": req.uid, "slot": i},
                )

    def _ensure_pages(self, slot: _Slot, upto_tokens: int) -> bool:
        """Grow slot's page list to cover positions [0, upto_tokens);
        True on success. On pool exhaustion, first reclaims unreferenced
        prefix-cache pages (LRU; a trie page nobody reads must never cost a
        live request a preemption), then asks the scheduler to pick a
        preemption victim among the STRICTLY YOUNGER running slots (the
        engine-enforced deadlock-freedom invariant: the oldest request
        always makes progress regardless of policy) and retries; False
        only when no younger victim exists or the policy defers."""
        need = -(-upto_tokens // self.page_size) - len(slot.pages)
        while need > 0:
            got = self.allocator.alloc(need)
            if got is not None:
                slot.pages.extend(got)
                return True
            if self.prefix_cache is not None:
                reclaimed = self.prefix_cache.evict(
                    need - self.allocator.free_count
                )
                if reclaimed:
                    self.allocator.free(reclaimed)
                    self.prefix_evictions += len(reclaimed)
                    continue
            candidates = [
                s
                for s in self.slots
                if s is not None and s.admit_order > slot.admit_order
            ]
            if not candidates:
                return False
            victim = self.scheduler.select_victim(slot, candidates, self._clock())
            if victim is None:
                return False
            if not any(victim is c for c in candidates):
                raise RuntimeError(
                    f"scheduler {self.scheduler.name!r} returned a "
                    "non-candidate victim — preemption must pick from the "
                    "strictly-younger running slots it was offered"
                )
            self._evict(victim)
        return True

    def _evict(self, victim: _Slot) -> None:
        """Recompute-style preemption: fold generated tokens into the
        prompt, free the pages, and re-queue at the FRONT so the request
        resumes (by re-prefilling) as soon as the pool breathes.

        With the prefix cache on, "free" means release THROUGH the trie:
        the victim's complete committed pages become refcount-0 trie
        entries, and the folded prompt's first len - 1 tokens are exactly
        the committed content — so readmission re-matches every one of
        those pages and re-prefills only the sub-page tail plus the pending
        token, instead of the whole history (the r10 self-re-prefill fix,
        pinned by tests/test_prefix_cache.py). The released pages are also
        the freshest LRU entries, so pool pressure reclaims them last."""
        i = self.slots.index(victim)
        req = victim.request
        new_prompt = np.concatenate(
            [req.prompt, np.asarray(victim.generated, np.int32)]
        )
        self.queue.insert(
            0,
            Request(
                req.uid,
                new_prompt,
                req.max_new_tokens - len(victim.generated),
                req.eos_id,
                req.deadline,  # the clock keeps running across preemptions
            ),
        )
        self._release_slot(victim)
        self.slots[i] = None
        self.preemptions += 1
        self._resumed_uids.add(req.uid)
        self._trace.instant(
            "preempt", "lifecycle", self._obs_tid, args={"uid": req.uid}
        )

    def _release_slot(self, slot: _Slot) -> None:
        """The ONE funnel a departing slot's pages go through (finish,
        cancel, timeout, preemption). Cache off: straight back to the
        allocator. Cache on: the trie drops the slot's shared-page refs,
        absorbs its complete committed pages for future matches, and only
        the remainder (partial tails, content-duplicates) hits the free
        list — page conservation becomes free_count + trie pages ==
        num_pages - 1 (tests/test_prefix_cache.py, chaos_serve.py)."""
        if self.prefix_cache is None:
            # -1 entries are window-reclaimed placeholders (already freed)
            self.allocator.free(p for p in slot.pages if p >= 0)
            return
        with self._trace.span("trie.release", "prefix", self._obs_tid):
            committed = np.concatenate(
                [slot.request.prompt, np.asarray(slot.generated, np.int32)]
            )[: slot.length]
            self.allocator.free(
                self.prefix_cache.release(committed, slot.pages, slot.n_shared)
            )

    def _page_table(self, n_pages: tp.Optional[int] = None) -> np.ndarray:
        table = np.zeros((self.max_slots, n_pages or self.max_pages_per_slot), np.int32)
        for i, s in enumerate(self.slots):
            if s is not None:
                pages = s.pages[: table.shape[1]]
                table[i, : len(pages)] = pages
        # Window-reclaimed entries (-1 in slot.pages) park on the sink page:
        # the kernel sweep skips them and the mask hides their columns, but
        # the BlockSpec index map still needs a valid physical page.
        np.maximum(table, 0, out=table)
        return table

    def _reclaim_window(self, slot: _Slot) -> None:
        """Free this slot's pages that no FUTURE attention row can see.

        Page j (positions [j*ps, (j+1)*ps)) is dead once the youngest
        visible position has moved past it — counts only grow, so
        (j+1)*ps <= length - sliding_window is permanent — unless it holds
        sink-prefix tokens. Freed entries become -1 placeholders so the
        page list keeps its LOGICAL length (position -> table column stays
        the identity; _ensure_pages and the settle bound len(pages)*ps are
        untouched); _page_table parks them on the sink page. Gated off
        under the prefix cache (the trie owns shared pages' lifetime) and
        speculative decoding (verify rollback re-reads recent history);
        conservation becomes free + live non-placeholder == num_pages - 1."""
        W = self.config.sliding_window
        if (
            not W
            or self.prefix_cache is not None
            or self.draft_config is not None
        ):
            return
        ps = self.page_size
        first_live = max(0, slot.length - W) // ps  # pages below are dead
        sink_pages = -(-self.config.attn_sinks // ps)  # keep the sink prefix
        dead = [
            j
            for j in range(sink_pages, first_live)
            if slot.pages[j] >= 0
        ]
        if not dead:
            return
        self.allocator.free(slot.pages[j] for j in dead)
        for j in dead:
            slot.pages[j] = -1
        self.window_reclaimed_pages += len(dead)

    def _page_bucket(self, max_tokens: int) -> int:
        """Smallest power-of-two page count covering `max_tokens` positions.

        The serve step's attention (and its CPU gather fallback) is
        O(table_width x page_size) per slot; slicing the table to a bucket
        makes it O(longest-active-request) instead of O(block_size) — the
        used-length attention lever of the ISSUE — while the pow2 bucketing
        keeps the compile set logarithmic, not per-length."""
        need = -(-max_tokens // self.page_size)
        b = 1
        while b < need:
            b *= 2
        return min(b, self.max_pages_per_slot)

    def _split_bucket(self, max_tokens: int) -> int:
        """Static split-K factor for a round whose widest slot spans
        `max_tokens` positions: double the split for every page-bucket
        doubling past 512 tokens (so each partition sweeps >= 512 tokens),
        capped at 8. Traffic at or under 512 tokens resolves to 1 — the
        unsplit program, byte-identical to a split_k-naive engine — so the
        rule only engages (and only adds compile-cache entries) when long
        requests actually arrive. Forced int engines skip the rule; the
        kernels normalize the forced value to a pow2 divisor of the round's
        table width (kernels/attention_template.normalize_split_k)."""
        if self.split_k != "auto":
            return self.split_k
        tokens = self._page_bucket(max_tokens) * self.page_size
        split = 1
        while split < 8 and tokens // (2 * split) >= 512:
            split *= 2
        return split

    def _prefill_round(self) -> None:
        """Advance every mid-prompt slot by one (padded) chunk.

        One chunk per slot per round bounds how long any running decode
        stalls (a 30k prompt can't monopolize the device), while letting
        freshly admitted slots reach the decode batch in parallel — an
        empty decode slot is pure lost throughput."""
        for slot_i, slot in enumerate(self.slots):
            if slot is not None and slot.prefilling:
                self._prefill_one(slot_i, slot)

    def _prefill_one(self, slot_i: int, slot: _Slot) -> None:
        prompt = slot.request.prompt
        n_valid = min(self.prefill_chunk, len(prompt) - slot.prompt_pos)
        if not self._ensure_pages(slot, slot.prompt_pos + n_valid):
            return  # pool fully ours and still short — wait for finishes
        if self.slots[slot_i] is not slot:  # evicted ourselves? (impossible)
            return
        chunk = np.zeros((1, self.prefill_chunk), np.int32)
        chunk[0, :n_valid] = prompt[slot.prompt_pos : slot.prompt_pos + n_valid]
        bucket = self._page_bucket(slot.prompt_pos + n_valid)
        row = jnp.asarray(self._page_table(bucket)[slot_i : slot_i + 1])
        chunk_j = jnp.asarray(chunk)
        start_j = jnp.asarray(slot.prompt_pos, jnp.int32)
        n_valid_j = jnp.asarray(n_valid, jnp.int32)
        # Span covers host assembly + async ENQUEUE only — prefill logits
        # are not forced here (mid-prompt chunks never sync; the final
        # chunk's force happens in the first-token block below).
        with self._trace.span("prefill.chunk", "prefill", self._obs_tid):
            logits, self.cache = _serve_prefill_chunk(
                self.config,
                self.params,
                chunk_j,
                start_j,
                n_valid_j,
                self.cache,
                row,
                self.mesh,
            )
            if self.draft_params is not None and not self.draft_shares_cache:
                # A separate draft model's pool must hold the same positions
                # as the target's — the spec round's draft steps attend
                # through the shared page table under the same per-slot
                # lengths. Draft prefill logits are discarded (the pending
                # token is sampled from the TARGET). A prefix self-draft
                # skips this: the target prefill above already filled its
                # layers of the shared pool.
                _, self.draft_cache = _serve_prefill_chunk(
                    self.draft_config,
                    self.draft_params,
                    chunk_j,
                    start_j,
                    n_valid_j,
                    self.draft_cache,
                    row,
                    self.mesh,
                )
        slot.prompt_pos += n_valid
        slot.length = slot.prompt_pos
        self._reclaim_window(slot)  # long prompts free behind-window pages
        self.prefilled_tokens += n_valid
        if not slot.prefilling:
            if self.prefix_cache is not None:
                # The prompt's complete pages are immutable from here on
                # (every later write lands at a position >= len(prompt)):
                # share them so concurrent and future requests — including
                # this one after a preemption — skip their prefill.
                slot.n_shared = self.prefix_cache.insert_live(
                    prompt, slot.pages, slot.n_shared
                )
            # Prompt complete: sample the first generated token from the
            # last valid prompt position's logits (host-side; greedy argmax
            # matches engine.generate's sample_logits(temperature=0) exactly).
            # The np.asarray is the force/sync — the span holds the device
            # wait for the final prefill chunk plus the host sample.
            with self._trace.span(
                "prefill.first_token", "prefill", self._obs_tid
            ):
                last = np.asarray(logits)[0, n_valid - 1]
                if self.temperature == 0.0:
                    tok = int(np.argmax(last.astype(np.float32)))
                else:
                    self._key, k = jax.random.split(self._key)
                    tok = int(
                        sample_logits(
                            jnp.asarray(last)[None],
                            k,
                            self.temperature,
                            self.top_k,
                            self.top_p,
                        )[0]
                    )
            self._append_token(slot_i, slot, tok, self._clock())

    def _decode_round(self) -> None:
        active_idx = [
            i
            for i, s in enumerate(self.slots)
            if s is not None and not s.prefilling and s.remaining > 0
        ]
        if not active_idx:
            return
        S = self.config.block_size
        budget = min(
            self.decode_chunk,
            min(self.slots[i].remaining for i in active_idx),
            min(S - self.slots[i].length for i in active_idx),
        )
        n = 1 << (budget.bit_length() - 1)  # largest power of two <= budget
        for i in list(active_idx):
            slot = self.slots[i]
            if slot is None:
                # An older slot's _ensure_pages earlier in this loop evicted
                # this one (eviction picks the youngest slot, which can sit
                # at any index). It is already re-queued; skip it.
                active_idx.remove(i)
                continue
            if not self._ensure_pages(slot, slot.length + n):
                # Reachable: the pool is held by slots at least as old as
                # this one, so there is no younger victim to evict. Defer
                # the slot to a later round; it resumes once older requests
                # finish and free pages.
                active_idx.remove(i)
        # A slot processed earlier in the loop can still be evicted by a
        # later, older slot's growth — drop any that went None.
        active_idx = [i for i in active_idx if self.slots[i] is not None]
        if not active_idx:
            return

        # Round decomposition (obs/__init__.py docstring): t0 -> t1 is host
        # assembly + jit ENQUEUE, t1 -> t_done is device compute + tunnel
        # round-trip (the np.asarray force is the only sync that works
        # through the tunnel — CLAUDE.md), t_done -> t_post is token commit.
        obs = self.obs
        t0 = 0.0 if obs is None else self._clock()
        token = np.zeros((self.max_slots,), np.int32)
        lengths = np.zeros((self.max_slots,), np.int32)
        active = np.zeros((self.max_slots,), bool)
        for i in active_idx:
            s = self.slots[i]
            token[i] = s.generated[-1] if s.generated else s.request.prompt[-1]
            lengths[i] = s.length
            active[i] = True
        if self.temperature == 0.0:
            key = None
        else:
            self._key, key = jax.random.split(self._key)
        round_span = max(self.slots[i].length for i in active_idx) + n
        bucket = self._page_bucket(round_span)
        self.cache, toks = _serve_decode_chunk(
            self.config,
            self.params,
            jnp.asarray(token),
            self.cache,
            jnp.asarray(self._page_table(bucket)),
            jnp.asarray(lengths),
            jnp.asarray(active),
            n,
            self.temperature,
            self.top_k,
            self.top_p,
            self.attn_impl,
            key,
            self.mesh,
            self._split_bucket(round_span),
        )
        t1 = 0.0 if obs is None else self._clock()
        self.dispatch_log.append(
            (
                self.rounds,
                tuple(self.slots[i].request.uid for i in active_idx),
            )
        )
        # The round's ONE host<->device sync; watchdog-bounded when armed —
        # the force below is where a dead tunnel would wedge forever.
        toks = self._force(
            lambda: np.asarray(toks), "serve.decode_sync"
        )  # (n, B)
        t_done = self._clock()
        for i in active_idx:
            slot = self.slots[i]
            if slot is None:
                continue
            for j in range(n):
                slot.length += 1
                if self._append_token(i, slot, int(toks[j, i]), t_done):
                    break  # finished (max_new or EOS); rest of chunk discarded
        if obs is not None:
            obs.record_round(
                "decode", self._obs_tid, t0, t1, t_done, self._clock()
            )

    def _spec_round(self) -> None:
        """One speculative round: k draft proposals per active slot (one
        program), one batched k+1-token verify forward + rejection sampler
        (one program), then host-side commit and page-aligned rollback.

        Rollback never touches device memory: a slot that accepted j of k
        drafts sets length = old + 1 + j and frees the tail pages past
        ceil(length / page_size) — the rejected columns stay in the pool,
        masked by every later read until the slot grows back over them
        (write-before-read; GPT.verify_step_paged docstring). k for the
        round is the pow2 min of the active slots' adaptive spec_k, so the
        compile set is one draft + one verify program per k bucket
        (tests/test_recompile_pins.py)."""
        active_idx = [
            i
            for i, s in enumerate(self.slots)
            if s is not None and not s.prefilling and s.remaining > 0
        ]
        if not active_idx:
            return
        S = self.config.block_size
        # submit() caps prompt + max_new at S, so an unfinished slot always
        # has length <= S - 2 and k_cap >= 1; the fallback is defensive
        # (a plain decode round also keeps the draft pool one round stale,
        # which only costs acceptance, never correctness).
        k_cap = min(S - 1 - self.slots[i].length for i in active_idx)
        budget = min([k_cap] + [self.slots[i].spec_k for i in active_idx])
        if budget < 1:
            self._decode_round()
            return
        k = 1 << (budget.bit_length() - 1)  # largest power of two <= budget
        for i in list(active_idx):
            slot = self.slots[i]
            if slot is None:
                # evicted by an older slot's page growth earlier in this loop
                active_idx.remove(i)
                continue
            if not self._ensure_pages(slot, slot.length + k + 1):
                active_idx.remove(i)  # pool held by older slots; wait
        active_idx = [i for i in active_idx if self.slots[i] is not None]
        if not active_idx:
            return

        # Same four-boundary decomposition as _decode_round; t1 is taken
        # after the VERIFY call returns (both programs enqueued by then),
        # with draft/verify enqueue sub-spans recorded off the same reads.
        obs = self.obs
        t0 = 0.0 if obs is None else self._clock()
        token = np.zeros((self.max_slots,), np.int32)
        lengths = np.zeros((self.max_slots,), np.int32)
        active = np.zeros((self.max_slots,), bool)
        for i in active_idx:
            s = self.slots[i]
            token[i] = s.generated[-1] if s.generated else s.request.prompt[-1]
            lengths[i] = s.length
            active[i] = True
        if self.temperature == 0.0:
            key_d = key_v = None
        else:
            self._key, key_d, key_v = jax.random.split(self._key, 3)
        round_span = max(self.slots[i].length for i in active_idx) + k + 1
        bucket = self._page_bucket(round_span)
        split_k = self._split_bucket(round_span)
        table = jnp.asarray(self._page_table(bucket))
        token_j = jnp.asarray(token)
        lengths_j = jnp.asarray(lengths)
        active_j = jnp.asarray(active)
        # drafts/draft_probs stay on device between the two dispatches —
        # the host only ever reads the small (B,) / (B, k+1) verify outputs.
        # With a prefix self-draft the draft steps run against the TARGET
        # pool (its first n_draft layers — ctor comment): the pool is
        # donated to the draft program and the returned one (speculative
        # columns written at the prefix layers) feeds verify, which
        # rewrites those columns with the identical values.
        shared = self.draft_shares_cache
        draft_cache_in = self.cache if shared else self.draft_cache
        draft_cache_out, drafts, draft_probs = _spec_draft_chunk(
            self.draft_config,
            self.draft_params,
            token_j,
            draft_cache_in,
            table,
            lengths_j,
            active_j,
            k,
            self.temperature,
            self.top_k,
            self.top_p,
            self.attn_impl,
            key_d,
            self.mesh,
            split_k,
        )
        t_draft = 0.0 if obs is None else self._clock()
        if shared:
            self.cache = draft_cache_out
        else:
            self.draft_cache = draft_cache_out
        self.cache, n_accept, out = _spec_verify_chunk(
            self.config,
            self.params,
            token_j,
            drafts,
            draft_probs,
            self.cache,
            table,
            lengths_j,
            active_j,
            self.temperature,
            self.top_k,
            self.top_p,
            self.attn_impl,
            key_v,
            self.mesh,
            split_k,
        )
        t1 = 0.0 if obs is None else self._clock()
        n_accept = np.asarray(n_accept)
        out = np.asarray(out)  # forces both dispatches
        t_done = self._clock()
        self._spec_rounds += 1
        for i in active_idx:
            slot = self.slots[i]
            if slot is None:
                continue
            j = int(n_accept[i])
            slot.length += 1 + j  # pending + accepted drafts are now cached
            self._spec_verifies += 1
            self._spec_drafted += k
            self._spec_accepted += j
            rate = j / k
            slot.accept_ema = 0.5 * slot.accept_ema + 0.5 * rate
            if self.spec_adapt:
                if slot.accept_ema > 0.75 and slot.spec_k * 2 <= self.spec_k_max:
                    slot.spec_k *= 2
                elif slot.accept_ema < 0.4 and slot.spec_k // 2 >= self.spec_k_min:
                    slot.spec_k //= 2
            finished = False
            for t in range(j + 1):
                if self._append_token(i, slot, int(out[i, t]), t_done):
                    finished = True  # EOS/budget; rest of the round discarded
                    break
            if finished:
                continue
            # page-aligned rollback: drop tail pages past the committed
            # length; the partial last page keeps its stale columns (masked).
            # In int8 mode the freed pages' scale entries are orphaned with
            # them — scales are indexed by physical page, so the same free
            # covers both, and both are rewritten before their page is next
            # read (write-before-read, GPT.verify_step_paged docstring).
            # Shared prefix pages sit below length (length >= matched + 1
            # from admission on), so keep > n_shared already; the max() is
            # a defensive floor — rollback must never hand a trie-owned
            # page to the allocator.
            keep = max(
                -(-slot.length // self.page_size), slot.n_shared
            )
            if len(slot.pages) > keep:
                tail = slot.pages[keep:]
                del slot.pages[keep:]
                self.allocator.free(tail)
        if obs is not None:
            obs.record_round(
                "spec", self._obs_tid, t0, t1, t_done, self._clock()
            )
            self._trace.complete(
                "spec.draft_enqueue", "spec", self._obs_tid, t0, t_draft - t0
            )
            self._trace.complete(
                "spec.verify_enqueue", "spec", self._obs_tid, t_draft,
                t1 - t_draft,
            )

    def spec_stats(self) -> tp.Dict[str, float]:
        """Aggregate speculative counters since construction: acceptance
        rate (accepted drafts / drafted) and tokens emitted per verify
        forward per slot (1.0 would mean speculation never pays — every
        verify also yields its correction/bonus token)."""
        drafted = max(self._spec_drafted, 1)
        verifies = max(self._spec_verifies, 1)
        return {
            "rounds": self._spec_rounds,
            "accept_rate": self._spec_accepted / drafted,
            "tokens_per_verify": (self._spec_accepted + self._spec_verifies)
            / verifies,
        }

    def prefix_stats(self) -> tp.Dict[str, tp.Any]:
        """Prefix-cache counters since construction (reported by
        tools/bench_serve.py's serve_prefix profile and tools/loadgen.py).
        `hit_rate` is matched / MATCHABLE prompt tokens, where matchable is
        the structural ceiling per admission — ((len(prompt) - 1) //
        page_size) * page_size, the most any match could hand out under the
        reserve-the-last-token rule — so a perfect template workload can
        actually reach 1.0. `prefilled_tokens` counts what went through
        prefill chunks; with sharing it is the complement of the hits (the
        r10 regression pin, tests/test_prefix_cache.py)."""
        pc = self.prefix_cache
        matchable = self._prefix_matchable_tokens
        return {
            "enabled": pc is not None,
            "matched_tokens": self._prefix_matched_tokens,
            "matchable_tokens": matchable,
            "hit_rate": (
                self._prefix_matched_tokens / matchable if matchable else 0.0
            ),
            "cow_pages": self.cow_pages,
            "prefilled_tokens": self.prefilled_tokens,
            "trie_pages": 0 if pc is None else pc.page_count(),
            "trie_referenced": 0 if pc is None else pc.referenced_page_count(),
            "reclaimed_pages": self.prefix_evictions,
        }

    def _finish(self, fr: FinishedRequest) -> None:
        """Record a terminal transition (ok/EOS/timeout/cancelled) and fire
        the streaming hook — the ONE funnel every path to `finished` goes
        through, so the async server never misses an ending."""
        self.finished[fr.uid] = fr
        self._trace.instant(
            "finish", "lifecycle", self._obs_tid,
            args={"uid": fr.uid, "status": fr.status},
        )
        if self.on_finish is not None:
            self.on_finish(fr)

    def _append_token(self, slot_i: int, slot: _Slot, tok: int, t: float) -> bool:
        """Record one generated token; returns True if the request finished
        (and the slot was freed)."""
        self._reclaim_window(slot)  # no-op unless config.sliding_window
        slot.generated.append(tok)
        slot.token_times.append(t)
        req = slot.request
        if self.on_token is not None:
            self.on_token(req.uid, tok, t)
        hit_eos = req.eos_id is not None and tok == req.eos_id
        if hit_eos or len(slot.generated) >= req.max_new_tokens:
            self._finish(
                FinishedRequest(
                    uid=req.uid,
                    tokens=np.concatenate(
                        [req.prompt, np.asarray(slot.generated, np.int32)]
                    ),
                    token_times=slot.token_times,
                )
            )
            self._release_slot(slot)
            self.slots[slot_i] = None
            return True
        return False
