"""Asyncio streaming front door over the continuous-batching engine.

`ServeEngine` is deliberately synchronous host code: one thread owns the
scheduler state and drives one device program at a time (sampling/serve.py).
Production traffic is the opposite shape — many concurrent clients, each
wanting tokens AS THEY LAND, some disconnecting mid-stream, all under a
process that must drain cleanly on SIGTERM. This module bridges the two
with one rule: **every touch of the engine happens on the driver loop.**
Client coroutines never call the engine directly; they enqueue commands
(submit / cancel) that the driver applies between rounds, and they consume
per-request asyncio queues that the engine's `on_token`/`on_finish` hooks
feed. The engine stays single-threaded, the event loop stays unblocked
(`engine.step` runs in a worker thread via `asyncio.to_thread`), and no
lock ever guards scheduler state.

    engine = ServeEngine(config, params, max_slots=8)
    server = AsyncServeServer(engine)
    driver = asyncio.create_task(server.run())
    uid = await server.submit(prompt, max_new_tokens=128, ttl_s=30.0)
    async for tok in server.stream(uid):   # tokens stream as rounds land
        ...
    await server.drain()                   # or SIGTERM: same path
    await driver

Robustness behaviors (the front-door half of the serving SLO story —
docs/ROBUSTNESS.md "Serving faults & SLOs"):

  * **Cancellation** — a client that stops consuming its stream (generator
    closed, task cancelled) enqueues `engine.cancel(uid)`: pages return to
    the pool at the next round boundary and co-resident requests are
    untouched (tests/test_server.py, tests/test_serving.py).
  * **Deadline propagation** — `submit(ttl_s=...)` rides the engine's TTL
    machinery unchanged; a timed-out request ends its stream with the
    `timeout` status visible in `result(uid)`.
  * **Backpressure retry** — a retryable BackpressureError is retried a
    bounded number of times on the shared exponential-backoff schedule
    (robustness/backoff.py — the same discipline as the PR 3 checkpoint
    write retry), using the exception's structured fields instead of
    string-parsing; non-retryable sheds (SLOScheduler deadline
    infeasibility) surface immediately.
  * **Slow clients** — each stream has a bounded server-side token buffer
    (`max_buffered_tokens`); a client that stops draining is shed with
    status "slow_client" instead of wedging pool pages behind a dead
    socket. The `slow_client` fault (robustness/faults.py, step key =
    request uid) forces exactly this condition deterministically.
  * **Graceful drain** — `drain()` (or SIGTERM/SIGINT through the PR 3
    one-shot preemption flag, robustness/preempt.py: the driver polls
    `preempt.requested()` each round) stops admission — further submits
    raise `ServerDraining` — finishes every in-flight request, then lets
    `run()` return.

Round-overlap dispatch (docs/SERVING.md "Round-overlap dispatch") changes
nothing structurally here, and that is the point: tokens only ever reach
the `on_token` hooks from SETTLED rounds — the engine's step() commits a
round's tokens after its force lands, and under overlap="double" that is
one step later than the dispatch. A client therefore never streams a
token the engine could still discard (an in-flight round killed by
`kill_overlapped_round` drops un-settled tokens and recompute-preempts;
anything already streamed was settled and stays bit-final). The driver
loop's `engine.idle` check also covers the in-flight handle, so drain
waits for the last overlapped round to settle before `run()` returns.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import typing as tp

from midgpt_tpu.obs import DISABLED_SNAPSHOT
from midgpt_tpu.obs.trace import NULL_TRACER
from midgpt_tpu.robustness import faults, preempt
from midgpt_tpu.robustness.backoff import backoff_delays
from midgpt_tpu.sampling.serve import (
    BackpressureError,
    FinishedRequest,
    ServeEngine,
)

_END = object()  # stream terminator sentinel


class ServerDraining(RuntimeError):
    """submit() after drain began — the process is shutting down; clients
    should fail over to another replica, not queue behind a drain."""


@dataclasses.dataclass
class _Stream:
    """Per-request delivery state. `queue` is consumed by the client
    coroutine; `buffered` counts tokens handed to the stream but not yet
    consumed (the slow-client bound); `stalled` marks a client the
    slow_client fault wedged — its tokens accrue in the buffer but never
    reach the queue, exactly like a dead socket."""

    queue: asyncio.Queue
    buffered: int = 0
    stalled: bool = False
    finished: tp.Optional[FinishedRequest] = None
    first_token_seen: bool = False  # TTFT instant fired (obs lifecycle)


class AsyncServeServer:
    """Streaming asyncio front end over one `ServeEngine` (module
    docstring). Construct, schedule `run()` as a task, then `submit` /
    `stream` / `result` from any number of client coroutines."""

    def __init__(
        self,
        engine: ServeEngine,
        *,
        submit_retries: int = 4,
        retry_backoff_s: float = 0.05,
        max_buffered_tokens: int = 512,
        idle_poll_s: float = 0.005,
        honor_preempt_flag: bool = True,
    ):
        # max_buffered_tokens sizes the per-client shed bound; tokens land
        # in per-ROUND bursts (up to decode_chunk, or spec_k+1 per slot),
        # so keep it a healthy multiple of the engine's chunk size or brief
        # consumer lag reads as a dead client.
        if engine.on_token is not None or engine.on_finish is not None:
            raise ValueError("engine already has streaming hooks installed")
        self.engine = engine
        self.submit_retries = submit_retries
        self.retry_backoff_s = retry_backoff_s
        self.max_buffered_tokens = max_buffered_tokens
        self.idle_poll_s = idle_poll_s
        self.honor_preempt_flag = honor_preempt_flag
        engine.on_token = self._on_token
        engine.on_finish = self._on_finish
        # Request-lifecycle tracing rides the ENGINE's observability (the
        # server claims on_token/on_finish exclusively — obs must not —
        # so the lifecycle events are emitted from these hook bodies).
        # NULL_TRACER when the engine runs obs-off: every site is free.
        self._trace = (
            engine.obs.tracer if engine.obs is not None else NULL_TRACER
        )
        self._streams: tp.Dict[int, _Stream] = {}
        # Commands are (fn, future-or-None); appended from the event loop
        # (submit/cancel) or the driver's worker thread (slow-client sheds
        # noticed mid-step) — deque append/popleft are atomic under the GIL
        # and the driver only APPLIES commands on the loop thread while no
        # step is in flight, so engine state stays single-threaded.
        self._cmds: tp.Deque[
            tp.Tuple[tp.Callable[[], tp.Any], tp.Optional[asyncio.Future]]
        ] = collections.deque()
        self._wake = asyncio.Event()
        self._draining = False
        self._running = False
        self._stopped = False  # run() returned; no command will ever apply
        self._loop: tp.Optional[asyncio.AbstractEventLoop] = None

    # -- driver --------------------------------------------------------

    async def run(self) -> None:
        """The driver loop: apply queued commands, step the engine in a
        worker thread while there is work, exit once draining AND idle.
        Exactly one run() may be active; it owns all engine access."""
        if self._running or self._stopped:
            raise RuntimeError("run() is already active or finished")
        self._running = True
        self._loop = asyncio.get_running_loop()
        try:
            while True:
                if (
                    self.honor_preempt_flag
                    and preempt.requested()
                    and not self._draining
                ):
                    # SIGTERM/SIGINT landed (one-shot flag handler,
                    # robustness/preempt.py): stop admission, finish
                    # in-flight work, exit — the serving twin of the train
                    # loop's emergency-save-and-exit.
                    self._trace.instant("drain.sigterm", "lifecycle", "server")
                    self._draining = True
                self._apply_commands()
                if not self.engine.idle:
                    await asyncio.to_thread(self.engine.step)
                elif self._draining and not self._cmds:
                    return
                else:
                    # Idle: park until a submit wakes us (or poll the
                    # preempt flag / drain request at a bounded interval).
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(
                            self._wake.wait(), timeout=self.idle_poll_s
                        )
                    except asyncio.TimeoutError:
                        pass
        finally:
            self._running = False
            self._stopped = True
            # Fail any command that raced the shutdown instead of hanging
            # its awaiter forever.
            while self._cmds:
                _, fut = self._cmds.popleft()
                if fut is not None and not fut.done():
                    fut.set_exception(
                        ServerDraining("server stopped before command ran")
                    )

    def _apply_commands(self) -> None:
        while self._cmds:
            fn, fut = self._cmds.popleft()
            try:
                result = fn()
            except Exception as e:
                if fut is None:
                    raise
                if not fut.done():
                    fut.set_exception(e)
            else:
                if fut is not None and not fut.done():
                    fut.set_result(result)

    async def _call(self, fn: tp.Callable[[], tp.Any]) -> tp.Any:
        """Run `fn` on the driver loop between engine rounds. Commands may
        be enqueued before run() is first scheduled (they apply on its
        first iteration); after run() returned they fail fast."""
        if self._stopped:
            raise ServerDraining("server driver has stopped")
        fut = asyncio.get_running_loop().create_future()
        self._cmds.append((fn, fut))
        self._wake.set()
        return await fut

    # -- client surface ------------------------------------------------

    async def submit(
        self,
        prompt: tp.Sequence[int],
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
    ) -> int:
        """Queue a request; returns its uid once admitted. A retryable
        BackpressureError is absorbed up to `submit_retries` attempts on
        the shared exponential-backoff schedule; a non-retryable shed (or
        budget exhaustion) re-raises to the caller."""

        def do_submit() -> int:
            if self._draining:
                raise ServerDraining("server is draining; submit refused")
            uid = self.engine.submit(
                prompt, max_new_tokens, eos_id=eos_id, ttl_s=ttl_s
            )
            self._streams[uid] = _Stream(queue=asyncio.Queue())
            # Async span: one Perfetto track per request from accepted
            # submit to terminal status (_on_finish closes it). Shed
            # attempts never reach here — the engine emits their instant.
            self._trace.async_begin(
                "request", str(uid), "lifecycle", "server",
                args={
                    "uid": uid,
                    "prompt_len": len(prompt),
                    "max_new_tokens": max_new_tokens,
                },
            )
            return uid

        delays = backoff_delays(self.submit_retries, self.retry_backoff_s)
        while True:
            try:
                return await self._call(do_submit)
            except BackpressureError as e:
                delay = next(delays, None)
                if delay is None or not e.retryable:
                    raise
                await asyncio.sleep(delay)

    async def stream(self, uid: int) -> tp.AsyncIterator[int]:
        """Yield `uid`'s generated tokens as the engine lands them; returns
        on any terminal status (ok/EOS/timeout/cancelled). Abandoning the
        iterator (client disconnect, task cancellation) cancels the request
        at the next round boundary and frees its pages
        (tests/test_server.py)."""
        st = self._streams[uid]
        try:
            while True:
                item = await st.queue.get()
                if item is _END:
                    return
                st.buffered -= 1
                yield item
        finally:
            if st.finished is None:
                # Enqueue-only (no await allowed in a generator finally
                # during GeneratorExit): the driver applies it next round.
                self._cmds.append(
                    (lambda: self.engine.cancel(uid, status="cancelled"), None)
                )
                self._wake.set()

    def result(self, uid: int) -> tp.Optional[FinishedRequest]:
        """The terminal record (tokens + status), once the stream ended."""
        st = self._streams.get(uid)
        return None if st is None else st.finished

    def stats(self) -> tp.Dict[str, tp.Any]:
        """Engine observability snapshot for metrics scrapes (used by
        tools/loadgen.py). Counters are plain ints mutated only inside
        `engine.step` on the driver's worker thread, so a read from the
        event loop is at worst one round stale, never torn."""
        eng = self.engine
        return {
            "rounds": eng.rounds,
            "shed": eng.shed,
            "timeouts": eng.timeouts,
            "cancelled": eng.cancelled,
            "preemptions": eng.preemptions,
            "decode_kills": eng.decode_kills,
            "prefilled_tokens": eng.prefilled_tokens,
            "free_pages": eng.allocator.free_count,
            "prefix": eng.prefix_stats(),
            "mesh": eng.mesh_shape(),
            "weights_version": eng.weights_version,
            "hot_swaps": eng.hot_swaps,
            "resizes": eng.resizes,
            "swap_pending": eng._staged_swap is not None,
            # same unified schema as ServeEngine.stats()["obs"]
            # (docs/OBSERVABILITY.md): round decomposition + metrics
            "obs": (
                DISABLED_SNAPSHOT if eng.obs is None else eng.obs.snapshot()
            ),
        }

    async def hot_swap(
        self,
        params,
        *,
        draft_params=None,
        version: str = "inline",
        config=None,
    ) -> tp.Dict[str, tp.Any]:
        """Stage a blue/green weight swap on the driver loop (the same
        command funnel as submit/cancel, so the stage lands between engine
        rounds, never mid-round). Returns the stage summary; the flip
        itself happens at the first slot-free round boundary and shows up
        on stats() as the new `weights_version`. Structured HotSwapError
        on shape/config mismatch (sampling/ops.py)."""

        def do_swap() -> tp.Dict[str, tp.Any]:
            return self.engine.hot_swap(
                params, draft_params=draft_params, version=version,
                config=config,
            )

        return await self._call(do_swap)

    async def resize(
        self,
        num_pages: tp.Optional[int] = None,
        *,
        max_slots: tp.Optional[int] = None,
    ) -> tp.Dict[str, tp.Any]:
        """Live pool resize on the driver loop; retryable PoolResizeError
        when shrinking below the resident working set (sampling/ops.py)."""

        def do_resize() -> tp.Dict[str, tp.Any]:
            return self.engine.resize(num_pages, max_slots=max_slots)

        return await self._call(do_resize)

    async def drain(self) -> None:
        """Stop admission and wait for every in-flight request to finish.
        `run()` returns once the engine is idle. Idempotent."""
        self._draining = True
        self._wake.set()
        while not self._stopped and not (self.engine.idle and not self._cmds):
            await asyncio.sleep(self.idle_poll_s)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- engine hooks (called inside engine.step, driver worker thread) --

    def _on_token(self, uid: int, tok: int, t: float) -> None:
        st = self._streams.get(uid)
        if st is None:
            return
        # The slow_client fault (step key = uid) wedges this stream: from
        # now on its tokens pile into the buffer like writes into a dead
        # socket, and the bound below sheds it.
        if faults.should_fire("slow_client", step=uid):
            st.stalled = True
        if not st.first_token_seen:
            st.first_token_seen = True
            self._trace.instant(
                "first_token", "lifecycle", "server", args={"uid": uid}
            )
        st.buffered += 1
        if not st.stalled:
            self._loop.call_soon_threadsafe(st.queue.put_nowait, tok)
        if st.buffered > self.max_buffered_tokens and st.finished is None:
            # Bounded-buffer shed: the client is not draining; cancel at
            # the next round boundary instead of holding pool pages behind
            # a dead consumer.
            self._trace.instant(
                "slow_client_shed", "lifecycle", "server", args={"uid": uid}
            )
            self._cmds.append(
                (lambda: self.engine.cancel(uid, status="slow_client"), None)
            )

    def _on_finish(self, fr: FinishedRequest) -> None:
        st = self._streams.get(fr.uid)
        if st is None:
            return
        st.finished = fr
        self._trace.async_end(
            "request", str(fr.uid), "lifecycle", "server",
            args={"status": fr.status},
        )
        self._loop.call_soon_threadsafe(st.queue.put_nowait, _END)
