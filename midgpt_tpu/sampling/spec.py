"""Speculative decoding primitives: self-draft construction and the exact
rejection sampler.

Autoregressive decode pays one full sweep of the target model's weights per
generated token. Speculative decoding (Leviathan et al. 2023, "Fast
Inference from Transformers via Speculative Decoding") amortizes that
sweep: a cheap DRAFT model proposes k tokens autoregressively, the target
scores all k+1 positions in one batched forward (`GPT.verify_step_paged`),
and a rejection sampler accepts the longest valid prefix plus one corrected
token. The output distribution equals the target's EXACTLY — the draft only
changes the acceptance rate (throughput), never the samples:

  * token d_i (drawn from warped draft distribution q_i) is accepted with
    probability min(1, p_i[d_i] / q_i[d_i]) where p_i is the warped target
    distribution at that position;
  * the first rejection is replaced by a draw from norm(max(p_i - q_i, 0))
    — the residual that makes accept + reject marginalize to p_i;
  * a fully accepted chain appends a FREE bonus token drawn from p_{k+1}
    (the target scored k+1 positions, so the last draw costs nothing).

Greedy (temperature=0) degenerates to argmax equality per position, which
makes speculative greedy decode token-identical to plain greedy decode
(pinned by tests/test_spec.py).

The engine wiring (draft rounds interleaved with verify rounds, per-slot
adaptive k, page-aligned cache rollback) lives in sampling/serve.py;
docs/SERVING.md documents the invariants. With the int8 quantized cache
(PagedKVCache int8 storage) the rollback story is unchanged: freeing a
tail page orphans its f32 scale entries together with its int8 columns
(both are indexed by physical page), and greedy speculative serving stays
token-identical to plain paged decode on the SAME quantized pool — the
draft's prefix-layer writes and the verify rewrite quantize identical
values (pinned by tests/test_quant_cache.py).

The cross-request prefix cache (sampling/prefix_cache.py) composes with
both draft modes. Self-draft rides shared pages for free: the draft IS the
target's first layers on the target's pool, so a trie-matched prefix skips
draft prefill too. A separate-weights draft keeps its own pool mirrored
page-for-page, so sharing carries over structurally; the one wrinkle is
that a trie page covering GENERATED tokens holds draft-pool K/V from
whichever proposal stream produced it, which may differ from what a fresh
draft prefill would write. That staleness can only lower the draft's
acceptance rate for the reader — verification re-scores every proposal
with the target, so output exactness is untouched (the serving greedy
parity pins hold with the cache on in every spec mode,
tests/test_prefix_cache.py).
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import GPTConfig, GPTParams
from midgpt_tpu.sampling.engine import warp_logits

Array = jax.Array


def self_draft(
    config: GPTConfig, params: GPTParams, n_draft_layers: int
) -> tp.Tuple[GPTConfig, GPTParams]:
    """Build a draft model from the first `n_draft_layers` blocks of the
    target, sharing its embedding and lm_head.

    No training, no extra checkpoint: early blocks of a converged decoder
    already carry most of the next-token signal, and the shared wte/lm_head
    keep the draft's output space aligned with the target's. `wte` and
    `lm_head` are the SAME arrays (zero copy); the block slice materializes
    n_draft_layers/n_layer of the stacked block weights. Residual-stream
    compatibility is structural: blocks are pre-norm residual updates, so
    truncating the stack still feeds the final norm a valid stream."""
    if not 0 < n_draft_layers < config.n_layer:
        raise ValueError(
            f"n_draft_layers={n_draft_layers} must be in [1, "
            f"n_layer={config.n_layer})"
        )
    draft_config = dataclasses.replace(config, n_layer=n_draft_layers)
    blocks = jax.tree.map(lambda a: a[:n_draft_layers], params.blocks)
    return draft_config, GPTParams(
        wte=params.wte, blocks=blocks, lm_head=params.lm_head
    )


def speculative_accept(
    target_logits: Array,  # (B, k+1, V) — verify forward, rows 0..k
    draft_probs: Array,  # (B, k, V) f32 — warped draft dist of each proposal
    drafts: Array,  # (B, k) int32 — the proposed tokens
    key: tp.Optional[Array],
    temperature: float,
    top_k: tp.Optional[int] = None,
    top_p: tp.Optional[float] = None,
) -> tp.Tuple[Array, Array]:
    """The rejection sampler (module docstring): returns (n_accept (B,)
    int32, out (B, k+1) int32). out[:, :n_accept] are the accepted drafts
    verbatim; out[:, n_accept] is the correction (on rejection) or the
    bonus token (all k accepted) — the caller emits out[:, :n_accept + 1].

    Row i of target_logits scores the position AFTER input token i (the
    verify input is [t_last, d_1, .., d_k]), so draft d_{i+1} is judged by
    row i and row k supplies the bonus distribution. Exactness — each
    emitted token distributed as a sequential draw from the warped target —
    is pinned statistically by tests/test_spec.py against a deliberately
    wrong draft."""
    B, K1, _ = target_logits.shape
    K = K1 - 1
    assert K >= 1, "speculation needs at least one drafted token"
    tl = target_logits.astype(jnp.float32)
    if temperature == 0.0:
        tgt = jnp.argmax(tl, axis=-1)  # (B, k+1) per-position greedy tokens
        acc = drafts == tgt[:, :K]
        n_accept = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
        corr = jnp.take_along_axis(tgt, n_accept[:, None], axis=1)[:, 0]
    else:
        p = jax.nn.softmax(warp_logits(tl, temperature, top_k, top_p), axis=-1)
        k_u, k_r = jax.random.split(key)
        p_d = jnp.take_along_axis(p[:, :K], drafts[..., None], axis=-1)[..., 0]
        q_d = jnp.take_along_axis(draft_probs, drafts[..., None], axis=-1)[..., 0]
        # accept iff u < p/q, written u*q < p so q=0 (a token the draft
        # filter zeroed but the caller force-fed) accepts whenever p > 0
        u = jax.random.uniform(k_u, (B, K))
        acc = u * q_d < p_d
        n_accept = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
        r = n_accept[:, None, None]
        p_r = jnp.take_along_axis(p, r, axis=1)[:, 0]  # (B, V)
        q_r = jnp.take_along_axis(
            draft_probs, jnp.minimum(r, K - 1), axis=1
        )[:, 0]
        resid = jnp.where(
            (n_accept == K)[:, None], p_r, jnp.maximum(p_r - q_r, 0.0)
        )
        # numerically-empty residual (p <= q everywhere yet u rejected — only
        # reachable through rounding) falls back to the target row itself
        resid = jnp.where(
            jnp.sum(resid, axis=-1, keepdims=True) > 0.0, resid, p_r
        )
        corr = jax.random.categorical(k_r, jnp.log(resid), axis=-1)
    out = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    out = out.at[jnp.arange(B), n_accept].set(corr.astype(jnp.int32))
    return n_accept.astype(jnp.int32), out
