"""Autoregressive sampling with a static KV cache.

The reference's generate loop re-runs a full right-padded forward over the
whole block for EVERY new token (reference sample.py:68-95) — O(T) full
forwards. Here: one jitted prefill over the prompt, then one jitted
single-token decode step per new token against the (n_layer, B, H, S, C)
cache, with the cache buffers donated so XLA updates them in place. Both
functions have static shapes, so the loop compiles exactly twice.

If generation would run past `block_size`, decoding falls back to the
reference's windowed full-forward scheme for the overflow tokens (the cache
is sized to the trained context; RoPE positions past it are extrapolation).
"""

from __future__ import annotations

import functools
import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.models.gpt import GPT, GPTConfig, GPTParams, KVCache

Array = jax.Array


def warp_logits(
    logits: Array,  # (..., V) float32
    temperature: float,
    top_k: tp.Optional[int] = None,
    top_p: tp.Optional[float] = None,
) -> Array:
    """Temperature scaling + top-k / nucleus filtering on f32 logits.

    The warped logits DEFINE the sampling distribution: `sample_logits`
    draws categorically from them, and the speculative-decoding rejection
    sampler (sampling/spec.py) needs the same warped distribution for both
    the draft and the target, so the filter lives here as a pure function.
    Requires temperature > 0 (greedy has no distribution to warp); works on
    any leading batch shape."""
    logits = logits / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        # lax.top_k is O(V) selection of k values — not a full-vocab sort
        # per token (the nucleus path below can't avoid its sort).
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        # nucleus: keep the smallest prefix of descending-prob tokens whose
        # cumulative mass reaches top_p (the first token is always kept —
        # its exclusive prefix mass is 0)
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive_cum < top_p
        threshold = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def sample_logits(
    logits: Array,  # (B, V) float
    key: Array,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    top_p: tp.Optional[float] = None,
) -> Array:
    """Temperature + optional top-k / nucleus (top-p) sampling; 0 = greedy."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        key, warp_logits(logits, temperature, top_k, top_p), axis=-1
    )


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6))
def _prefill_and_first(config, params, tokens, key, temperature, top_k, top_p):
    logits, cache = GPT.prefill(config, params, tokens, KVCache.init(
        config, tokens.shape[0], dtype=tokens_dtype(params)))
    first = sample_logits(logits[:, -1], key, temperature, top_k, top_p)
    return first, cache


def tokens_dtype(params: GPTParams):
    return params.wte.dtype


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6), donate_argnums=(3,))
def _decode_and_sample(config, params, token, cache, temperature, top_k, top_p, key):
    logits, cache = GPT.decode_step(config, params, token, cache)
    nxt = sample_logits(logits, key, temperature, top_k, top_p)
    return nxt, cache


# Tokens decoded per device dispatch. Each host->device round trip costs
# ~5-8 ms under remote-TPU setups (far more than a 124M decode step), so the
# per-token python loop is latency-bound; a lax.scan of decode steps inside
# one jit amortizes the dispatch over the whole chunk.
DECODE_CHUNK = 64
assert DECODE_CHUNK & (DECODE_CHUNK - 1) == 0, "tail decomposition assumes a power of two"


@functools.partial(jax.jit, static_argnums=(0,))
def _window_forward(config, params, window):
    """Full forward on a static (B, S) window -> last-position logits.

    Module-level jit (NOT a fresh jax.jit per generate call): the overflow
    window is always exactly block_size wide — the fast path only exits the
    cache once T_ctx + produced > S, so seq is at least S+1 long by the
    first overflow token — giving ONE compile per (B, S) across all calls."""
    return GPT.apply(config, params, window, inference=True)[:, -1]


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6, 7), donate_argnums=(3,))
def _decode_chunk(config, params, token, cache, temperature, top_k, top_p, n_steps, key):
    """n_steps sequential decode+sample steps as ONE device program.

    Returns (last_token, cache, tokens (n_steps, B))."""

    def body(carry, _):
        token, cache, key = carry
        key, k = jax.random.split(key)
        logits, cache = GPT.decode_step(config, params, token, cache)
        nxt = sample_logits(logits, k, temperature, top_k, top_p)
        return (nxt, cache, key), nxt

    (token, cache, _), toks = jax.lax.scan(
        body, (token, cache, key), None, length=n_steps
    )
    return token, cache, toks


def restore_for_sampling(
    ckpt_dir: str,
    config,  # ExperimentConfig (duck-typed to avoid an import cycle)
    mesh=None,
) -> tp.Tuple[GPTParams, int]:
    """Restore the 'params' item sharded over an inference mesh.

    The naive restore targets ONE device — a 7B checkpoint can never load
    that way. Here the abstract skeleton carries NamedShardings from the
    same FSDP spec rule training uses, so Orbax reads each host's shards
    straight into sharded device arrays (training/checkpoint.py restore
    honors the target shardings), and the decode jits inherit the layout
    via GSPMD. With one device (or mesh=None on a 1-chip host) this reduces
    to the plain single-device restore. Returns (params, step)."""
    from midgpt_tpu.parallel.fsdp import fsdp_param_specs, named_shardings
    from midgpt_tpu.training.checkpoint import CheckpointManager

    if mesh is None:
        from midgpt_tpu.config import MeshConfig
        from midgpt_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(MeshConfig(data=1, fsdp=jax.device_count(), sp=1))
    model_cfg = config.model_config
    abstract = jax.eval_shape(
        lambda k: GPT.init(model_cfg, k), jax.random.PRNGKey(0)
    )
    specs = fsdp_param_specs(
        abstract,
        mesh,
        shard_model=mesh.shape["fsdp"] > 1,
        min_size=config.fsdp_min_size,
    )
    shardings = named_shardings(specs, mesh)
    abstract = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(
            s.shape, jnp.dtype(config.param_dtype), sharding=sh
        ),
        abstract,
        shardings,
    )
    mngr = CheckpointManager(ckpt_dir)
    # Verified steps only (training/checkpoint.py manifests): never sample
    # from a save truncated by a mid-save kill. Pre-manifest checkpoint
    # dirs fall back to the plain latest step.
    step = mngr.latest_verified_step()
    if step is None:
        raise FileNotFoundError(f"no verified checkpoint found under {ckpt_dir}")
    params = mngr.restore(step, {"params": abstract})["params"]
    return params, step


def generate(
    config: GPTConfig,
    params: GPTParams,
    prompt: Array,  # (B, T0) int32
    max_new_tokens: int,
    *,
    temperature: float = 1.0,
    top_k: tp.Optional[int] = None,
    top_p: tp.Optional[float] = None,
    key: tp.Optional[Array] = None,
) -> Array:
    """Returns (B, T0 + max_new_tokens) including the prompt."""
    key = key if key is not None else jax.random.PRNGKey(0)
    B, T0 = prompt.shape
    S = config.block_size
    prompt = jnp.asarray(prompt, jnp.int32)
    if T0 > S:
        prompt_ctx = prompt[:, -S:]
    else:
        prompt_ctx = prompt

    out = [prompt]
    key, k0 = jax.random.split(key)
    nxt, cache = _prefill_and_first(
        config, params, prompt_ctx, k0, temperature, top_k, top_p  # graftcheck: disable=GC011 — one-shot CLI sampler: config and sampling knobs come from argparse and are process-constant; one compile per process is the contract (ServeEngine pins them init-frozen instead)
    )
    out.append(nxt[:, None])
    produced = 1

    # Fast path: incremental decode while the write position fits the cache.
    # Decode call #i writes K/V at position T_ctx + i; a chunk of n steps
    # starting at call index (produced - 1) last writes T_ctx + produced +
    # n - 2, which must stay <= S - 1. Chunks run as one device program
    # (DECODE_CHUNK tokens per dispatch); a partial tail is decomposed into
    # power-of-two chunks, so the scan only ever compiles at lengths
    # {DECODE_CHUNK, DECODE_CHUNK/2, ..., 1} — a bounded, request-pattern-
    # independent compile set (at most log2(DECODE_CHUNK) extra dispatches
    # per generation).
    T_ctx = int(min(T0, S))
    while produced < max_new_tokens and T_ctx + produced <= S:
        budget = min(
            DECODE_CHUNK,
            max_new_tokens - produced,
            S - T_ctx - produced + 1,
        )
        n = 1 << (budget.bit_length() - 1)  # largest power of two <= budget
        key, k = jax.random.split(key)
        nxt, cache, toks = _decode_chunk(
            config, params, nxt, cache, temperature, top_k, top_p, n, k  # graftcheck: disable=GC011 — one-shot CLI sampler: knobs are process-constant argparse values (n itself is pow2-clamped)
        )
        out.append(toks.T)  # (B, n)
        produced += n

    # Overflow: windowed full-forward per token (reference scheme). The
    # window is a static (B, S) slice — see _window_forward.
    if produced < max_new_tokens:
        seq = jnp.concatenate(out, axis=1)
        for _ in range(max_new_tokens - produced):
            key, k = jax.random.split(key)
            window = seq[:, -S:]
            nxt = sample_logits(
                _window_forward(config, params, window), k, temperature, top_k, top_p  # graftcheck: disable=GC011 — one-shot CLI sampler: config is process-constant; the overflow window compiles once
            )
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        return seq

    return jnp.concatenate(out, axis=1)
