"""Pluggable serving scheduler policies (admission, ordering, preemption).

PR 1 inlined three policy decisions in the `ServeEngine` round loop: which
queued request claims a freed slot (`_admit`), which running slot is
preempted when the page pool runs dry (`_ensure_pages`/`_evict`), and when
`submit` refuses a request outright (backpressure shedding). This module
extracts them behind a `Scheduler` interface so serving policy is a host-
side plug — the page table, lengths and active mask stay plain jit inputs,
so SWAPPING POLICIES NEVER TOUCHES A COMPILED PROGRAM (pinned by
tests/test_scheduler.py with the tests/test_recompile_pins.py counter
methodology). The extraction is also what mesh-sharded serving and prefix
caching (ROADMAP items 1-2) hook into: both need to reorder admission and
choose eviction victims without re-opening the engine's round loop.

Two policies ship:

  * `FCFSScheduler` — the PR 1 behavior, bit-for-bit: admit the queue head,
    evict the youngest, shed only on the `max_backlog_pages` budget. The
    default; every existing serving/spec/quant parity test runs through it
    unchanged (tests/test_serving.py, tests/test_spec.py,
    tests/test_quant_cache.py).
  * `SLOScheduler` — deadline-aware: admission is earliest-deadline-first,
    preemption picks the victim with the MOST deadline slack (an urgent
    request keeps its pages; a request with an hour to spare re-prefills),
    and admission sheds requests whose deadline is already infeasible
    (closer than `min_headroom_s`) — refusing work it cannot finish in time
    instead of burning pool pages on it (load shedding). Shed decisions are
    reported via `BackpressureError.retryable=False` so the async front
    door (sampling/server.py) fails them fast instead of retrying.

Deadlock-freedom is the ENGINE's invariant, not the policy's: the engine
only ever offers preemption candidates strictly younger (later
`admit_order`) than the slot that needs pages, so the oldest running
request always makes progress no matter what a policy returns. A policy
returning a non-candidate is a contract violation and raises.

**Deferred-effect semantics under round-overlap dispatch** (sampling/
serve.py `_step_overlapped`, docs/SERVING.md "Round-overlap dispatch"):
policy decisions are HOST decisions and only ever take effect at the next
dispatch boundary, never mid-flight. With overlap off that boundary is the
same round; with overlap="double" the engine dispatches round N+1 BEFORE
running round N's host phase, so a request this policy admits (or a victim
it selects) during round N's host phase first appears in (disappears from)
round N+2's dispatched batch — the one-round-late boundary the engine's
`dispatch_log` records and tests/test_overlap.py pins for both shipped
policies. Policies need no awareness of this: the interface below is
unchanged, the engine alone decides when a decision lands on the device,
and an eviction of a slot with an in-flight dispatch simply discards that
slot's un-settled tokens (recompute preemption regenerates them
bit-exactly).

With the cross-request prefix cache on (sampling/prefix_cache.py), the
backpressure accounting policies see is refcount-aware: the engine's
`_backlog_pages` charges a trie-shared page ONCE no matter how many
queued/running requests will map it, and unreferenced trie pages are
charged nothing because the engine reclaims them on demand BEFORE asking a
policy for a preemption victim (`_ensure_pages`). Policies themselves are
unchanged — eviction candidates are still slots, never trie nodes, so a
policy can never evict a shared prefix out from under a co-reader.
"""

from __future__ import annotations

import typing as tp

if tp.TYPE_CHECKING:  # import cycle: serve.py imports this module
    from midgpt_tpu.sampling.serve import Request, _Slot


class Scheduler:
    """Host-side serving policy. Stateless by default; implementations may
    keep statistics but must not touch device state — scheduling decisions
    feed the engine's page table and queue order only, which are plain jit
    inputs (the zero-new-compiled-programs contract,
    tests/test_scheduler.py)."""

    name = "base"

    def select_admit(
        self, queue: tp.Sequence["Request"], now: float
    ) -> tp.Optional[int]:
        """Index into `queue` of the request to admit into a freed slot,
        or None to deliberately leave the slot empty this round."""
        raise NotImplementedError

    def select_victim(
        self,
        requester: "_Slot",
        candidates: tp.Sequence["_Slot"],
        now: float,
    ) -> tp.Optional["_Slot"]:
        """Which of `candidates` to preempt so `requester` can grow.

        `candidates` holds only running slots strictly younger than
        `requester` (the engine's deadlock-freedom invariant — see module
        docstring); it is never empty. Return None to defer `requester`
        instead of preempting anyone."""
        raise NotImplementedError

    def shed_reason(
        self,
        need_pages: int,
        deadline: tp.Optional[float],
        engine,
        now: float,
    ) -> tp.Optional[tp.Tuple[str, bool]]:
        """Admission control, called by `ServeEngine.submit` before a
        request enters the queue. None admits; `(reason, retryable)`
        sheds — the engine raises `BackpressureError(reason,
        retryable=retryable, ...)`."""
        raise NotImplementedError

    # Shared backpressure-budget check: every policy sheds when the
    # worst-case committed page demand would exceed `max_backlog_pages`
    # (the PR 3 bound; None = unbounded, the pre-TTL behavior).
    def _over_budget(self, need_pages: int, engine) -> tp.Optional[tp.Tuple[str, bool]]:
        if engine.max_backlog_pages is None:
            return None
        backlog = engine._backlog_pages()
        if backlog + need_pages > engine.max_backlog_pages:
            return (
                f"admission refused: request needs {need_pages} worst-case "
                f"pages on top of a committed backlog of {backlog} "
                f"(budget {engine.max_backlog_pages}) — the pool is "
                "oversubscribed; shed load or retry after requests finish",
                True,  # retryable: capacity frees as requests finish
            )
        return None


class FCFSScheduler(Scheduler):
    """The PR 1 policy, extracted verbatim: first-come-first-served
    admission (queue head), youngest-first preemption, budget-only
    shedding. Behavior preservation is pinned token-for-token by the
    pre-existing serving parity suite (tests/test_serving.py,
    tests/test_spec.py, tests/test_quant_cache.py) running through this
    default policy."""

    name = "fcfs"

    def select_admit(self, queue, now):
        return 0 if queue else None

    def select_victim(self, requester, candidates, now):
        return max(candidates, key=lambda s: s.admit_order)

    def shed_reason(self, need_pages, deadline, engine, now):
        return self._over_budget(need_pages, engine)


class SLOScheduler(Scheduler):
    """Deadline-urgency scheduling: serve the requests whose SLO is at
    risk, shed the ones that are already lost.

    * **Admission order** — earliest deadline first; deadline-less requests
      rank last; ties fall back to FCFS (queue position).
    * **Preemption** — among the (strictly younger) candidates, evict the
      slot with the MOST deadline slack, ties youngest-first. An urgent
      request near its deadline keeps its pages; the recompute cost of
      preemption lands on whoever can best absorb it.
    * **Load shedding** — beyond the backpressure budget (retryable, like
      FCFS), and additionally any request whose deadline is nearer than
      `min_headroom_s` (non-retryable: waiting only makes it later). A
      request shed at submit costs zero pool pages and zero prefill work —
      the error-budget lever the load harness (tools/loadgen.py) measures
      as `shed_frac`.
    """

    name = "slo"

    def __init__(self, min_headroom_s: float = 0.0):
        self.min_headroom_s = min_headroom_s

    @staticmethod
    def _slack(deadline: tp.Optional[float], now: float) -> float:
        return float("inf") if deadline is None else deadline - now

    def select_admit(self, queue, now):
        if not queue:
            return None
        return min(
            range(len(queue)),
            key=lambda i: (self._slack(queue[i].deadline, now), i),
        )

    def select_victim(self, requester, candidates, now):
        return max(
            candidates,
            key=lambda s: (self._slack(s.request.deadline, now), s.admit_order),
        )

    def shed_reason(self, need_pages, deadline, engine, now):
        over = self._over_budget(need_pages, engine)
        if over is not None:
            return over
        if deadline is not None and deadline - now < self.min_headroom_s:
            return (
                f"admission refused: deadline headroom {deadline - now:.3f}s "
                f"is below the {self.min_headroom_s:.3f}s service floor — "
                "the SLO is already infeasible, shedding instead of burning "
                "pool pages on a request that cannot finish in time",
                False,  # waiting cannot make a past-due deadline feasible
            )
        return None


def set_backlog_budget(engine, pages: tp.Optional[int]) -> tp.Optional[int]:
    """Retune the engine's `max_backlog_pages` shed threshold live (None
    disables the budget). This is the shed-threshold actuator of the
    model-ops policy loop (sampling/ops.py ModelOps): the budget is pure
    host-side admission state, so moving it never touches a compiled
    program — the same guarantee as swapping scheduler policies. Returns
    the previous budget."""
    prev = engine.max_backlog_pages
    engine.max_backlog_pages = pages
    return prev
